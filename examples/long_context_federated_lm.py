"""Long-context federated language modeling on a ('clients','seq') mesh.

Each sampled client trains a TransformerLM on sequences LONGER than one
device comfortably holds: the 'seq' mesh axis shards every client's
activations (ring or Ulysses attention over ICI), while the 'clients' axis
runs the usual FL client parallelism with weighted-psum aggregation. This is
the capability the reference lacks entirely (SURVEY.md §2.7: no sequence
parallelism; its longest sequence is 80 chars).

Run on the 8-device virtual CPU mesh:
    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        PYTHONPATH=. python examples/long_context_federated_lm.py
Flags: --seq_shards 2 --clients_shards 4 --seq_len 256 --seq_impl ring
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser("long_context_federated_lm")
    ap.add_argument("--seq_len", type=int, default=256)
    ap.add_argument("--seq_shards", type=int, default=2)
    ap.add_argument("--clients_shards", type=int, default=4)
    ap.add_argument("--seq_impl", type=str, default="ring",
                    choices=["ring", "ulysses"])
    ap.add_argument("--comm_round", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=128)
    args = ap.parse_args(argv)

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.algorithms.fedavg_seq import FedAvgSeqAPI
    from fedml_tpu.data.synthetic import synthetic_sequences
    from fedml_tpu.models.transformer import TransformerLM

    n_dev = args.clients_shards * args.seq_shards
    devs = jax.devices()
    if len(devs) < n_dev:
        raise SystemExit(f"need {n_dev} devices, have {len(devs)} — set "
                         "XLA_FLAGS=--xla_force_host_platform_device_count=N")
    mesh = Mesh(np.asarray(devs[:n_dev]).reshape(args.clients_shards,
                                                 args.seq_shards),
                ("clients", "seq"))

    n_clients = 2 * args.clients_shards
    data = synthetic_sequences(num_clients=n_clients, seq_len=args.seq_len,
                               vocab_size=args.vocab, samples_per_client=16,
                               test_samples=64, seed=0)
    cfg = FedAvgConfig(comm_round=args.comm_round,
                       client_num_in_total=n_clients,
                       client_num_per_round=args.clients_shards,
                       epochs=1, batch_size=8, lr=0.3,
                       frequency_of_the_test=2, seed=0)
    api = FedAvgSeqAPI(
        data,
        lambda seq_axis: TransformerLM(
            vocab_size=args.vocab, dim=64, depth=2, num_heads=4,
            max_len=args.seq_len, seq_axis=seq_axis, seq_impl=args.seq_impl),
        cfg, mesh=mesh)
    print(f"mesh: {args.clients_shards} client-shards x {args.seq_shards} "
          f"seq-shards; T={args.seq_len} ({args.seq_len // args.seq_shards} "
          f"per device); impl={args.seq_impl}")
    api.train()
    for rec in api.history:
        print(rec)


if __name__ == "__main__":
    main()
