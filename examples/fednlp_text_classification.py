"""FedNLP-style application: federated next-char language modeling.

The reference's applications/FedNLP is a pointer README; this is a worked
equivalent on fedml_tpu: a TransformerLM (with the Pallas flash-attention
kernel) trained with FedAvg over naturally-partitioned character sequences —
the shakespeare task shape (715 speakers, 80-char windows) at toy scale.

Run:  PYTHONPATH=. python examples/fednlp_text_classification.py
"""

from __future__ import annotations


def main():
    from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
    from fedml_tpu.core.tasks import sequence_task
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.transformer import TransformerLM

    data = load_dataset("shakespeare", client_num=32, samples_per_client=40)
    model = TransformerLM(vocab_size=90, dim=64, depth=2, num_heads=4,
                          max_len=128, use_flash=True)
    cfg = FedAvgConfig(
        comm_round=10, client_num_in_total=data.num_clients,
        client_num_per_round=8, epochs=1, batch_size=8, lr=0.05,
        client_optimizer="adam", frequency_of_the_test=5,
    )
    api = FedAvgAPI(data, sequence_task(model), cfg)
    api.train()
    for rec in api.history:
        print(rec)


if __name__ == "__main__":
    main()
