"""Reproduce the reference's headline accuracy benchmarks (BASELINE.md).

Each entry below maps one row of the reference's published
accuracy-vs-rounds table (benchmark/README.md, mirrored in BASELINE.md) to
the equivalent fedml_tpu CLI invocation with the SAME hyperparameters:
model, dataset, client counts, sampling, batch size, optimizer, lr, rounds.

With real dataset files under --data_dir the runs reproduce the published
curves; without files the registry substitutes shape-identical synthetic
data, which exercises the identical compiled program (useful as a dry run /
throughput measurement, meaningless for accuracy).

Usage:
    python examples/reproduce_benchmarks.py --list
    python examples/reproduce_benchmarks.py femnist_cnn [--data_dir ...]
    python examples/reproduce_benchmarks.py all --rounds 10   # quick smoke

Reference rows (BASELINE.md):
  mnist_lr            MNIST + LR,       1000 clients, 10/round, bs=10,  lr=0.03,    >75%  @ 100+ rounds
  synthetic_1_1_lr    Synthetic(1,1)+LR,  30 clients, 10/round, bs=10,  lr=0.01,    >60%  @ 200+ rounds (no download needed)
  femnist_cnn         FEMNIST + CNN,    3400 clients, 10/round, bs=20,  lr=0.1,     84.9% @ 1500+ rounds
  fed_cifar100_rn18   ResNet18-GN,       500 clients, 10/round, bs=20,  lr=0.1,     44.7% @ 4000+ rounds
  shakespeare_rnn     Shakespeare RNN,   715 clients, 10/round, bs=4,   lr=1.0,     56.9% @ 1200+ rounds
  stackoverflow_nwp   SO NWP RNN,     342477 clients, 50/round, bs=16,  lr=10^-0.5, 19.5% @ 1500+ rounds
  cifar10_resnet56    CIFAR-10 + RN56,    10 clients, 10/round, bs=64,  lr=0.001,   93.19/87.12 (IID/LDA-0.5) @ 100 rounds, E=20
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable as `python examples/reproduce_benchmarks.py` from a source
# checkout: sys.path[0] is examples/, the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIGS: dict[str, list[str]] = {
    # benchmark/README.md:12
    "mnist_lr": [
        "--algo", "fedavg", "--dataset", "mnist", "--model", "lr",
        "--client_num_in_total", "1000", "--client_num_per_round", "10",
        "--batch_size", "10", "--lr", "0.03", "--epochs", "1",
        "--comm_round", "100", "--frequency_of_the_test", "10",
    ],
    # benchmark/README.md:54 (the bench.py flagship)
    "femnist_cnn": [
        "--algo", "fedavg", "--dataset", "femnist", "--model", "cnn",
        "--client_num_in_total", "3400", "--client_num_per_round", "10",
        "--batch_size", "20", "--lr", "0.1", "--epochs", "1",
        "--comm_round", "1500", "--frequency_of_the_test", "50",
        "--device_data", "1", "--uint8_pixels", "1",
        # bit-exact fast path: scan only the sampled clients' ladder
        # bucket instead of the 550-sample worst case every round
        "--bucket_batches", "1",
    ],
    # benchmark/README.md:14 (Linear Models table) — needs NO download: the
    # registry regenerates the reference's fixed-seed dataset bit-exactly;
    # scripts/repro_synthetic.py additionally evaluates on the reference's
    # committed test split
    "synthetic_1_1_lr": [
        "--algo", "fedavg", "--dataset", "synthetic_1_1", "--model", "lr",
        "--client_num_in_total", "30", "--client_num_per_round", "10",
        "--batch_size", "10", "--lr", "0.01", "--epochs", "1",
        "--comm_round", "220", "--frequency_of_the_test", "10",
    ],
    # benchmark/README.md:55
    "fed_cifar100_rn18": [
        "--algo", "fedavg", "--dataset", "fed_cifar100", "--model", "resnet18_gn",
        "--client_num_in_total", "500", "--client_num_per_round", "10",
        "--batch_size", "20", "--lr", "0.1", "--epochs", "1",
        "--comm_round", "4000", "--frequency_of_the_test", "100",
    ],
    # benchmark/README.md:56
    "shakespeare_rnn": [
        "--algo", "fedavg", "--dataset", "fed_shakespeare", "--model", "rnn",
        "--client_num_in_total", "715", "--client_num_per_round", "10",
        "--batch_size", "4", "--lr", "1.0", "--epochs", "1",
        "--comm_round", "1200", "--frequency_of_the_test", "50",
    ],
    # benchmark/README.md:57 (lr = 10**-0.5 ~= 0.3162)
    "stackoverflow_nwp": [
        "--algo", "fedavg", "--dataset", "stackoverflow_nwp", "--model", "rnn_stackoverflow",
        "--client_num_in_total", "342477", "--client_num_per_round", "50",
        "--batch_size", "16", "--lr", "0.31622776601", "--epochs", "1",
        "--comm_round", "1500", "--frequency_of_the_test", "50",
    ],
    # benchmark/README.md:105 cross-silo row (hetero = LDA alpha 0.5)
    "cifar10_resnet56": [
        "--algo", "fedavg", "--dataset", "cifar10", "--model", "resnet56",
        "--client_num_in_total", "10", "--client_num_per_round", "10",
        "--partition_method", "hetero", "--partition_alpha", "0.5",
        "--batch_size", "64", "--lr", "0.001", "--wd", "0.001",
        "--epochs", "20", "--comm_round", "100", "--frequency_of_the_test", "10",
    ],
}


def main(argv=None):
    ap = argparse.ArgumentParser("reproduce_benchmarks")
    ap.add_argument("name", nargs="?", help="config name or 'all'")
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--data_dir", type=str, default=None)
    ap.add_argument("--rounds", type=int, default=None,
                    help="override comm_round (smoke runs)")
    args, extra = ap.parse_known_args(argv)

    if args.list or not args.name:
        for k, v in CONFIGS.items():
            print(f"{k:20s} {' '.join(v)}")
        return

    names = list(CONFIGS) if args.name == "all" else [args.name]
    unknown = [n for n in names if n not in CONFIGS]
    if unknown:
        print(f"unknown config(s) {unknown}; valid: {', '.join(CONFIGS)}",
              file=sys.stderr)
        sys.exit(2)

    from fedml_tpu.experiments import cli
    for name in names:
        flags = list(CONFIGS[name])
        if args.data_dir:
            flags += ["--data_dir", args.data_dir]
        if args.rounds is not None:
            i = flags.index("--comm_round")
            flags[i + 1] = str(args.rounds)
        print(f"=== {name}: fedml_tpu.experiments.cli {' '.join(flags + extra)}")
        cli.main(flags + extra)


if __name__ == "__main__":
    main()
