"""fedlint engine — module loading, rule registry, suppressions, baseline.

Deliberately stdlib-only (ast/json/re/pathlib): the CLI must run in a bare
interpreter as fast as pyflakes would, and the engine itself must never
import the code it scans (a broken module must still be LINTABLE — the
import gate is test_lint.py's job, not ours).

The moving parts:

- :class:`Module` — one parsed source file plus its suppression table;
- :class:`Rule` — subclass, set ``name``/``description``, implement
  ``check(module)`` yielding :class:`Finding`; register with ``@register``;
- :func:`run` — scan paths, run rules, drop suppressed findings;
- baseline — ``scripts/fedlint_baseline.json`` entries grandfather known
  findings by (rule, path, message-substring), never by line number (lines
  drift on every edit; messages only when the code actually changes). Each
  entry must carry a ``why`` — an unannotated grandfather is a shape error.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Callable, Iterable, Iterator


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at one source location."""

    path: str  # posix path relative to the scan root
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


# a suppression directive: "fedlint: disable=rule-a,rule-b" followed by an
# optional rationale (required by review convention; docs/ANALYSIS.md). The
# directive must LEAD the comment — prose or doc examples that merely
# mention the syntax mid-sentence must not suppress anything — and it is
# matched against real COMMENT tokens, never raw source lines, so a string
# literal containing the text (a fixture, a docstring example) is inert.
_SUPPRESS_RE = re.compile(r"#+\s*fedlint:\s*disable=([A-Za-z0-9_,-]+)")


class Module:
    """One parsed source file handed to every rule.

    ``path`` is posix-relative to the scan root, so path-scoped rules can
    test directory membership (``module.in_dirs("core", "comm")``) the same
    way against the live tree and against test fixtures.
    """

    def __init__(self, path: str, source: str, tree: ast.AST):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        # file-wide and per-line suppression tables, parsed once from real
        # comment tokens (tokenize can reject what ast accepted only in
        # exotic encodings — treat that as "no suppressions", never a crash)
        self.file_suppressions: set[str] = set()
        self.line_suppressions: dict[int, set[str]] = {}
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline))
        except (tokenize.TokenError, SyntaxError, IndentationError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.match(tok.string)
            if not m:
                continue
            rules = {r for r in m.group(1).split(",") if r}
            row, col = tok.start
            if self.lines[row - 1][:col].strip() == "":
                # a comment line of its own suppresses the whole file
                self.file_suppressions |= rules
            else:  # trailing a statement: that line only
                self.line_suppressions.setdefault(row, set()).update(rules)

    @property
    def parts(self) -> tuple[str, ...]:
        return Path(self.path).parts

    def in_dirs(self, *names: str) -> bool:
        """True when any path segment (not the filename) matches a name."""
        return bool(set(self.parts[:-1]) & set(names))

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions or "all" in self.file_suppressions:
            return True
        at = self.line_suppressions.get(line, ())
        return rule in at or "all" in at

    def finding(self, rule: "Rule | str", node: ast.AST | int,
                message: str) -> Finding:
        line = node if isinstance(node, int) else getattr(node, "lineno", 1)
        name = rule if isinstance(rule, str) else rule.name
        return Finding(path=self.path, line=line, rule=name, message=message)


class Rule:
    """Base class: subclasses set ``name``/``description`` and implement
    ``check``. Rules are stateless — one instance serves every module."""

    name: str = ""
    description: str = ""

    def check(self, module: Module) -> Iterator[Finding]:
        raise NotImplementedError


RULES: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    """Class decorator adding one instance to the process-wide registry."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if cls.name in RULES and type(RULES[cls.name]) is not cls:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    RULES[cls.name] = cls()
    return cls


# ---------------------------------------------------------------- scanning
def iter_sources(paths: Iterable[Path], root: Path) -> Iterator[tuple[str, Path]]:
    """(relative posix path, absolute path) for every .py under ``paths``.

    Sorted for stable output. __pycache__ and hidden dirs are skipped —
    judged on components BELOW each scan path only, so a repo cloned under
    a dotted ancestor (~/.local/src/...) still scans (an ancestor the
    caller explicitly pointed at is not ours to veto)."""
    seen = set()
    for p in paths:
        p = Path(p)
        if p.is_file():
            files = [(p, p.name)] if p.suffix == ".py" else []
        else:
            files = [(f, f.relative_to(p).as_posix())
                     for f in sorted(p.rglob("*.py"))]
        for f, below in files:
            if any(part == "__pycache__" or part.startswith(".")
                   for part in Path(below).parts):
                continue
            try:
                rel = f.resolve().relative_to(root.resolve()).as_posix()
            except ValueError:
                rel = f.as_posix()
            if rel in seen:
                continue
            seen.add(rel)
            yield rel, f


def load_module(rel: str, abspath: Path) -> Module:
    source = abspath.read_text(encoding="utf-8")
    tree = ast.parse(source, filename=str(abspath))
    return Module(rel, source, tree)


def run(paths: Iterable[str | Path], root: str | Path | None = None,
        rules: Iterable[str] | None = None,
        on_error: Callable[[str, Exception], None] | None = None,
        stats: dict | None = None) -> list[Finding]:
    """Scan ``paths`` (files or directories) with ``rules`` (default: all
    registered), returning unsuppressed findings sorted by location.

    ``root`` anchors the relative paths findings and baselines use; it
    defaults to the repo root guess (parent of the fedml_tpu package) so
    baseline paths read ``fedml_tpu/comm/base.py``. A file that fails to
    PARSE becomes a ``parse-error`` finding — an unparseable module must
    fail the gate, not silently drop out of it. Pass ``stats={}`` to get
    ``stats['files']`` — the count of files this very scan visited (the
    CLI reports it; a second walk could disagree with what was linted)."""
    root = Path(root) if root is not None else Path(__file__).parents[2]
    active = [RULES[name] for name in rules] if rules is not None \
        else list(RULES.values())
    findings: list[Finding] = []
    if stats is not None:
        stats["files"] = 0
    for rel, abspath in iter_sources([Path(p) for p in paths], root):
        if stats is not None:
            stats["files"] += 1
        try:
            module = load_module(rel, abspath)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            if on_error is not None:
                on_error(rel, e)
            findings.append(Finding(path=rel,
                                    line=getattr(e, "lineno", 1) or 1,
                                    rule="parse-error", message=str(e)))
            continue
        for rule in active:
            for f in rule.check(module):
                if not module.suppressed(f.rule, f.line):
                    findings.append(f)
    return sorted(findings)


# ---------------------------------------------------------------- baseline
def load_baseline(path: str | Path) -> list[dict]:
    """Parse + validate a baseline file. Schema::

        {"findings": [{"rule": ..., "path": ..., "contains": ...,
                       "why": "<mandatory one-line rationale>"}, ...]}

    ``contains`` is a substring of the finding message (line numbers are
    deliberately not part of the key). A missing ``why`` is a ValueError:
    the committed baseline stays annotated or it does not parse."""
    with open(path) as f:
        doc = json.load(f)
    entries = doc.get("findings")
    if not isinstance(entries, list):
        raise ValueError(f"{path}: expected a 'findings' list")
    for i, e in enumerate(entries):
        for key in ("rule", "path", "contains", "why"):
            if not isinstance(e.get(key), str) or not e[key].strip():
                raise ValueError(
                    f"{path}: findings[{i}] needs a non-empty {key!r} "
                    "(every grandfathered entry must name its rule, path, "
                    "a message substring, and why it is grandfathered)")
    return entries


def make_baseline(findings: Iterable[Finding],
                  why: str = "TODO: annotate") -> dict:
    """A baseline document grandfathering ``findings`` — the --write-baseline
    starting point; each entry's ``why`` still needs a human sentence."""
    return {"findings": [
        {"rule": f.rule, "path": f.path, "contains": f.message, "why": why}
        for f in sorted(set(findings))]}


def apply_baseline(findings: list[Finding], entries: list[dict],
                   ) -> tuple[list[Finding], list[Finding], list[dict]]:
    """-> (new findings, grandfathered findings, stale entries).

    A stale entry matches nothing — the debt it recorded was paid (or the
    message changed, which means the code changed and deserves a fresh
    look); the CLI reports staleness so the baseline shrinks over time
    instead of accreting."""
    new: list[Finding] = []
    old: list[Finding] = []
    used = [False] * len(entries)
    for f in findings:
        hit = False
        for i, e in enumerate(entries):
            if (e["rule"] == f.rule and e["path"] == f.path
                    and e["contains"] in f.message):
                used[i] = True
                hit = True
        (old if hit else new).append(f)
    stale = [e for i, e in enumerate(entries) if not used[i]]
    return new, old, stale
