"""The fedlint rule catalogue — one rule per recurring review-fix class.

Each rule names the historical bug class that motivated it (full writeups
in docs/ANALYSIS.md). Rules are AST-only and over-approximate on purpose:
a linter that misses the next `_undeliverable` race is worthless, and the
escape hatch for a justified exception is a one-line suppression comment
with a rationale, not a looser rule.

Shared machinery first (dotted-name resolution, jit-seam discovery), then
the rules in the order docs/ANALYSIS.md documents them.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.engine import Finding, Module, Rule, register


# --------------------------------------------------------------- ast helpers
def dotted(node: ast.AST) -> str | None:
    """'jax.lax.scan' for a Name/Attribute chain; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_names(tree: ast.AST) -> Iterator[tuple[str, ast.Call]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name is not None:
                yield name, node


def module_imports(module: Module) -> set[str]:
    """Top-level module names imported anywhere in the file (``import x``,
    ``import x.y``, ``from x.y import z`` all contribute 'x')."""
    roots: set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                roots.add(alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            roots.add(node.module.split(".")[0])
    return roots


# ------------------------------------------------------------ jit seam index
# dotted names that turn their function argument (or decorated function)
# into a traced program: Python side effects inside run at TRACE time only,
# and host syncs inside force a device round-trip per call
_JIT_WRAPPERS = frozenset({
    "jit", "jax.jit", "pjit", "jax.pjit",
})
_TRACE_CALLS = frozenset(_JIT_WRAPPERS | {
    "lax.scan", "jax.lax.scan", "scan",
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "pmap", "jax.pmap", "vmap", "jax.vmap",
    "lax.fori_loop", "jax.lax.fori_loop",
    "lax.while_loop", "jax.lax.while_loop",
    "checkpoint", "jax.checkpoint", "jax.remat",
})


def _is_jit_expr(node: ast.AST) -> bool:
    """True when ``node`` evaluates to a tracing transform: ``jax.jit``,
    ``partial(jax.jit, ...)``, or a call of either (decorator factories)."""
    name = dotted(node)
    if name in _JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        fname = dotted(node.func)
        if fname in _JIT_WRAPPERS:
            return True
        if fname in ("partial", "functools.partial"):
            return any(_is_jit_expr(a) for a in node.args)
    return False


def traced_functions(module: Module) -> list[ast.FunctionDef]:
    """Function defs that become traced programs: decorated with (a partial
    of) ``jax.jit``, or passed by name into a ``_TRACE_CALLS`` seam
    (``jax.jit(step)``, ``lax.scan(body, ...)``, ``shard_map(f, ...)``).
    Memoized per module — jit-purity and host-sync share the index."""
    cached = getattr(module, "_traced_fns", None)
    if cached is not None:
        return cached
    defs_by_name: dict[str, list[ast.FunctionDef]] = {}
    traced: list[ast.FunctionDef] = []
    seen: set[ast.FunctionDef] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
            if any(_is_jit_expr(d) for d in node.decorator_list):
                if node not in seen:
                    seen.add(node)
                    traced.append(node)
    for name, call in _call_names(module.tree):
        if name in _TRACE_CALLS and call.args \
                and isinstance(call.args[0], ast.Name):
            for fn in defs_by_name.get(call.args[0].id, ()):
                if fn not in seen:
                    seen.add(fn)
                    traced.append(fn)
    module._traced_fns = traced  # type: ignore[attr-defined]
    return traced


# wall-clock reads (value depends on when, not what) and unseeded RNG draws
_WALL_CLOCK = frozenset({
    "time.time", "time.time_ns", "time.strftime", "time.ctime",
    "time.localtime", "time.gmtime",
})
_DATETIME_READS = frozenset({"now", "utcnow", "today"})
# np.random module-level callables that are SEEDED constructors, not draws
# from the hidden global stream
_NP_RANDOM_OK = frozenset({"RandomState", "Generator", "SeedSequence",
                           "PCG64", "Philox", "MT19937", "BitGenerator"})
_STDLIB_RANDOM_OK = frozenset({"Random", "SystemRandom"})


def _clock_or_rng_violation(name: str, call: ast.Call,
                            has_random_import: bool) -> str | None:
    """Why ``name(...)`` breaks replay determinism, or None if it doesn't."""
    parts = name.split(".")
    if name in _WALL_CLOCK:
        return f"wall-clock read {name}()"
    if "datetime" in parts[:-1] and parts[-1] in _DATETIME_READS:
        return f"wall-clock read {name}()"
    if parts[:2] in (["np", "random"], ["numpy", "random"]) and len(parts) == 3:
        if parts[2] == "default_rng":
            seeded = bool(call.args) or any(kw.arg == "seed"
                                            for kw in call.keywords)
            return None if seeded else \
                f"unseeded {name}() (pass an explicit seed)"
        if parts[2] not in _NP_RANDOM_OK:
            return (f"{name}() draws from numpy's hidden global stream "
                    "(use a seeded RandomState/fold_in chain)")
    if parts[0] == "random" and len(parts) == 2 and has_random_import \
            and parts[1] not in _STDLIB_RANDOM_OK:
        return (f"{name}() draws from the random module's hidden global "
                "stream (use a seeded generator)")
    return None


def _entropy_violation(name: str, imports: set[str]) -> str | None:
    """Why ``name(...)`` is nondeterministic key material, or None.

    The secure-aggregation contract (core/secure_agg.py, docs/
    ROBUSTNESS.md §Secure aggregation): every mask/share seed in core/
    and collectives/ must flow through the sha256 derive chain so chaos
    runs replay bit-for-bit — os.urandom / the secrets module would make
    masked aggregates unreplayable AND unauditable. Import-guarded like
    the stdlib-random check: a local variable named ``secrets`` (or an
    ``urandom`` helper) in a file that never imports the module must not
    trip the live-tree gate."""
    parts = name.split(".")
    if name in ("os.urandom", "urandom") and "os" in imports:
        # bare 'urandom' covers the from-import form; the os-import guard
        # keeps same-named local helpers in os-free files clean
        return ("os.urandom() is nondeterministic key material (derive "
                "seeds via the sha256 chain — core/secure_agg."
                "derive_secret)")
    if parts[0] == "secrets" and len(parts) == 2 and "secrets" in imports:
        return (f"{name}() is nondeterministic key material (derive "
                "seeds via the sha256 chain — core/secure_agg."
                "derive_secret)")
    return None


# ===================================================================== rules
@register
class JitPurity(Rule):
    """No Python side effects inside traced programs.

    A ``self.X = ...`` or ``time.time()`` inside a jitted function runs
    once at trace time and never again — the classic silently-wrong round
    program (the PR-6 scan-block driver and every ``_dispatch_round`` seam
    re-risk this on each refactor)."""

    name = "jit-purity"
    description = ("no self/global mutation or wall-clock/global-RNG reads "
                   "inside functions handed to jax.jit / shard_map / "
                   "lax.scan")

    def check(self, module: Module) -> Iterator[Finding]:
        has_random = "random" in module_imports(module)
        for fn in traced_functions(module):
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Attribute) \
                                    and isinstance(leaf.value, ast.Name) \
                                    and leaf.value.id == "self" \
                                    and isinstance(leaf.ctx, ast.Store):
                                yield module.finding(self, node, (
                                    f"traced function {fn.name!r} mutates "
                                    f"self.{leaf.attr} — runs once at trace "
                                    "time, then never again"))
                elif isinstance(node, ast.Global):
                    yield module.finding(self, node, (
                        f"traced function {fn.name!r} declares "
                        f"global {', '.join(node.names)} — trace-time "
                        "side effect"))
                elif isinstance(node, ast.Call):
                    name = dotted(node.func)
                    if name is None:
                        continue
                    why = _clock_or_rng_violation(name, node, has_random)
                    if why is not None:
                        yield module.finding(self, node, (
                            f"traced function {fn.name!r}: {why} — value "
                            "freezes at trace time"))


@register
class HostSync(Rule):
    """No host syncs on traced values in hot-path modules.

    ``float(x)`` / ``x.item()`` / ``np.asarray(x)`` inside a jitted
    function blocks on the device per call — the dispatch-pipeline killer
    the PR-6/PR-7 drivers kept out of the round program by review."""

    name = "host-sync"
    description = ("no float()/int()/bool()/.item()/np.asarray on traced "
                   "values inside jitted code — and no blocking "
                   "float(jnp.*(...))-style fetches on host hot paths — "
                   "in core/, algorithms/, distributed/")

    _CASTS = frozenset({"float", "int", "bool"})
    _MATERIALIZE = frozenset({"np.asarray", "np.array", "numpy.asarray",
                              "numpy.array", "jax.device_get",
                              "onp.asarray", "onp.array"})
    _JNP_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "lax.")

    def _is_device_expr(self, node: ast.AST) -> bool:
        """A call that transparently produces a device value: jnp.sum(x),
        jax.lax.*, jnp.linalg.norm(...) — the argument shape of the
        blocking-fetch pattern."""
        if not isinstance(node, ast.Call):
            return False
        name = dotted(node.func)
        return bool(name) and name.startswith(self._JNP_PREFIXES)

    def check(self, module: Module) -> Iterator[Finding]:
        if not module.in_dirs("core", "algorithms", "distributed"):
            return
        # blocking device fetches fused into HOST expressions:
        # float(jnp.sum(x)) on a round path blocks the dispatch pipeline
        # per call (the FedAvgAggregator all-quarantined check shipped
        # exactly this) — flag the cast-of-a-jnp-call pattern module-wide;
        # traced functions are covered by the generic cast walk below
        traced_nodes = {id(n) for fn in traced_functions(module)
                        for n in ast.walk(fn)}
        for node in ast.walk(module.tree):
            if id(node) in traced_nodes or not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name in self._CASTS and len(node.args) == 1 \
                    and self._is_device_expr(node.args[0]):
                inner = dotted(node.args[0].func)
                yield module.finding(self, node, (
                    f"blocking device fetch {name}({inner}(...)) on a "
                    "host path — the cast synchronizes on the device "
                    "result; derive the flag from already-fetched host "
                    "state, or sync once at the drain point"))
        for fn in traced_functions(module):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                if name in self._CASTS and len(node.args) == 1 \
                        and not isinstance(node.args[0], ast.Constant):
                    yield module.finding(self, node, (
                        f"traced function {fn.name!r} host-syncs via "
                        f"{name}(...) — forces a device round-trip per "
                        "call (keep it in jnp, or sync outside the jit)"))
                elif name in self._MATERIALIZE:
                    yield module.finding(self, node, (
                        f"traced function {fn.name!r} materializes a "
                        f"device value via {name}(...) — host transfer "
                        "inside the traced program"))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args:
                    yield module.finding(self, node, (
                        f"traced function {fn.name!r} host-syncs via "
                        ".item() — forces a device round-trip per call"))


# ---------------------------------------------------------- lock discipline
_LOCKISH = frozenset({"lock", "rlock", "mutex", "cond", "condition", "cv",
                      "sem", "semaphore"})


def _is_lock_ctx(expr: ast.AST) -> bool:
    """Whole-word match on the dotted name's snake/dot segments: _rx_lock,
    round_lock, Lock, _cv, _cond all qualify; recv_stream must not (``cv``
    inside ``recv``) and block_ctx must not (``lock`` inside ``block``)."""
    name = dotted(expr.func if isinstance(expr, ast.Call) else expr)
    if name is None:
        return False
    segments = name.lower().replace(".", "_").split("_")
    return bool(_LOCKISH & set(segments))


class _MethodFacts(ast.NodeVisitor):
    """Per-method: self-attr writes (with guarded flag), self-method calls
    (with guarded flag). 'Guarded' = lexically inside ``with self._lock:``
    (any context-manager whose dotted name mentions lock/mutex/cond)."""

    def __init__(self) -> None:
        self.writes: list[tuple[str, int, bool]] = []
        self.calls: list[tuple[str, bool]] = []
        self._depth = 0

    def visit_With(self, node: ast.With) -> None:
        locked = any(_is_lock_ctx(item.context_expr) for item in node.items)
        self._depth += locked
        self.generic_visit(node)
        self._depth -= locked

    def _record_target(self, t: ast.AST, lineno: int) -> None:
        for leaf in ast.walk(t):
            if isinstance(leaf, ast.Attribute) \
                    and isinstance(leaf.value, ast.Name) \
                    and leaf.value.id == "self" \
                    and isinstance(leaf.ctx, ast.Store):
                self.writes.append((leaf.attr, lineno, self._depth > 0))
            elif isinstance(leaf, ast.Subscript):
                base = dotted(leaf.value)
                if base is not None and base.startswith("self."):
                    self.writes.append((base.split(".")[1], lineno,
                                        self._depth > 0))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted(node.func)
        if name is not None and name.startswith("self.") \
                and name.count(".") == 1:
            self.calls.append((name.split(".")[1], self._depth > 0))
        self.generic_visit(node)


@register
class LockDiscipline(Rule):
    """Shared attributes touched by a background thread must be written
    under a lock.

    The `_undeliverable` race, the gRPC channel-cache reconnect race, and
    the MemorySink append race were all this shape: a class starts a
    ``threading.Thread`` on one of its methods and some OTHER method
    mutates the same attribute with no ``with self._lock:`` around either
    side."""

    name = "lock-discipline"
    description = ("attributes written both by a thread-target method and "
                   "elsewhere in the class must be written under "
                   "'with self.<lock>:'")

    def check(self, module: Module) -> Iterator[Finding]:
        for cls in ast.walk(module.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(module, cls)

    def _check_class(self, module: Module,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        targets: set[str] = set()
        for name, call in _call_names(cls):
            if name.split(".")[-1] != "Thread":
                continue
            for kw in call.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Attribute) \
                        and isinstance(kw.value.value, ast.Name) \
                        and kw.value.value.id == "self":
                    targets.add(kw.value.attr)
        targets &= set(methods)
        if not targets:
            return

        facts = {}
        for name, fn in methods.items():
            v = _MethodFacts()
            v.visit(fn)
            facts[name] = v

        def closure(entries: set[str]) -> set[str]:
            out, frontier = set(entries), list(entries)
            while frontier:
                for callee, _ in facts[frontier.pop()].calls:
                    if callee in methods and callee not in out:
                        out.add(callee)
                        frontier.append(callee)
            return out

        # thread side: the targets plus every self-method reachable from
        # them; main side: everything reachable from the non-thread entry
        # points. A shared helper (reachable from BOTH) counts its writes
        # on both sides — that is exactly how the `_undeliverable`-shape
        # race hides behind an innocent-looking helper.
        thread_set = closure(targets)
        main_set = closure(set(methods) - thread_set - {"__init__"})

        # a method is lock-held when EVERY call site already holds the lock
        # (the 'caller holds self._lock' docstring convention) — its writes
        # then count as guarded. any() would let one guarded call site
        # whitelist the helper's writes at an unguarded one, which is
        # exactly the race shape this rule exists to catch.
        sites: dict[str, list[bool]] = {}
        for f in facts.values():
            for callee, g in f.calls:
                sites.setdefault(callee, []).append(g)
        lock_held = {m for m in methods if sites.get(m) and all(sites[m])}

        def write_sites(names: set[str]) -> dict[str, list[tuple[int, bool]]]:
            out: dict[str, list[tuple[int, bool]]] = {}
            for m in names:
                if m == "__init__":
                    continue  # pre-thread construction is single-threaded
                for attr, line, guarded in facts[m].writes:
                    out.setdefault(attr, []).append(
                        (line, guarded or m in lock_held))
            return out

        by_thread = write_sites(thread_set)
        by_main = write_sites(main_set)
        shared = set(by_thread) & set(by_main)
        for attr in sorted(shared):
            # a shared helper contributes the same site to both maps: dedup
            for line, guarded in sorted(set(by_thread[attr] + by_main[attr])):
                if not guarded:
                    yield module.finding(self, line, (
                        f"class {cls.name}: self.{attr} is written by "
                        f"thread target(s) {sorted(targets)} AND other "
                        "methods, but this write holds no lock (wrap in "
                        "'with self._lock:')"))


@register
class Determinism(Rule):
    """Replay-deterministic paths take no wall-clock or hidden-RNG input.

    The PR-2 replay contract: every chaos/comm/core decision derives from
    seeds via sha256/fold_in chains (monotonic DURATION reads,
    time.perf_counter/monotonic, are fine — they never steer replayed
    decisions). In core/ and collectives/ the rule additionally bans
    nondeterministic KEY MATERIAL (os.urandom, the secrets module): every
    secure-aggregation mask/share seed must flow through the sha256
    derive chain (core/secure_agg.py) or masked runs stop replaying.
    comm/ is exempt from the entropy half — transport nonces (the gRPC
    dedup epoch) are not replayed state."""

    name = "determinism"
    description = ("no wall-clock reads or unseeded np.random/random calls "
                   "in core/, chaos/, comm/; no os.urandom/secrets key "
                   "material in core/, collectives/")

    def check(self, module: Module) -> Iterator[Finding]:
        entropy_scope = module.in_dirs("core", "collectives")
        if not (module.in_dirs("core", "chaos", "comm") or entropy_scope):
            return
        clock_scope = module.in_dirs("core", "chaos", "comm")
        imports = module_imports(module)
        has_random = "random" in imports
        for name, call in _call_names(module.tree):
            why = (_clock_or_rng_violation(name, call, has_random)
                   if clock_scope else None)
            if why is None and entropy_scope:
                why = _entropy_violation(name, imports)
            if why is not None:
                yield module.finding(self, call, (
                    f"{why} in a replay-deterministic module (derive from "
                    "the seed via sha256/fold_in, or take a clock "
                    "parameter)"))


@register
class MetricDiscipline(Rule):
    """Metric family names are literal and namespaced.

    An f-string family name silently forks a new time series per format
    value (unbounded cardinality) and drifts from the exporters' expected
    vocabulary; names outside fed_/comm_ vanish from the dashboards and
    the bench-gate blobs (the PR-8/PR-10 review rule)."""

    name = "metric-discipline"
    description = ("registry.counter/gauge/histogram family names must be "
                   "string literals with a fed_/comm_ prefix")

    _KINDS = frozenset({"counter", "gauge", "histogram"})

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._KINDS):
                continue
            recv = dotted(node.func.value)
            if recv is None \
                    or recv.split(".")[-1].lstrip("_").lower() != "registry":
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.JoinedStr):
                yield module.finding(self, node, (
                    "f-string metric family name — unbounded label-free "
                    "cardinality; make the family a fed_/comm_ literal and "
                    "put the variable part in a label"))
            elif not isinstance(arg, ast.Constant):
                yield module.finding(self, node, (
                    "non-literal metric family name — exporters and the "
                    "bench gate can only track literal fed_/comm_ "
                    "families"))
            elif not (isinstance(arg.value, str)
                      and arg.value.startswith(("fed_", "comm_"))):
                yield module.finding(self, node, (
                    f"metric family {arg.value!r} lacks the fed_/comm_ "
                    "namespace prefix"))


@register
class WireKeys(Rule):
    """Message param keys come from the message_define vocabulary.

    A literal key on ``add_params`` drifts from the registered handler
    vocabulary the moment one side is renamed — the cross-protocol decode
    table and the LOSSY_EXEMPT contract only protect keys they know
    about."""

    name = "wire-keys"
    description = ("Message.add_params keys must be message_define "
                   "MSG_ARG_KEY_* constants; LOSSY_EXEMPT keys must stay "
                   "in the _KNOWN_ARRAY_KEYS decode table")

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "add_params" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str) \
                    and not node.args[0].value.startswith("__"):
                yield module.finding(self, node, (
                    f"literal wire key {node.args[0].value!r} on "
                    "add_params — use the message_define MSG_ARG_KEY_* "
                    "constant so handlers, the decode table, and "
                    "LOSSY_EXEMPT stay one vocabulary"))
        yield from self._check_lossy_table(module)

    def _check_lossy_table(self, module: Module) -> Iterator[Finding]:
        """Inside the file that defines both: every LOSSY_EXEMPT key must
        appear in the _KNOWN_ARRAY_KEYS decode table, so a key exempted
        from lossy re-encoding is also decodable from interop frames."""
        exempt: tuple[ast.AST, set[str]] | None = None
        known: set[str] | None = None
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            tname = t.id if isinstance(t, ast.Name) else \
                t.attr if isinstance(t, ast.Attribute) else None
            if tname == "LOSSY_EXEMPT":
                keys = {e.value for e in ast.walk(node.value)
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
                exempt = (node, keys)
            elif tname == "_KNOWN_ARRAY_KEYS" \
                    and isinstance(node.value, ast.Dict):
                known = {k.value for k in node.value.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str)}
        if exempt is not None and known is not None:
            node, keys = exempt
            for key in sorted(keys - known):
                yield module.finding(self, node, (
                    f"LOSSY_EXEMPT key {key!r} is missing from the "
                    "_KNOWN_ARRAY_KEYS decode table — interop json frames "
                    "would hand handlers nested lists for it"))


@register
class ExceptSwallow(Rule):
    """Comm dispatch, chaos injection, and obs sink failures are counted
    or logged, never silently dropped.

    A swallowed handler error turns protocol bugs into eternal hangs (the
    ``_notify`` re-raise rationale); a swallowed sink error silently
    stops telemetry. Bare ``except:`` additionally eats KeyboardInterrupt
    and SystemExit."""

    name = "except-swallow"
    description = ("no bare except, and no 'except Exception' that neither "
                   "logs nor counts, in comm/, chaos/, obs/")

    _EVIDENCE = ("log", "warn", "error", "exception", "debug", "info",
                 "record", "inc", "observe", "emit", "count", "print",
                 "fail")
    _BROAD = frozenset({"Exception", "BaseException"})

    def _is_broad(self, type_node: ast.AST | None) -> bool:
        if type_node is None:
            return True
        names = [dotted(type_node)] if not isinstance(type_node, ast.Tuple) \
            else [dotted(e) for e in type_node.elts]
        return any(n is not None and n.split(".")[-1] in self._BROAD
                   for n in names)

    def _handles(self, handler: ast.ExceptHandler) -> bool:
        """True when the handler visibly does something with the failure:
        re-raises, or calls anything that looks like logging/metrics."""
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name is not None and any(tok in name.lower()
                                            for tok in self._EVIDENCE):
                    return True
        return False

    def check(self, module: Module) -> Iterator[Finding]:
        if not module.in_dirs("comm", "chaos", "obs"):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield module.finding(self, node, (
                    "bare 'except:' — eats KeyboardInterrupt/SystemExit; "
                    "catch a concrete type (and log or count the drop)"))
            elif self._is_broad(node.type) and not self._handles(node):
                yield module.finding(self, node, (
                    "'except Exception' swallows the failure silently — "
                    "dispatch/chaos/sink paths must log or count every "
                    "absorbed error (docs/ANALYSIS.md §except-swallow)"))


@register
class FsyncDiscipline(Rule):
    """Durability commit points route through the shared fsync helpers.

    ``core/wal.py``, ``core/checkpoint.py``, and ``core/privacy.py``
    are the crash-recovery substrate (docs/ROBUSTNESS.md §Server crash
    recovery): a bare ``open(..., 'w')`` there writes through the page
    cache only, so the "committed" round/WAL record a recovery later
    trusts can silently not exist after power loss — crash-safe until
    the cache says otherwise. ``privacy.py`` is in scope because the
    per-client ε ledgers carry the never-under-report promise: any
    persistence a ledger ever grows must be as durable as the WAL
    precharge records it rides today. Every write in those modules must
    go through the shared helpers
    (``durable_open``/``durable_write``/``durable_replace`` in
    core/wal.py) or live inside a ``durable_*``-named helper that owns
    its own fsync ceremony (the WAL's append-handle constructor)."""

    name = "fsync-discipline"
    description = ("no bare open-for-write in core wal/checkpoint/"
                   "privacy modules — route commit points through the "
                   "shared durable_* fsync helpers")

    _TARGETS = ("wal.py", "checkpoint.py", "privacy.py")
    _WRITE_MODES = ("w", "a", "x", "+")

    def _scoped(self, module: Module) -> bool:
        parts = module.parts
        return parts[-1] in self._TARGETS and "core" in parts[:-1]

    def _write_mode(self, call: ast.Call) -> bool:
        mode = None
        if len(call.args) >= 2:
            mode = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if mode is None:
            return False  # bare open(path) reads — recovery's job
        if not (isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)):
            return True  # dynamic mode: assume the worst
        return any(c in mode.value for c in self._WRITE_MODES)

    def check(self, module: Module) -> Iterator[Finding]:
        if not self._scoped(module):
            return
        # map each open() call to its enclosing function name (if any)
        enclosing: dict[int, str] = {}
        for fn in ast.walk(module.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(fn):
                    enclosing.setdefault(id(sub), fn.name)
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "open"
                    and self._write_mode(node)):
                continue
            fn_name = enclosing.get(id(node), "")
            if fn_name.startswith("durable_") or \
                    fn_name.startswith("_durable_"):
                continue  # the shared helpers own their fsync ceremony
            yield module.finding(self, node, (
                "bare open-for-write at a WAL/checkpoint commit point — "
                "route it through core/wal.py's durable_open/"
                "durable_write (tmp -> fsync -> rename) so the record "
                "survives the crash it exists to recover from"))


@register
class NoBarePrint(Rule):
    """Library code routes output through logging or the obs EventLog.

    Telemetry must be structured and capturable, not interleaved with
    stdout; the only legitimate bare prints are CLI entry points whose
    stdout IS their interface, which suppress file-wide with a rationale
    (migrated from tests/test_lint.py's walker)."""

    name = "no-bare-print"
    description = ("no bare print() in library code — use logging or the "
                   "obs EventLog (CLIs suppress file-wide)")

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "print":
                yield module.finding(self, node, (
                    "bare print() in library code (route telemetry "
                    "through fedml_tpu.obs.EventLog or logging, or "
                    "suppress file-wide for a stdout-interface CLI)"))
