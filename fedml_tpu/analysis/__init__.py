"""fedlint — AST-based invariant checker for the jit/thread/wire discipline.

Every scale PR in this repo shipped review fixes for the same recurring
bug classes: unlocked shared state touched by background threads, wall
clock or unseeded randomness leaking into replay-deterministic paths,
host syncs and Python side effects inside jitted round programs, and
ad-hoc metric/message-key strings drifting from their registries. FedJAX
(arXiv:2108.02117) gets its simulation speed precisely from keeping
per-client training a pure traced program, and the reference FedML paper
(arXiv:2007.13518) ties reproducibility to a disciplined message/metric
protocol layer. This package machine-checks those invariants so each new
driver does not re-risk them by hand.

Entry points:

- ``scripts/fedlint.py`` — the CLI (text + ``--json`` blob, ``--baseline``,
  bench_gate-style exit codes);
- :func:`fedml_tpu.analysis.engine.run` — the library API tests drive;
- ``fedml_tpu/analysis/rules.py`` — the rule catalogue (documented rule by
  rule in docs/ANALYSIS.md).

Suppression: ``# fedlint: disable=<rule>[,<rule>...] — <rationale>`` as a
trailing comment silences that line; on a line of its own it silences the
whole file. Grandfathered findings live in ``scripts/fedlint_baseline.json``
(annotated; kept minimal).
"""

from fedml_tpu.analysis.engine import (  # noqa: F401
    Finding,
    RULES,
    apply_baseline,
    load_baseline,
    make_baseline,
    run,
)

# importing the catalogue registers every rule into RULES
from fedml_tpu.analysis import rules as _rules  # noqa: F401  (registration)
