"""Pallas TPU kernels for the hot ops.

The compute path is mostly XLA-fused jnp; this package holds the ops where a
hand-written kernel beats the fusion XLA finds on its own — currently
blockwise flash attention (forward + backward), the inner loop of the
TransformerLM and of ring attention's per-device block update.
"""

from fedml_tpu.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
