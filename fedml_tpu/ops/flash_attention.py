"""Blockwise flash attention as Pallas TPU kernels (fwd + bwd).

The reference has no attention anywhere (its largest sequence model is an
80-char LSTM, model/nlp/rnn.py:4-36); long-context support is a
capability-plus of this framework (SURVEY.md §2.7). The sequence-parallel
layer (fedml_tpu/parallel/ring_attention.py) rotates K/V blocks over ICI and
runs an online-softmax block update per step — this module is that block
update as a proper TPU kernel: Q/K/V tiles staged through VMEM, scores on
the MXU with f32 accumulation, the softmax running max/denominator kept in
registers instead of HBM round-trips.

Layout: [B, T, H, D] in, collapsed to a (B*H, q-block) grid; each program
owns one 128-row query tile and loops over key tiles. Backward follows the
standard flash recurrence (recompute P from the saved logsumexp, then
dV = P^T dO, dS = P*(dP - delta), dQ/dK via dS) as two kernels gridded over
q-tiles (dQ) and k-tiles (dK/dV).

Runs in interpreter mode off-TPU (tests exercise it on CPU); on TPU the
kernels compile with Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _sds(shape, dtype, like):
    """ShapeDtypeStruct inheriting ``like``'s varying-manual-axes (vma): a
    pallas_call's out_shape carries no vma by default, which would fail
    shard_map(check_vma=True) at the kernel boundary on TPU."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _mode(x) -> str:
    """Which implementation serves this call.

    - 'pallas' on TPU: the real Mosaic kernels (vma-typed via _sds).
    - 'jnp' off-TPU when the inputs carry varying-manual-axes, i.e. we are
      inside shard_map(check_vma=True): Pallas INTERPRET lowering emulates
      the grid as a while_loop of dynamic_slices whose counters carry no
      vma, so strict vma checking rejects it (an interpreter artifact, not
      a property of the kernels). The jnp path is semantically identical
      (same masking, same lse definition, same lse cotangent) and
      vma-transparent.
    - 'interpret' otherwise (off-TPU, no vma): the Pallas interpreter —
      keeps the kernel logic itself under test on CPU.
    """
    if jax.default_backend() == "tpu":
        return "pallas"
    if getattr(jax.typeof(x), "vma", None):
        return "jnp"
    return "interpret"


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _mask(scores, q0, k0, bq, bk, seq_len, causal):
    qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    ok = kpos < seq_len
    if causal:
        ok = jnp.logical_and(ok, kpos <= qpos)
    return jnp.where(ok, scores, NEG_INF)


# ----------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, seq_len,
                causal, scale):
    bq, d = q_ref.shape[1], q_ref.shape[2]
    qi = pl.program_id(1)
    q0 = qi * bq
    q = q_ref[0].astype(jnp.float32)

    nk = pl.cdiv(k_ref.shape[1], block_k)

    def body(j, carry):
        o, l, m = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = _mask(s, q0, j * block_k, bq, block_k, seq_len, causal)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[:, None] + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return o_new, l_new, m_new

    o0 = jnp.zeros((bq, d), jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    o, l, m = jax.lax.fori_loop(0, nk, body, (o0, l0, m0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


def _dense_mask(s, seq_len, causal):
    """The kernels' _mask on the full [BH, Tpad, Tpad] score tensor."""
    Tq, Tk = s.shape[-2], s.shape[-1]
    qpos = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1)
    ok = kpos < seq_len
    if causal:
        ok = jnp.logical_and(ok, kpos <= qpos)
    return jnp.where(ok[None], s, NEG_INF)


def _dense_fwd(qf, kf, vf, seq_len, causal, scale):
    """jnp twin of _fwd_kernel on the padded [BH, Tpad, D] layout: same
    masking, same l_safe floor, same lse = m + log(l) definition."""
    s = jnp.einsum("btd,bsd->bts", qf.astype(jnp.float32),
                   kf.astype(jnp.float32)) * scale
    s = _dense_mask(s, seq_len, causal)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    l_safe = jnp.maximum(l, 1e-30)
    o = jnp.einsum("bts,bsd->btd", p, vf.astype(jnp.float32)) / l_safe[..., None]
    return o.astype(qf.dtype), m + jnp.log(l_safe)


def _dense_bwd(qf, kf, vf, dof, lse, delta, glse, seq_len, causal, scale):
    """jnp twin of the two backward kernels (recompute-P flash recurrence)."""
    f32 = jnp.float32
    s = jnp.einsum("btd,bsd->bts", qf.astype(f32), kf.astype(f32)) * scale
    s = _dense_mask(s, seq_len, causal)
    p = jnp.exp(s - lse[..., None])
    do = dof.astype(f32)
    dv = jnp.einsum("bts,btd->bsd", p, do)
    dp = jnp.einsum("btd,bsd->bts", do, vf.astype(f32))
    ds = p * (dp + (glse - delta)[..., None])
    dq = jnp.einsum("bts,bsd->btd", ds, kf.astype(f32)) * scale
    dk = jnp.einsum("bts,btd->bsd", ds, qf.astype(f32)) * scale
    return dq.astype(qf.dtype), dk.astype(kf.dtype), dv.astype(vf.dtype)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    Tp = -(-T // block_q) * block_q
    Tkp = -(-T // block_k) * block_k
    Tpad = max(Tp, Tkp)

    def prep(x):
        x = jnp.moveaxis(x, 2, 1).reshape(B * H, T, D)  # [BH, T, D]
        return jnp.pad(x, ((0, 0), (0, Tpad - T), (0, 0)))

    qf, kf, vf = prep(q), prep(k), prep(v)
    BH = B * H
    grid = (BH, Tpad // block_q)

    if _mode(q) == "jnp":
        o, lse = _dense_fwd(qf, kf, vf, T, causal, scale)
        return o, lse, (qf, kf, vf)

    if _mode(q) == "pallas" and getattr(jax.typeof(q), "vma", None):
        # TPU + strict shard_map: the kernels SHOULD pass with the vma-typed
        # out_shapes (_sds), but that combination hasn't been provable
        # off-hardware — if Mosaic's vma rule rejects it at trace time, fall
        # back to the XLA-fused dense path rather than failing the engine.
        try:
            return _pallas_fwd(qf, kf, vf, T, Tpad, BH, D, grid, causal,
                               scale, block_q, block_k)
        except Exception:  # noqa: BLE001 — trace-time vma rejection
            o, lse = _dense_fwd(qf, kf, vf, T, causal, scale)
            return o, lse, (qf, kf, vf)

    return _pallas_fwd(qf, kf, vf, T, Tpad, BH, D, grid, causal, scale,
                       block_q, block_k)


def _pallas_fwd(qf, kf, vf, T, Tpad, BH, D, grid, causal, scale,
                block_q, block_k):
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, seq_len=T,
                          causal=causal, scale=scale),
        out_shape=(
            _sds((BH, Tpad, D), qf.dtype, qf),
            _sds((BH, Tpad), jnp.float32, qf),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tpad, D), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tpad, D), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i), memory_space=pltpu.VMEM),
        ),
        interpret=_use_interpret(),
    )(qf, kf, vf)
    return o, lse, (qf, kf, vf)


# ---------------------------------------------------------------- backward
# ds_ij = p_ij * (dp_ij - delta_i + glse_i): the last term is the cotangent
# of the lse output (dlse_i/ds_ij = p_ij), zero when only `out` is used.
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref,
                   dq_ref, *, block_k, seq_len, causal, scale):
    bq = q_ref.shape[1]
    qi = pl.program_id(1)
    q0 = qi * bq
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    corr = glse_ref[0] - delta_ref[0]
    nk = pl.cdiv(k_ref.shape[1], block_k)

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = _mask(s, q0, j * block_k, bq, block_k, seq_len, causal)
        p = jnp.exp(s - lse[:, None])
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp + corr[:, None])
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32) * scale

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros_like(q))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref,
                    dk_ref, dv_ref, *, block_q, seq_len, causal, scale):
    bk = k_ref.shape[1]
    ki = pl.program_id(1)
    k0 = ki * bk
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    nq = pl.cdiv(q_ref.shape[1], block_q)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q)]
        corr = (glse_ref[0, pl.ds(i * block_q, block_q)]
                - delta_ref[0, pl.ds(i * block_q, block_q)])
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = _mask(s, i * block_q, k0, block_q, bk, seq_len, causal)
        p = jnp.exp(s - lse[:, None])
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp + corr[:, None])
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32) * scale
        return dk, dv

    dk, dv = jax.lax.fori_loop(0, nq, body, (jnp.zeros_like(k), jnp.zeros_like(v)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ------------------------------------------------------------------ public
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_with_lse(q, k, v, causal: bool = False, block_q: int = 128,
                             block_k: int = 128):
    """flash attention returning (out [B,T,H,D], lse [B,H,T]).

    The per-row logsumexp is a first-class output with a correct cotangent
    (folded into the backward kernels), so downstream code may use it —
    ring attention merges per-rotation partials as
    out = w1*out1 + w2*out2, w_i = exp(lse_i - logaddexp(lse1, lse2))
    (parallel/ring_attention.ring_attention_flash) and gradients stay exact.
    """
    out, (_, lse) = _flash_call(q, k, v, causal, block_q, block_k)
    B, T, H, D = q.shape
    return out, lse[:, :T].reshape(B, H, T)


def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128):
    """softmax(QK^T/sqrt(D))V with O(T) memory. [B, T, H, D] in/out.

    Equivalent to parallel/ring_attention.full_attention; pads T internally
    to the block size, so any sequence length works.
    """
    return flash_attention_with_lse(q, k, v, causal, block_q, block_k)[0]


def _flash_call(q, k, v, causal, block_q, block_k):
    B, T, H, D = q.shape
    o, lse, _ = _flash_fwd(q, k, v, causal, block_q, block_k)
    out = jnp.moveaxis(o[:, :T].reshape(B, H, T, D), 1, 2)
    return out, (o, lse)


def _fwd_rule(q, k, v, causal, block_q, block_k):
    out, (o, lse) = _flash_call(q, k, v, causal, block_q, block_k)
    B, T, H, D = q.shape
    return (out, lse[:, :T].reshape(B, H, T)), (q, k, v, o, lse)


def _bwd_rule(causal, block_q, block_k, res, gs):
    g, g_lse = gs
    q, k, v, o, lse = res
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    Tpad = o.shape[1]
    BH = B * H

    def prep(x):
        x = jnp.moveaxis(x, 2, 1).reshape(BH, T, D)
        return jnp.pad(x, ((0, 0), (0, Tpad - T), (0, 0)))

    qf, kf, vf = prep(q), prep(k), prep(v)
    dof = prep(g)
    # delta_i = sum_d dO_i O_i (the rowwise correction of the softmax vjp)
    delta = jnp.sum(dof.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # lse cotangent, padded back to [BH, Tpad] (zeros on out-only use)
    glse = jnp.pad(g_lse.astype(jnp.float32).reshape(BH, T),
                   ((0, 0), (0, Tpad - T)))

    def dense():
        dqf, dkf, dvf = _dense_bwd(qf, kf, vf, dof, lse, delta, glse,
                                   T, causal, scale)
        up = lambda x: jnp.moveaxis(x[:, :T].reshape(B, H, T, D), 1, 2)
        return up(dqf), up(dkf), up(dvf)

    mode = _mode(q)
    if mode == "jnp":
        return dense()
    if mode == "pallas" and getattr(jax.typeof(q), "vma", None):
        try:  # same trace-time fallback as _flash_fwd
            return _pallas_bwd(qf, kf, vf, dof, lse, delta, glse, B, T, H, D,
                               Tpad, BH, causal, scale, block_q, block_k)
        except Exception:  # noqa: BLE001 — trace-time vma rejection
            return dense()
    return _pallas_bwd(qf, kf, vf, dof, lse, delta, glse, B, T, H, D,
                       Tpad, BH, causal, scale, block_q, block_k)


def _pallas_bwd(qf, kf, vf, dof, lse, delta, glse, B, T, H, D, Tpad, BH,
                causal, scale, block_q, block_k):
    common_in = [
        pl.BlockSpec((1, Tpad, D), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tpad, D), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tpad, D), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tpad, D), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tpad), lambda b, i: (b, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Tpad), lambda b, i: (b, 0), memory_space=pltpu.VMEM),
    ]

    dqf = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, seq_len=T,
                          causal=causal, scale=scale),
        out_shape=_sds((BH, Tpad, D), qf.dtype, qf),
        grid=(BH, Tpad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            common_in[1], common_in[2],
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_use_interpret(),
    )(qf, kf, vf, dof, lse, delta, glse)

    dkf, dvf = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, seq_len=T,
                          causal=causal, scale=scale),
        out_shape=(
            _sds((BH, Tpad, D), kf.dtype, kf),
            _sds((BH, Tpad, D), vf.dtype, vf),
        ),
        grid=(BH, Tpad // block_k),
        in_specs=[
            common_in[0],
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            common_in[3], common_in[4], common_in[5], common_in[5],
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
        ),
        interpret=_use_interpret(),
    )(qf, kf, vf, dof, lse, delta, glse)

    def unprep(x):
        return jnp.moveaxis(x[:, :T].reshape(B, H, T, D), 1, 2)

    return unprep(dqf), unprep(dkf), unprep(dvf)


flash_attention_with_lse.defvjp(_fwd_rule, _bwd_rule)
