"""In-process loopback transport — ranks are threads, links are queues.

The reference has no mock transport; its "fake cluster" is mpirun with all
ranks on localhost (SURVEY.md §4.5). On TPU CI we want the same multi-party
semantics without processes, so this backend routes Message frames through a
process-local registry keyed by (job_id, rank). Frames still round-trip
through to_bytes()/from_bytes(), so loopback exercises the exact wire path
the gRPC backend uses — a loopback test is a serialization test.
"""

from __future__ import annotations

import threading
from collections import defaultdict

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message

_registry: dict = defaultdict(dict)  # job_id -> {rank: LoopbackCommManager}
_registry_lock = threading.Lock()


class LoopbackCommManager(BaseCommManager):
    backend_name = "loopback"

    def __init__(self, job_id: str, rank: int, size: int):
        super().__init__()
        self.job_id, self.rank, self.size = job_id, rank, size
        with _registry_lock:
            _registry[job_id][rank] = self

    def send_message(self, msg: Message) -> None:
        frame = self._encode(msg)  # force the real wire path (and count it)
        dest = int(msg.get_receiver_id())
        with _registry_lock:
            peer = _registry[self.job_id].get(dest)
        if peer is None:
            raise RuntimeError(f"loopback: rank {dest} not registered in job {self.job_id}")
        peer._receive_frame(frame)

    def stop_receive_message(self) -> None:
        super().stop_receive_message()
        with _registry_lock:
            _registry[self.job_id].pop(self.rank, None)
            if not _registry[self.job_id]:
                _registry.pop(self.job_id, None)
