"""In-process loopback transport — ranks are threads, links are queues.

The reference has no mock transport; its "fake cluster" is mpirun with all
ranks on localhost (SURVEY.md §4.5). On TPU CI we want the same multi-party
semantics without processes, so this backend routes Message frames through a
process-local registry keyed by (job_id, rank). Frames still round-trip
through to_bytes()/from_bytes(), so loopback exercises the exact wire path
the gRPC backend uses — a loopback test is a serialization test.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message

_registry: dict = defaultdict(dict)  # job_id -> {rank: LoopbackCommManager}
_registry_lock = threading.Lock()


class LoopbackCommManager(BaseCommManager):
    backend_name = "loopback"

    # an uplink to an unregistered RANK 0 retries inside this window
    # before failing — the loopback analogue of the gRPC backend's
    # backoff on UNAVAILABLE (docs/ROBUSTNESS.md §Server crash recovery:
    # a client must SURVIVE the server's restart outage, not die on the
    # first refused frame; a supervised in-process restart re-registers
    # rank 0 within milliseconds). Sends to any OTHER unregistered rank
    # fail immediately — the server's elastic machinery owns dead
    # clients, and a retry there would only stall teardown. Either way
    # the failure is a ConnectionError — a transport error the elastic
    # paths tolerate — never an opaque RuntimeError that kills the rank.
    RETRY_WINDOW_S = 3.0
    _RETRY_TICK_S = 0.02

    def __init__(self, job_id: str, rank: int, size: int):
        super().__init__()
        self.job_id, self.rank, self.size = job_id, rank, size
        with _registry_lock:
            _registry[job_id][rank] = self

    def _peer(self, dest: int):
        with _registry_lock:
            return _registry[self.job_id].get(dest)

    def send_message(self, msg: Message) -> None:
        frame = self._encode(msg)  # force the real wire path (and count it)
        dest = int(msg.get_receiver_id())
        peer = self._peer(dest)
        if peer is None and dest == 0:
            deadline = time.monotonic() + self.RETRY_WINDOW_S
            while peer is None and time.monotonic() < deadline:
                time.sleep(self._RETRY_TICK_S)
                peer = self._peer(dest)
        if peer is None:
            raise ConnectionError(
                f"loopback: rank {dest} not registered in job "
                f"{self.job_id}")
        peer._receive_frame(frame)

    def stop_receive_message(self) -> None:
        super().stop_receive_message()
        with _registry_lock:
            _registry[self.job_id].pop(self.rank, None)
            if not _registry[self.job_id]:
                _registry.pop(self.job_id, None)
