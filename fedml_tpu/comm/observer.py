"""Observer — callback interface for inbound messages.

Mirror of fedml_core/distributed/communication/observer.py:4-7.
"""

from __future__ import annotations

import abc


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type: str, msg_params) -> None:
        ...
