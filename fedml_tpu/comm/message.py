"""Typed key-value message envelope with zero-copy array payloads.

Mirror of fedml_core/distributed/communication/message.py:5-74 (Message =
dict of params keyed by type/sender/receiver, carrying model params in-band).

Wire-format redesign: the reference JSON-encodes model weights as nested
python lists for its gRPC/MQTT paths (fedml_api/distributed/fedavg/
utils.py:7-16) and pickles them for MPI — both slow and (pickle) unsafe.
Here the envelope is a self-describing binary frame:

    b"FMT1" | u32 header_len | header(JSON) | raw array buffers...

Scalars ride in the JSON header; every numpy/JAX array (or list of arrays —
the natural shape of a flattened pytree of weights) is shipped as raw
little-endian bytes described by a manifest. Encoding a pytree is
tree_flatten on the sender and unflatten-by-structure on the receiver, so no
class bytecode ever crosses the wire.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

_MAGIC = b"FMT1"


class Message:
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"

    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"

    def __init__(self, type: str = "default", sender_id: int = 0, receiver_id: int = 0):
        self.msg_params: dict[str, Any] = {
            Message.MSG_ARG_KEY_TYPE: type,
            Message.MSG_ARG_KEY_SENDER: sender_id,
            Message.MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    # -------------------------------------------------------- dict interface
    def add_params(self, key: str, value: Any):
        self.msg_params[key] = value

    def get(self, key: str, default=None):
        return self.msg_params.get(key, default)

    def get_type(self) -> str:
        return self.msg_params[Message.MSG_ARG_KEY_TYPE]

    def get_sender_id(self) -> int:
        return self.msg_params[Message.MSG_ARG_KEY_SENDER]

    def get_receiver_id(self) -> int:
        return self.msg_params[Message.MSG_ARG_KEY_RECEIVER]

    def get_params(self) -> dict:
        return self.msg_params

    # ---------------------------------------------------------- wire format
    @staticmethod
    def _as_array(v):
        """numpy view of an array-like leaf (jax.Array included) or None."""
        if isinstance(v, np.ndarray):
            return v
        if hasattr(v, "__array__") and hasattr(v, "dtype") and hasattr(v, "shape"):
            return np.asarray(v)
        return None

    def to_bytes(self) -> bytes:
        scalars: dict[str, Any] = {}
        manifest: list[dict] = []
        buffers: list[bytes] = []

        def put_array(key, idx, arr):
            arr = np.ascontiguousarray(arr)
            manifest.append(
                {"key": key, "idx": idx, "dtype": arr.dtype.str, "shape": list(arr.shape)}
            )
            buffers.append(arr.tobytes())

        for key, val in self.msg_params.items():
            arr = self._as_array(val)
            if arr is not None:
                put_array(key, None, arr)
            elif isinstance(val, (list, tuple)) and val and all(
                self._as_array(v) is not None for v in val
            ):
                for i, v in enumerate(val):
                    put_array(key, i, self._as_array(v))
                scalars["__len_" + key] = len(val)
            else:
                scalars[key] = val

        header = json.dumps({"scalars": scalars, "arrays": manifest}).encode()
        out = [_MAGIC, len(header).to_bytes(4, "little"), header]
        out.extend(buffers)
        return b"".join(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Message":
        if data[:4] != _MAGIC:
            raise ValueError("bad message frame")
        hlen = int.from_bytes(data[4:8], "little")
        header = json.loads(data[8 : 8 + hlen])
        msg = cls.__new__(cls)
        msg.msg_params = {}

        lists: dict[str, int] = {}
        for k, v in header["scalars"].items():
            if k.startswith("__len_"):
                lists[k[len("__len_"):]] = v
            else:
                msg.msg_params[k] = v
        for key, n in lists.items():
            msg.msg_params[key] = [None] * n

        off = 8 + hlen
        for ent in header["arrays"]:
            arr = np.frombuffer(
                data, dtype=np.dtype(ent["dtype"]), count=int(np.prod(ent["shape"], dtype=np.int64)),
                offset=off,
            ).reshape(ent["shape"])
            off += arr.nbytes
            if ent["idx"] is None:
                msg.msg_params[ent["key"]] = arr
            else:
                msg.msg_params[ent["key"]][ent["idx"]] = arr
        return msg

    def __repr__(self):  # message-size print parity (message.py:64)
        return f"Message(type={self.get_type()}, {self.get_sender_id()}->{self.get_receiver_id()})"


def pack_pytree(tree) -> list[np.ndarray]:
    """Flatten a pytree of arrays into wire-ready leaves (sender side)."""
    import jax

    return [np.asarray(v) for v in jax.tree.leaves(tree)]


def unpack_pytree(template, leaves):
    """Rebuild a pytree from wire leaves using the receiver's own structure
    (both sides construct the same model, so no treedef crosses the wire)."""
    import jax

    treedef = jax.tree.structure(template)
    return jax.tree.unflatten(treedef, list(leaves))
