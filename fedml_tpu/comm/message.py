"""Typed key-value message envelope with zero-copy array payloads.

Mirror of fedml_core/distributed/communication/message.py:5-74 (Message =
dict of params keyed by type/sender/receiver, carrying model params in-band).

Wire-format redesign: the reference JSON-encodes model weights as nested
python lists for its gRPC/MQTT paths (fedml_api/distributed/fedavg/
utils.py:7-16) and pickles them for MPI — both slow and (pickle) unsafe.
Here the envelope is a self-describing binary frame:

    b"FMT1" | u32 header_len | header(JSON) | raw array buffers...

Scalars ride in the JSON header; every numpy/JAX array (or list of arrays —
the natural shape of a flattened pytree of weights) is shipped as raw
little-endian bytes described by a manifest. Encoding a pytree is
tree_flatten on the sender and unflatten-by-structure on the receiver, so no
class bytecode ever crosses the wire.

Frame integrity: the binary frame carries a CRC32 of everything after the
checksum field (FMT2). A receiver that computes a mismatch raises
:class:`CorruptFrame`, which the dispatch path (``BaseCommManager.
_receive_frame``) turns into a counted drop (``comm_corrupt_frames_total``)
instead of a crashed receive loop — a flipped bit on the wire degrades one
frame, not the job. Legacy FMT1 frames (no checksum) still decode — the
compatibility is old-sender -> new-receiver only: senders emit FMT2
unconditionally, which a pre-integrity receiver rejects, so upgrade
receivers before (or with) senders. The 'json' interop tier carries no
checksum (a stock reference peer wouldn't know to send one).
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any

import numpy as np

_MAGIC = b"FMT1"   # legacy: no integrity field (still decoded)
_MAGIC2 = b"FMT2"  # FMT2 | u32 header_len | u32 crc32(rest) | header | bufs
_ZMAGIC = b"FMZ1"  # zlib-wrapped frame: FMZ1 | u32 raw_len | deflate bytes


class CorruptFrame(ValueError):
    """A wire frame that failed its integrity check (CRC32 mismatch, bad
    magic, or an undecodable body). Subclasses ValueError so pre-existing
    callers that caught ValueError keep working."""

# Wire codec (sender-side choice; receivers auto-detect, so mixed peers
# interoperate). The reference ships f32 weights as JSON lists — here the
# baseline is already raw binary, and the codec trades further:
#   'f16'  — cast float32 array payloads to float16 on the wire (2x; the
#            classic FL uplink compression; manifest records the original
#            dtype so receivers restore f32 — a ~1e-3-relative quantization
#            of the weights, NOT bit-exact)
#   'q8'   — symmetric int8 quantization of float32 payloads (4x; scale =
#            max|x|/127 per array, kept in the manifest; ~0.4% of the
#            array's max absolute value per entry — the aggressive tier)
#   'zlib' — lossless deflate of the whole frame (big wins on int/uint8
#            payloads and sparse updates; modest on dense f32)
#   '+zlib' composes with either lossy tier. f16 and q8 are mutually
#   exclusive (both re-encode the same f32 payloads).
#   'json' — the REFERENCE's wire format: one UTF-8 JSON object of
#            msg_params with arrays as nested python lists (Message.to_json,
#            message.py:62-66 + transform_tensor_to_list,
#            fedavg/utils.py:13-16, the is_mobile=1 path) — so a stock
#            reference mobile/IoT client can join a fedml_tpu round.
#            Interop tier only: ~7x the bytes of the binary frame.
_CODECS = ("none", "f16", "q8", "zlib", "f16+zlib", "q8+zlib", "json")


def set_wire_codec(codec: str) -> None:
    """Process-wide default codec for Message.to_bytes (one of _CODECS:
    'none', 'f16', 'q8', 'zlib', 'f16+zlib', 'q8+zlib', 'json'). Exposed
    on the CLI as --compression."""
    global _CODEC
    if codec not in _CODECS:
        raise ValueError(f"unknown wire codec {codec!r} (one of {_CODECS})")
    _CODEC = codec


def _codec_from_env() -> str:
    # a typo in the env var must not SILENTLY ship uncompressed frames
    # while the operator believes compression is on — warn and run plain
    v = os.environ.get("FEDML_COMM_CODEC", "none")
    if v not in _CODECS:
        import logging

        logging.getLogger("fedml_tpu.comm").warning(
            "FEDML_COMM_CODEC=%r is not one of %s — using 'none'", v, _CODECS)
        return "none"
    return v


_CODEC = _codec_from_env()


def _f16_wire(arr: np.ndarray) -> np.ndarray:
    """float32 -> its f16 wire form. Saturates at the f16 range: a stray
    huge value (diverging weight, unscaled statistic) must degrade to
    ±65504, not become inf and poison every peer's aggregate."""
    return np.clip(arr, -65504.0, 65504.0).astype(np.float16)


def _q8_wire(arr: np.ndarray) -> tuple[np.ndarray, float]:
    """float32 -> (int8 wire form, scale). Non-finite guard: nan→0 and
    ±inf saturate to the largest FINITE magnitude so one diverged entry
    can't blow the scale up / NaN the decode.

    Policy note: this clamp exists because q8's SCALE computation would
    otherwise be destroyed by a single non-finite entry — it is a codec
    necessity, not a sanitization layer. The plain float paths ('none',
    'f16' pre-clip aside, 'zlib', 'json') deliberately ship the sender's
    bits verbatim: silently laundering a NaN to 0 at unpack time would
    hide a diverging or hostile client from every defense. Non-finite
    uploads are instead REJECTED, counted, and quarantined by the
    aggregation-side sanitation gate (core/robust_agg.sanitize_updates,
    unconditional in FedAvgAggregator.aggregate) — a NaN can reach the
    server, but never ``tree_weighted_mean``, and never unannounced."""
    finite = np.isfinite(arr)
    if not finite.all():
        amax = float(np.max(np.abs(arr[finite]))) if finite.any() else 0.0
        arr = np.nan_to_num(arr, nan=0.0, posinf=amax, neginf=-amax)
    scale = float(np.max(np.abs(arr))) / 127.0 if arr.size else 0.0
    q = (np.zeros(arr.shape, np.int8) if scale == 0.0 else
         np.clip(np.rint(arr / scale), -127, 127).astype(np.int8))
    return q, scale


class Message:
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"

    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"

    # Keys the lossy f16/q8 frame tiers must NEVER re-encode, whatever the
    # process-wide codec says. These are codec/protocol payloads, not model
    # tensors: a sparse top-k value array is EXACTLY what the server adds to
    # its global (quantizing it would silently break the client's error-
    # feedback accounting — the residual assumes what was SENT is what was
    # APPLIED), an update-codec scale vector quantized by q8 corrupts every
    # entry it scales, and a round-delta broadcast must reconstruct the
    # exact base the next uplink delta is computed against. Integer leaves
    # (sparse_idx) dodge the float tiers by dtype today, but are listed so
    # the exemption is a protocol contract, not a dtype accident.
    LOSSY_EXEMPT = frozenset({
        "sparse_idx", "sparse_val",          # comm/sparse.py top-k uplinks
        "upd_q", "upd_scale",                # comm/delta.py update tiers
        "delta_params",                      # round-delta broadcast payload
    })

    def __init__(self, type: str = "default", sender_id: int = 0, receiver_id: int = 0):
        self.msg_params: dict[str, Any] = {
            Message.MSG_ARG_KEY_TYPE: type,
            Message.MSG_ARG_KEY_SENDER: sender_id,
            Message.MSG_ARG_KEY_RECEIVER: receiver_id,
        }
        # per-message additions to LOSSY_EXEMPT (mark_lossless): e.g. the
        # delta-broadcast protocol's dense fallback, whose model_params must
        # land bit-exact so every rank holds the same base chain value
        self._lossless_keys: set[str] = set()

    # -------------------------------------------------------- dict interface
    def add_params(self, key: str, value: Any):
        self.msg_params[key] = value

    def mark_lossless(self, key: str) -> None:
        """Exempt ``key``'s array payload from the lossy f16/q8 frame
        tiers on THIS message (zlib still applies — it is lossless)."""
        self._lossless_keys.add(key)

    def get(self, key: str, default=None):
        return self.msg_params.get(key, default)

    def get_type(self) -> str:
        return self.msg_params[Message.MSG_ARG_KEY_TYPE]

    def get_sender_id(self) -> int:
        return self.msg_params[Message.MSG_ARG_KEY_SENDER]

    def get_receiver_id(self) -> int:
        return self.msg_params[Message.MSG_ARG_KEY_RECEIVER]

    def get_params(self) -> dict:
        return self.msg_params

    # ---------------------------------------------------------- wire format
    @staticmethod
    def _as_array(v):
        """numpy view of an array-like leaf (jax.Array included) or None."""
        if isinstance(v, np.ndarray):
            return v
        if hasattr(v, "__array__") and hasattr(v, "dtype") and hasattr(v, "shape"):
            return np.asarray(v)
        return None

    def to_bytes(self, codec: str | None = None) -> bytes:
        codec = _CODEC if codec is None else codec
        if codec not in _CODECS:
            raise ValueError(f"unknown wire codec {codec!r} (one of {_CODECS})")
        if codec == "json":
            return self._to_reference_json()
        f16, q8 = "f16" in codec, "q8" in codec
        scalars: dict[str, Any] = {}
        manifest: list[dict] = []
        buffers: list[bytes] = []
        # protocol payloads the lossy tiers must not touch (class contract
        # + per-message mark_lossless; getattr: a Message rebuilt by
        # from_bytes and re-encoded — chaos duplicates — has no set)
        exempt = self.LOSSY_EXEMPT | getattr(self, "_lossless_keys", set())

        def put_array(key, idx, arr):
            arr = np.ascontiguousarray(arr)
            ent = {"key": key, "idx": idx, "dtype": arr.dtype.str,
                   "shape": list(arr.shape)}
            if key in exempt:
                pass  # verbatim bits, whatever the frame codec says
            elif f16 and arr.dtype == np.float32:
                ent["orig"], ent["dtype"] = arr.dtype.str, "<f2"
                arr = _f16_wire(arr)
            elif q8 and arr.dtype == np.float32:
                ent["orig"], ent["dtype"] = arr.dtype.str, "|i1"
                arr, ent["scale"] = _q8_wire(arr)
            manifest.append(ent)
            buffers.append(arr.tobytes())

        for key, val in self.msg_params.items():
            arr = self._as_array(val)
            if arr is not None:
                put_array(key, None, arr)
            elif isinstance(val, (list, tuple)) and val and all(
                self._as_array(v) is not None for v in val
            ):
                for i, v in enumerate(val):
                    put_array(key, i, self._as_array(v))
                scalars["__len_" + key] = len(val)
            else:
                scalars[key] = val

        header = json.dumps({"scalars": scalars, "arrays": manifest}).encode()
        body = b"".join([header] + buffers)
        # crc covers header + payload (everything after the crc field):
        # one pass over bytes already in cache — the only per-frame work
        # the integrity layer adds to the no-chaos hot path
        frame = b"".join([_MAGIC2, len(header).to_bytes(4, "little"),
                          (zlib.crc32(body) & 0xFFFFFFFF).to_bytes(4, "little"),
                          body])
        if "zlib" in codec:
            frame = (_ZMAGIC + len(frame).to_bytes(4, "little")
                     + zlib.compress(frame, 1))  # level 1: wire CPU is cheap
        return frame

    def _to_reference_json(self) -> bytes:
        """The reference's wire form: json.dumps(msg_params) with every
        array payload as nested lists (message.py:62-66 to_json; weights
        listified per transform_tensor_to_list, fedavg/utils.py:13-16).

        Decode-symmetry extension (ADVICE r5 item 1): the frame also carries
        an ``__arrays__`` manifest naming every top-level key that was
        listified, with its dtype(s) — so ``_from_reference_json`` can
        restore ndarrays for EVERY protocol's array params (split_nn
        acts/grads, fedgkt feats/logits, sparse idx/val...), not just
        ``model_params``, and with the sender's dtype instead of a blanket
        float32. A stock reference peer ignores the extra key (its decode
        is a plain json.loads into the params dict), so interop holds; a
        stock reference SENDER omits it and we fall back to the
        ``model_params``-only heuristic arrify below."""
        manifest: dict[str, Any] = {}

        def listify(v):
            arr = self._as_array(v)
            if arr is not None:
                return arr.tolist()
            if isinstance(v, (list, tuple)):
                return [listify(e) for e in v]
            if isinstance(v, dict):
                return {k: listify(e) for k, e in v.items()}
            return v

        doc: dict[str, Any] = {}
        for k, v in self.msg_params.items():
            arr = self._as_array(v)
            if arr is not None:
                doc[k] = arr.tolist()
                manifest[k] = arr.dtype.str
            elif isinstance(v, (list, tuple)) and v and all(
                self._as_array(e) is not None for e in v
            ):
                arrs = [self._as_array(e) for e in v]
                doc[k] = [a.tolist() for a in arrs]
                manifest[k] = [a.dtype.str for a in arrs]
            elif isinstance(v, dict) and v and all(
                self._as_array(e) is not None for e in v.values()
            ):  # state_dict shape: key -> one tensor
                arrs2 = {k2: self._as_array(e) for k2, e in v.items()}
                doc[k] = {k2: a.tolist() for k2, a in arrs2.items()}
                manifest[k] = {k2: a.dtype.str for k2, a in arrs2.items()}
            else:
                doc[k] = listify(v)
        if manifest:
            doc["__arrays__"] = manifest
        return json.dumps(doc).encode()

    # reference integer msg types (fedavg/message_define.py:6-11) -> the
    # string vocabulary fedml_tpu managers register handlers under
    # (distributed/fedavg/message_define.py) — without this translation a
    # stock reference client's upload would parse but never dispatch
    _REFERENCE_MSG_TYPES = {1: "s2c_init", 2: "s2c_sync",
                            3: "c2s_send_model", 4: "c2s_send_stats"}

    # Decode-symmetry fallback for manifest-LESS json frames (ADVICE r5
    # item 1): ``to_bytes('json')`` listifies EVERY array param, so a
    # receiver must restore ndarrays for every protocol's array keys, not
    # just ``model_params`` — otherwise --compression json hands split_nn/
    # fedgkt/vfl handlers nested python lists. fedml_tpu senders attach the
    # ``__arrays__`` manifest (exact keys + dtypes, handled above); this
    # table covers frames from stock peers that don't. Values are
    # (wire dtype, kind): 'leaves' = a LIST of tensors (pack_pytree shape —
    # nested-list depth is per-tensor), 'array' = ONE tensor however deep
    # its nesting. Dtypes are the senders' conventional ones — best-effort
    # by construction (the manifest path is the exact one).
    _KNOWN_ARRAY_KEYS = {
        "model_params": ("<f4", "leaves"),   # fedavg weights
        "params": ("<f4", "leaves"),         # vfl final host params
        "sparse_idx": ("<i4", "leaves"),     # comm/sparse top-k uplinks
        "sparse_val": ("<f4", "leaves"),
        "upd_q": ("|u1", "leaves"),          # comm/delta quantized payloads
        "upd_scale": ("<f4", "array"),       # comm/delta per-leaf scales
        "delta_params": ("<f4", "leaves"),   # round-delta broadcast
        "acts": ("<f4", "array"),            # split_nn activations
        "grads": ("<f4", "array"),           # split_nn / vfl cotangents
        "feats": ("<f4", "array"),           # fedgkt features
        "s_logits": ("<f4", "array"),        # fedgkt server logits
        "c_logits": ("<f4", "array"),        # fedgkt client logits
        "logits": ("<f4", "array"),          # vfl host logit contribution
        "labels": ("<i8", "array"),
        "mask": ("<f4", "array"),
        "sel": ("<i8", "array"),             # vfl batch index selection
    }

    @classmethod
    def _from_reference_json(cls, data: bytes) -> "Message":
        msg = cls.__new__(cls)
        msg.msg_params = json.loads(data)
        t = msg.msg_params.get(Message.MSG_ARG_KEY_TYPE)
        if isinstance(t, int):
            msg.msg_params[Message.MSG_ARG_KEY_TYPE] = \
                cls._REFERENCE_MSG_TYPES.get(t, str(t))

        manifest = msg.msg_params.pop("__arrays__", None)
        if manifest is not None:
            # fedml_tpu sender: restore ndarrays (with the sender's dtype)
            # for exactly the keys it listified — symmetric for every
            # protocol's array params, not just model_params
            for k, spec in manifest.items():
                v = msg.msg_params.get(k)
                if v is None:
                    continue
                if isinstance(spec, list):  # list-of-arrays payload
                    msg.msg_params[k] = [np.asarray(e, np.dtype(d))
                                         for e, d in zip(v, spec)]
                elif isinstance(spec, dict):  # state_dict-shaped payload
                    msg.msg_params[k] = {k2: np.asarray(v[k2], np.dtype(d))
                                         for k2, d in spec.items()}
                else:
                    msg.msg_params[k] = np.asarray(v, np.dtype(spec))
            return msg

        def arrify(v, dtype, kind):  # transform_list_to_tensor analogue
            if isinstance(v, dict):
                # reference state_dict shape: key -> ONE tensor as nested
                # lists, however deep
                return {k: np.asarray(e, dtype) for k, e in v.items()}
            if kind == "leaves" and isinstance(v, list) and v \
                    and isinstance(v[0], list):
                # fedml_tpu pack_pytree shape: a LIST of tensors
                return [np.asarray(e, dtype) for e in v]
            if isinstance(v, list):
                return np.asarray(v, dtype)
            return v

        # stock sender (no manifest): restore every KNOWN array-valued key
        # of the protocol vocabulary (fedavg weights, split_nn acts/grads,
        # fedgkt feats/logits, vfl sel, sparse idx/val) instead of only
        # model_params — the decode-asymmetry fix for interop frames
        for k, (dtype, kind) in cls._KNOWN_ARRAY_KEYS.items():
            if k in msg.msg_params:
                msg.msg_params[k] = arrify(msg.msg_params[k],
                                           np.dtype(dtype), kind)
        return msg

    @classmethod
    def from_bytes(cls, data: bytes) -> "Message":
        if data[:1] == b"{":  # auto-detect: reference-format JSON peer
            return cls._from_reference_json(data)
        if data[:4] == _ZMAGIC:  # auto-detect: sender chose zlib
            # raw_len (bytes 4:8) is advisory; zlib integrity-checks itself
            try:
                data = zlib.decompress(data[8:])
            except zlib.error as e:  # deflate stream damaged in transit
                raise CorruptFrame(f"zlib frame failed to inflate: {e}")
        if data[:4] == _MAGIC2:
            body_off = 12
            crc = int.from_bytes(data[8:12], "little")
            if zlib.crc32(data[12:]) & 0xFFFFFFFF != crc:
                raise CorruptFrame("frame CRC32 mismatch")
        elif data[:4] == _MAGIC:  # legacy peer: no integrity field
            body_off = 8
        else:
            raise CorruptFrame("bad message frame")
        hlen = int.from_bytes(data[4:8], "little")
        header = json.loads(data[body_off : body_off + hlen])
        msg = cls.__new__(cls)
        msg.msg_params = {}

        lists: dict[str, int] = {}
        for k, v in header["scalars"].items():
            if k.startswith("__len_"):
                lists[k[len("__len_"):]] = v
            else:
                msg.msg_params[k] = v
        for key, n in lists.items():
            msg.msg_params[key] = [None] * n

        off = body_off + hlen
        for ent in header["arrays"]:
            arr = np.frombuffer(
                data, dtype=np.dtype(ent["dtype"]), count=int(np.prod(ent["shape"], dtype=np.int64)),
                offset=off,
            ).reshape(ent["shape"])
            off += arr.nbytes
            if "scale" in ent:  # q8: dequantize back to the sender's dtype
                arr = (arr.astype(np.dtype(ent["orig"]))
                       * np.dtype(ent["orig"]).type(ent["scale"]))
            elif "orig" in ent:  # f16-on-the-wire: restore the dtype
                arr = arr.astype(np.dtype(ent["orig"]))
            if ent["idx"] is None:
                msg.msg_params[ent["key"]] = arr
            else:
                msg.msg_params[ent["key"]][ent["idx"]] = arr
        return msg

    def __repr__(self):  # message-size print parity (message.py:64)
        return f"Message(type={self.get_type()}, {self.get_sender_id()}->{self.get_receiver_id()})"


def codec_roundtrip(leaves, codec: str | None = None) -> list:
    """The lossy transform each float32 array experiences on the wire under
    ``codec`` (encode then decode), without building a frame — identity for
    lossless codecs.

    A server that stashes its broadcast pack to densify sparse client
    deltas must stash THIS, not the pre-codec arrays: clients compute their
    delta against the broadcast they RECEIVED (the decoded, lossy copy), so
    densifying against the exact pack would add an untracked
    ``g_exact - g_lossy`` offset to every transmitted entry each round and
    break the ratio=1.0 dense-equivalence contract. Built from the same
    ``_f16_wire``/``_q8_wire`` helpers ``to_bytes`` encodes with, and the
    same f32*f32(scale) dequant ``from_bytes`` applies."""
    codec = _CODEC if codec is None else codec
    if codec not in _CODECS:
        raise ValueError(f"unknown wire codec {codec!r} (one of {_CODECS})")
    f16, q8 = "f16" in codec, "q8" in codec
    if not (f16 or q8):
        return list(leaves)
    out = []
    for arr in leaves:
        arr = np.asarray(arr)
        if arr.dtype != np.float32:
            out.append(arr)
            continue
        if f16:
            arr = _f16_wire(arr).astype(np.float32)
        else:
            q, scale = _q8_wire(arr)
            arr = q.astype(np.float32) * np.float32(scale)
        out.append(arr)
    return out


def pack_pytree(tree) -> list[np.ndarray]:
    """Flatten a pytree of arrays into wire-ready leaves (sender side)."""
    import jax

    return [np.asarray(v) for v in jax.tree.leaves(tree)]


def unpack_pytree(template, leaves):
    """Rebuild a pytree from wire leaves using the receiver's own structure
    (both sides construct the same model, so no treedef crosses the wire)."""
    import jax

    treedef = jax.tree.structure(template)
    return jax.tree.unflatten(treedef, list(leaves))
