"""Abstract communication backend API.

Mirror of fedml_core/distributed/communication/base_com_manager.py:7-27,
with one behavioral fix: the reference's MPI manager polls its receive queue
with a 0.3 s sleep (mpi/com_manager.py:71-78), which puts a 0.3 s floor under
every round. Backends here block on the queue instead, so message dispatch
latency is microseconds.
"""

from __future__ import annotations

import abc
import queue
import threading
import time
from typing import TYPE_CHECKING

from fedml_tpu.obs import comm_instrument as _obs

if TYPE_CHECKING:
    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.observer import Observer


class BaseCommManager(abc.ABC):
    # wire-accounting label (obs/comm_instrument); backends override
    backend_name = "base"

    def __init__(self):
        self._observers: list["Observer"] = []
        # (message, enqueue-time) pairs: the dispatch loop reports how long
        # each decoded message waited before its handler ran
        self._q: "queue.Queue[tuple[Message, float]]" = queue.Queue()
        self._running = threading.Event()

    # ------------------------------------------------------------- interface
    @abc.abstractmethod
    def send_message(self, msg: "Message") -> None:
        ...

    def add_observer(self, observer: "Observer") -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: "Observer") -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        """Dispatch loop: block on the inbound queue, notify observers.

        Returns when stop_receive_message() is called.
        """
        self._running.set()
        while self._running.is_set():
            try:
                msg, t_in = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            _obs.record_dispatch_latency(self.backend_name,
                                         time.perf_counter() - t_in)
            self._notify(msg)

    def stop_receive_message(self) -> None:
        self._running.clear()

    # -------------------------------------------------------------- plumbing
    def _encode(self, msg: "Message", codec: str | None = None) -> bytes:
        """Serialize an outgoing message through the wire codec, recording
        messages/bytes-per-codec into the process metrics registry. Every
        backend's send path routes through here so loopback, gRPC, and MQTT
        report identically.

        Direction split: frames addressed TO rank 0 are uplink, everything
        else downlink (rank 0 is the server in every protocol here), so
        ``comm_bytes_total{codec,direction}`` separates the broadcast-
        dominated downlink from the uplink byte budget the delta/quantized
        tiers optimize. The codec label is the EFFECTIVE tier — the
        update codec riding the message (top-k / comm/delta.py tiers)
        composed with the frame codec — not just the frame codec."""
        from fedml_tpu.comm import message as _message

        frame = msg.to_bytes(codec)
        frame_codec = codec if codec is not None else _message._CODEC
        _obs.record_send(self.backend_name, frame_codec,
                         len(frame), str(msg.get_type()))
        params = msg.get_params()
        upd = params.get("upd_codec")
        if upd is None and "sparse_idx" in params:
            upd = "topk"
        if upd is None and "delta_params" in params:
            upd = "delta-bcast"  # round-delta downlink (server side)
        eff = (frame_codec if upd is None
               else str(upd) if frame_codec == "none"
               else f"{upd}+{frame_codec}")
        # protocol frames with a registered override (e2s_evidence /
        # s2e_verdict — the cross-tier robust control plane) are accounted
        # under their own direction label so their byte budget is
        # separable from the update-frame traffic they exist to bound
        direction = _obs.direction_override(msg.get_type())
        if direction is None:
            try:
                direction = ("uplink" if int(msg.get_receiver_id()) == 0
                             else "downlink")
            except (TypeError, ValueError, KeyError):
                direction = "downlink"  # interop peers with exotic ids
        _obs.record_wire_bytes(eff, direction, len(frame))
        return frame

    def _receive_frame(self, data: bytes) -> None:
        """Decode an inbound frame, record its size, and enqueue it for the
        dispatch loop — the shared receive half of ``_encode``.

        A frame that fails to decode — CRC32 mismatch (message.py FMT2),
        bad magic, damaged deflate stream, or any downstream parse error a
        flipped bit can cause (CorruptFrame and the json/frombuffer errors
        are ValueError; a truncated header manifest raises KeyError) — is
        dropped and counted (``comm_corrupt_frames_total``), never raised:
        wire damage must degrade one frame, not kill the transport's
        receive thread and wedge the job. Only those two exception types
        are absorbed — a genuine programming error in the decode path
        still fails fast (the same rationale as ``_notify``'s re-raise)."""
        from fedml_tpu.comm.message import Message

        _obs.record_receive(self.backend_name, len(data))
        try:
            msg = Message.from_bytes(data)
        except (ValueError, KeyError):
            _obs.record_corrupt_frame(self.backend_name)
            import logging

            logging.getLogger("fedml_tpu.comm").warning(
                "dropping corrupt %d-byte frame", len(data), exc_info=True)
            return
        # liveness: a decoded frame proves its sender alive — feeds the
        # fed_last_heartbeat_age_seconds{rank} gauges on every transport
        _obs.record_rank_seen(msg.get_params().get("sender"))
        self._enqueue(msg)

    def _enqueue(self, msg: "Message") -> None:
        self._q.put((msg, time.perf_counter()))

    def _notify(self, msg: "Message") -> None:
        for obs in list(self._observers):
            try:
                obs.receive_message(msg.get_type(), msg.get_params())
            except Exception:
                # log with traceback THEN re-raise: a silently swallowed
                # handler error turns protocol bugs into eternal hangs, and a
                # silently dead loop does too. Re-raising fails the server's
                # run() fast (the reference's MPI.Abort analogue) while the
                # log names the culprit; client daemon threads die visibly.
                import logging

                logging.getLogger("fedml_tpu.comm").exception(
                    "handler for msg_type=%s raised", msg.get_type())
                raise
