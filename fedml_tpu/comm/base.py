"""Abstract communication backend API.

Mirror of fedml_core/distributed/communication/base_com_manager.py:7-27,
with one behavioral fix: the reference's MPI manager polls its receive queue
with a 0.3 s sleep (mpi/com_manager.py:71-78), which puts a 0.3 s floor under
every round. Backends here block on the queue instead, so message dispatch
latency is microseconds.
"""

from __future__ import annotations

import abc
import queue
import threading
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.observer import Observer


class BaseCommManager(abc.ABC):
    def __init__(self):
        self._observers: list["Observer"] = []
        self._q: "queue.Queue[Message]" = queue.Queue()
        self._running = threading.Event()

    # ------------------------------------------------------------- interface
    @abc.abstractmethod
    def send_message(self, msg: "Message") -> None:
        ...

    def add_observer(self, observer: "Observer") -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: "Observer") -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        """Dispatch loop: block on the inbound queue, notify observers.

        Returns when stop_receive_message() is called.
        """
        self._running.set()
        while self._running.is_set():
            try:
                msg = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            self._notify(msg)

    def stop_receive_message(self) -> None:
        self._running.clear()

    # -------------------------------------------------------------- plumbing
    def _enqueue(self, msg: "Message") -> None:
        self._q.put(msg)

    def _notify(self, msg: "Message") -> None:
        for obs in list(self._observers):
            try:
                obs.receive_message(msg.get_type(), msg.get_params())
            except Exception:
                # log with traceback THEN re-raise: a silently swallowed
                # handler error turns protocol bugs into eternal hangs, and a
                # silently dead loop does too. Re-raising fails the server's
                # run() fast (the reference's MPI.Abort analogue) while the
                # log names the culprit; client daemon threads die visibly.
                import logging

                logging.getLogger("fedml_tpu.comm").exception(
                    "handler for msg_type=%s raised", msg.get_type())
                raise
