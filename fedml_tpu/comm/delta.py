"""Round-delta + quantized update codecs — uplink bytes as a perf budget.

The reference framework ships the full dense f32 model on every upload;
at fleet fan-in the uplink — not FLOPs — is the binding constraint on
rounds/second (FedJAX arXiv:2108.02117 treats client payload size as the
population-scaling lever; the smart-NIC FL-server study arXiv:2307.06561
shows server ingest bandwidth bounding the round). This module owns the
wire form of the *update* tiers (docs/PERFORMANCE.md §Wire efficiency):

- ``delta``       — ``local - global@version`` as raw f32. Lossless; wins
                    only through frame-level deflate (near-converged
                    deltas are small and low-entropy) but establishes the
                    versioned-base protocol the lossy tiers ride.
- ``delta-int8``  — symmetric per-tensor int8 (scale = max|d|/127) with a
                    DGC-style deadzone: entries below
                    ``deadzone * rms(d)`` are withheld to the
                    error-feedback residual (comm/ef.py) and shipped as
                    zeros, which is what makes the int8 stream deflate —
                    the tier deflates its own payload, so the ~4x from
                    quantization compounds with the zero-run entropy win
                    (>= 8x uplink vs dense f32, bench-asserted).
- ``delta-sign1`` — 1-bit scaled sign (scale = mean|d|, signs packed 8/
                    byte): ~32x before headers. The server decodes every
                    client's signs to ±scale f32 and hands them to the
                    SAME weighted ``gated_aggregate`` path as dense
                    uploads, which IS scaled-sign aggregation — no new
                    server math, and the PR-4 sanitation gate still fronts
                    it.

Versioned bases: a delta is meaningless without the exact base it was
computed against. Every encoded update travels with the round/version tag
of the broadcast the dispatch carried, and the server densifies against
its per-version broadcast stash — which is what lets sparsified/quantized
uplinks compose with buffered-async dispatch waves (the PR-8 refusal is
lifted; only a genuinely unversioned base stays a loud error).

Poison policy (PR-4): quantization cannot represent a NaN, but it must
not LAUNDER one either — a non-finite input leaf encodes with a NaN
scale, so the server-side decode is non-finite everywhere and dies at the
sanitation gate exactly like a dense NaN upload would. Corrupt scales and
chaos bit-flips that survive CRC land in the same place: garbage decodes
to garbage values, and the gate — not the codec — quarantines them.

Leaf convention (shared with comm/sparse.py and comm/ef.py): floating
leaves participate; integer leaves ship dense (payload = the leaf
verbatim, scale slot 0) and ``apply_delta`` REPLACES the base with them.
"""

from __future__ import annotations

import zlib

import numpy as np

UPDATE_CODECS = ("delta", "delta-int8", "delta-sign1")

# Deadzone (delta-int8 only), in units of the compensated delta's RMS:
# entries below it are withheld to the EF residual and shipped as zero.
# 1.5 RMS keeps ~10-15% of a Gaussian-shaped delta per round (EF ships the
# rest later, same convergence contract as top-k) and turns the int8
# stream into mostly zero runs — the deflate win the >= 8x budget needs.
DEADZONE_DEFAULT = 1.5


class CorruptPayload(ValueError):
    """A structurally-undecodable update payload (truncated deflate
    stream, size mismatch vs the model template). ValueError so the
    server's decode guard can catch it alongside numpy's own."""


def _is_float(arr) -> bool:
    return np.issubdtype(np.asarray(arr).dtype, np.floating)


def round_delta(local_leaves, base_leaves) -> list:
    """``local - base`` per float leaf (f32); non-float leaves pass
    through VERBATIM (they ship dense — ``apply_delta`` replaces)."""
    out = []
    for w, g in zip(local_leaves, base_leaves):
        w = np.asarray(w)
        if not _is_float(w):
            out.append(w)
            continue
        out.append(np.asarray(w, np.float32) - np.asarray(g, np.float32))
    return out


def apply_delta(base_leaves, delta_leaves) -> list:
    """Server side: ``base + delta`` per float leaf (the client's
    effective model, ready for the unchanged weighted aggregator);
    non-float delta entries REPLACE the base (dense convention)."""
    out = []
    for g, d in zip(base_leaves, delta_leaves):
        g = np.asarray(g)
        d = np.asarray(d)
        if not _is_float(g):
            out.append(d.reshape(g.shape))
            continue
        out.append((np.asarray(g, np.float32)
                    + np.asarray(d, np.float32)).astype(g.dtype))
    return out


# ------------------------------------------------------------- leaf codecs
def _q8_leaf(d: np.ndarray, deadzone: float) -> tuple[np.ndarray, float]:
    """One float leaf -> (deflated int8 bytes as uint8, f32 scale)."""
    d = np.asarray(d, np.float32).ravel()
    if d.size and not np.isfinite(d).all():
        # poison, not launder: a NaN scale makes the DECODE non-finite
        # everywhere, so the sanitation gate sees it (module docstring)
        q = np.zeros(d.size, np.int8)
        scale = float("nan")
    else:
        if deadzone > 0.0 and d.size:
            rms = float(np.sqrt(np.mean(d * d)))
            amax0 = float(np.max(np.abs(d)))
            if rms > 0.0:
                # cap the threshold at the leaf's own max magnitude: for a
                # single-element or uniform-|d| leaf, |d| == rms <
                # deadzone*rms would otherwise hold FOREVER (EF rescales
                # the compensated delta and the ratio with it), silently
                # freezing that parameter while the residual grows without
                # bound — the top entries must always be transmittable
                tau = min(deadzone * rms, amax0)
                d = np.where(np.abs(d) >= tau, d, 0.0).astype(np.float32)
        amax = float(np.max(np.abs(d))) if d.size else 0.0
        scale = amax / 127.0
        q = (np.zeros(d.size, np.int8) if scale == 0.0 else
             np.clip(np.rint(d / scale), -127, 127).astype(np.int8))
    payload = np.frombuffer(zlib.compress(q.tobytes(), 6), np.uint8)
    return payload, scale


def _q8_leaf_decode(payload, scale, template: np.ndarray) -> np.ndarray:
    try:
        raw = zlib.decompress(np.asarray(payload, np.uint8).tobytes())
    except zlib.error as e:
        raise CorruptPayload(f"int8 payload failed to inflate: {e}")
    q = np.frombuffer(raw, np.int8)
    if q.size != template.size:
        raise CorruptPayload(
            f"int8 payload has {q.size} entries, model leaf has "
            f"{template.size}")
    return (q.astype(np.float32) * np.float32(scale)) \
        .reshape(template.shape)


def _sign_leaf(d: np.ndarray) -> tuple[np.ndarray, float]:
    """One float leaf -> (packed sign bits, f32 scale = mean|d|)."""
    d = np.asarray(d, np.float32).ravel()
    if d.size and not np.isfinite(d).all():
        return np.packbits(np.zeros(d.size, bool)), float("nan")
    scale = float(np.mean(np.abs(d))) if d.size else 0.0
    return np.packbits(d >= 0.0), scale


def _sign_leaf_decode(payload, scale, template: np.ndarray) -> np.ndarray:
    bits = np.unpackbits(np.asarray(payload, np.uint8))
    if bits.size < template.size:
        raise CorruptPayload(
            f"sign payload has {bits.size} bits, model leaf has "
            f"{template.size}")
    s = np.float32(scale)
    return np.where(bits[: template.size].astype(bool), s, -s) \
        .astype(np.float32).reshape(template.shape)


# ----------------------------------------------------------- tier encoders
def encode_update(delta_leaves, codec: str,
                  deadzone: float = DEADZONE_DEFAULT
                  ) -> tuple[list, np.ndarray]:
    """Encode (already EF-compensated) delta leaves under ``codec``.

    Returns ``(payload, scales)``: one payload array per leaf (deflated
    int8 bytes / packed sign bits / raw f32 delta / dense non-float leaf)
    and a per-leaf f32 scale vector (0 for lossless and dense leaves; NaN
    marks a non-finite input — see the poison policy in the module doc).
    Both ride the frame LOSSLESS (comm/message.py exempts the update keys
    from the lossy f16/q8 frame tiers — a quantized scale would corrupt
    every entry it scales)."""
    if codec not in UPDATE_CODECS:
        raise ValueError(
            f"unknown update codec {codec!r} (one of {UPDATE_CODECS})")
    payload: list = []
    scales = np.zeros(len(delta_leaves), np.float32)
    for i, d in enumerate(delta_leaves):
        d = np.asarray(d)
        if not _is_float(d):
            payload.append(d)  # dense passthrough, scale slot stays 0
            continue
        if codec == "delta":
            payload.append(np.asarray(d, np.float32))
        elif codec == "delta-int8":
            p, s = _q8_leaf(d, deadzone)
            payload.append(p)
            scales[i] = s
        else:  # delta-sign1
            p, s = _sign_leaf(d)
            payload.append(p)
            scales[i] = s
    return payload, scales


def decode_update(payload, scales, codec: str, template_leaves) -> list:
    """Server side: payload + scales -> delta leaves (f32 for float
    leaves; dense non-float leaves verbatim), shaped by the receiver's
    own model template — no shapes cross the wire. Raises
    :class:`CorruptPayload` on structural garbage (the server maps it to
    an ``undecodable`` quarantine, never a crashed receive loop); VALUE
    garbage (corrupt scale, bit-flipped payload) decodes to values the
    sanitation gate judges."""
    if codec not in UPDATE_CODECS:
        raise ValueError(
            f"unknown update codec {codec!r} (one of {UPDATE_CODECS})")
    if len(payload) != len(template_leaves) or \
            len(np.atleast_1d(scales)) != len(template_leaves):
        raise CorruptPayload(
            f"update payload has {len(payload)} leaves / "
            f"{len(np.atleast_1d(scales))} scales, model has "
            f"{len(template_leaves)}")
    scales = np.atleast_1d(np.asarray(scales, np.float32))
    out = []
    for p, s, t in zip(payload, scales, template_leaves):
        t = np.asarray(t)
        if not _is_float(t):
            out.append(np.asarray(p).reshape(t.shape))
            continue
        if codec == "delta":
            p = np.asarray(p, np.float32)
            if p.size != t.size:
                raise CorruptPayload(
                    f"delta leaf has {p.size} entries, model leaf has "
                    f"{t.size}")
            out.append(p.reshape(t.shape))
        elif codec == "delta-int8":
            out.append(_q8_leaf_decode(p, s, t))
        else:
            out.append(_sign_leaf_decode(p, s, t))
    return out


def inflate_update(payload, scales, codec: str,
                   template_leaves) -> tuple[list, np.ndarray]:
    """Structural half of :func:`decode_update` for the fused on-device
    server path (docs/PERFORMANCE.md §Fused aggregation): validate the
    payload's structure and return the RAW quantized per-leaf arrays —
    inflated flat int8 for ``delta-int8`` (zlib cannot run in a jit, and
    int8 is 4x smaller than the f32 tree the stacked path materializes),
    packed sign BYTES for ``delta-sign1``, flat f32 deltas for ``delta``,
    dense non-float leaves verbatim — ready for the on-device densify in
    ``core/fused_agg.py``. VALUE garbage still flows through (a NaN scale
    decodes non-finite on device and dies at the in-graph gate);
    structural garbage raises :class:`CorruptPayload` exactly like
    :func:`decode_update`."""
    if codec not in UPDATE_CODECS:
        raise ValueError(
            f"unknown update codec {codec!r} (one of {UPDATE_CODECS})")
    if len(payload) != len(template_leaves) or \
            len(np.atleast_1d(scales)) != len(template_leaves):
        raise CorruptPayload(
            f"update payload has {len(payload)} leaves / "
            f"{len(np.atleast_1d(scales))} scales, model has "
            f"{len(template_leaves)}")
    scales = np.atleast_1d(np.asarray(scales, np.float32))
    out = []
    for p, t in zip(payload, template_leaves):
        t = np.asarray(t)
        if not _is_float(t):
            p = np.asarray(p)
            if p.size != t.size:
                # the fused densify reshapes on device — a wrong-sized
                # dense leaf must die HERE as structural garbage, not as
                # a trace error inside the server's receive loop
                raise CorruptPayload(
                    f"dense leaf has {p.size} entries, model leaf has "
                    f"{t.size}")
            out.append(p)
            continue
        if codec == "delta":
            p = np.asarray(p, np.float32)
            if p.size != t.size:
                raise CorruptPayload(
                    f"delta leaf has {p.size} entries, model leaf has "
                    f"{t.size}")
            out.append(p.reshape(-1))
        elif codec == "delta-int8":
            try:
                raw = zlib.decompress(np.asarray(p, np.uint8).tobytes())
            except zlib.error as e:
                raise CorruptPayload(f"int8 payload failed to inflate: {e}")
            q = np.frombuffer(raw, np.int8)
            if q.size != t.size:
                raise CorruptPayload(
                    f"int8 payload has {q.size} entries, model leaf has "
                    f"{t.size}")
            out.append(q)
        else:  # delta-sign1
            p = np.asarray(p, np.uint8)
            if p.size * 8 < t.size:
                raise CorruptPayload(
                    f"sign payload has {p.size * 8} bits, model leaf has "
                    f"{t.size}")
            out.append(p)
    return out, scales


def payload_nbytes(payload, scales) -> int:
    """Wire-payload bytes of one encoded update (tests/bench evidence)."""
    return int(sum(np.asarray(p).nbytes for p in payload)
               + np.asarray(scales).nbytes)
