"""Client/Server manager base classes — handler registry + dispatch loop.

Mirror of fedml_core/distributed/client/client_manager.py:13-73 and
.../server/server_manager.py:13-68: a manager owns a comm backend, registers
per-msg_type callbacks, and runs the receive loop.

Differences from the reference (deliberate):
- Backend switch offers loopback/grpc/mqtt (no MPI — SURVEY.md §2.8: on-TPU
  transport is XLA collectives; this layer is inter-job only).
- finish() shuts the transport down cleanly instead of
  MPI.COMM_WORLD.Abort() (client_manager.py:66-73) which nukes every rank.
- A watchdog thread (failure detection — absent in the reference, SURVEY.md
  §5) calls ``on_timeout`` if no message arrives for ``timeout_s``, so a
  dead peer surfaces as a callback instead of an eternal hang.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.observer import Observer
from fedml_tpu.obs import comm_instrument as _obs

log = logging.getLogger("fedml_tpu.comm.managers")


def make_comm_manager(backend: str, rank: int, size: int, **kw) -> BaseCommManager:
    """Backend switch (parity with client_manager.py:20-32).

    When a chaos FaultPlan is installed (fedml_tpu/chaos — seeded
    deterministic fault injection for robustness tests and soak runs), the
    manager comes back wrapped in a ChaosCommManager executing that plan;
    with no plan installed the manager is returned as-is and the hot path
    is untouched."""
    backend = backend.upper()
    if backend == "LOOPBACK":
        from fedml_tpu.comm.loopback import LoopbackCommManager

        mgr: BaseCommManager = LoopbackCommManager(
            kw.get("job_id", "default"), rank, size)
    elif backend == "GRPC":
        from fedml_tpu.comm.grpc_backend import GrpcCommManager

        mgr = GrpcCommManager(
            rank, size, ip_table=kw.get("ip_table"),
            base_port=kw.get("base_port", 50000),
            send_timeout_s=kw.get("send_timeout_s", 600.0),
        )
    elif backend == "MQTT":
        from fedml_tpu.comm.mqtt_backend import MqttCommManager

        mgr = MqttCommManager(
            kw.get("broker_host", "127.0.0.1"), kw.get("broker_port", 1883),
            rank, size - 1, job_id=kw.get("job_id"),
        )
    else:
        raise ValueError(f"unknown backend {backend!r} (LOOPBACK|GRPC|MQTT)")
    from fedml_tpu import chaos

    return chaos.maybe_wrap(mgr, rank)


class DistributedManager(Observer):
    """Shared machinery of ClientManager/ServerManager."""

    def __init__(
        self,
        rank: int,
        size: int,
        backend: str = "LOOPBACK",
        timeout_s: float | None = None,
        **backend_kw,
    ):
        self.rank, self.size, self.backend = rank, size, backend
        self.com_manager = make_comm_manager(backend, rank, size, **backend_kw)
        self.com_manager.add_observer(self)
        self._handlers: dict[str, Callable] = {}
        self.timeout_s = timeout_s
        # written by the dispatch thread (receive_message) AND the watchdog
        # thread (_watch's rate-limit reset) — both sides go through
        # _rx_lock so an idle-age read can never interleave with a refresh
        # (the fedlint lock-discipline rule pins this)
        self._rx_lock = threading.Lock()
        self._last_rx = time.monotonic()
        self._finished = threading.Event()
        self.register_message_receive_handlers()

    # ------------------------------------------------------------- handlers
    def register_message_receive_handlers(self) -> None:
        """Subclasses register their per-msg_type handlers here."""

    def register_message_receive_handler(self, msg_type: str, handler: Callable) -> None:
        self._handlers[msg_type] = handler

    def receive_message(self, msg_type: str, msg_params) -> None:
        with self._rx_lock:
            self._last_rx = time.monotonic()
        handler = self._handlers.get(msg_type)
        if handler is None:
            log.warning("rank %d: no handler for msg_type=%s", self.rank, msg_type)
            return
        handler(msg_params)

    def on_timeout(self, idle_s: float) -> None:
        """Failure-detection hook: no inbound traffic for timeout_s."""
        log.error("rank %d: no message for %.1fs — peer failure suspected", self.rank, idle_s)

    # ------------------------------------------------------------------ run
    def run(self) -> None:
        watchdog = None
        if self.timeout_s is not None:
            watchdog = threading.Thread(target=self._watch, daemon=True)
            watchdog.start()
        self.com_manager.handle_receive_message()
        self._finished.set()

    def _watch(self) -> None:
        while not self._finished.is_set():
            time.sleep(min(self.timeout_s / 4, 1.0))
            # periodic liveness refresh: heartbeat-age gauges keep growing
            # while the link is silent — exactly when the watchdog watches
            _obs.refresh_liveness()
            with self._rx_lock:
                idle = time.monotonic() - self._last_rx
                if idle > self.timeout_s:
                    self._last_rx = time.monotonic()  # rate-limit the callback
                else:
                    idle = None
            if idle is not None:  # callback outside the lock: a handler
                try:
                    self.on_timeout(idle)  # calling receive_message must
                    # not deadlock against its own watchdog
                except BaseException as e:
                    # a simulated server crash (detected by name so this
                    # layer needs no distributed import, like
                    # _is_transport_error's RpcError) legitimately kills
                    # the watchdog: the manager's run() re-raises it to
                    # the supervision driver — exit quietly instead of
                    # spraying a thread traceback
                    if type(e).__name__ == "SimulatedServerCrash":
                        return
                    raise

    def send_message(self, message: Message) -> None:
        self.com_manager.send_message(message)

    def finish(self) -> None:
        self._finished.set()
        self.com_manager.stop_receive_message()


class ClientManager(DistributedManager):
    """Base class for client-side round participants
    (≈ fedml_core/distributed/client/client_manager.py)."""


class ServerManager(DistributedManager):
    """Base class for the server-side coordinator
    (≈ fedml_core/distributed/server/server_manager.py)."""
