"""Top-k sparsified model uplinks with error feedback.

The reference ships the FULL model every client→server upload
(fedml_api/distributed/fedavg/FedAvgClientManager.py:66-70); it has no
update compression anywhere. At cross-silo bandwidth the upload is the
round bottleneck, and the classic fix (Deep Gradient Compression / top-k
with error feedback) applies cleanly to FedAvg:

  * the client uploads only the top-k |entries| of its model DELTA
    (local - global, plus the residual of everything never yet shipped);
  * the untransmitted mass stays in a client-side residual and rides in
    later rounds — error feedback, which is what preserves convergence;
  * the server adds each sparse delta onto the global it broadcast —
    since avg_k(global + d_k) == global + avg_k(d_k), the aggregation
    math is untouched and the dense aggregator is reused as-is.

``ratio=1.0`` transmits every entry — numerically equivalent to the dense
protocol (zero residual; the reconstruction ``g + (w - g)`` carries f32
roundoff, so the oracle in tests/test_comm.py compares at 2e-5, not
bitwise).

Residual OWNERSHIP moved to :mod:`fedml_tpu.comm.ef` (PR 9): the client
manager threads one shared :class:`~fedml_tpu.comm.ef.ErrorFeedback`
through every lossy tier (top-k here, the int8/1-bit delta tiers in
comm/delta.py); ``topk_residual`` remains the top-k shortcut for
``compensated - shipped`` and the conservation oracle. Residuals are
per-RANK (the parameter-server convention): under cross-device
reassignment a rank's residual mixes the clients it hosted — acceptable
in practice and zero extra protocol state; cross-silo (fixed assignment)
is the setting this targets (docs/PERFORMANCE.md §Wire efficiency).

Versioned bases (PR 9): the server densifies a sparse uplink against its
per-version broadcast stash keyed by the upload's round tag — which is
what lets top-k compose with buffered-async dispatch waves
(distributed/fedavg/server_manager._decode_upload).

Non-float leaves (e.g. integer counters in a model's extra state) ship
dense, marked by a sentinel index of [-1].
"""

from __future__ import annotations

import numpy as np

_DENSE_SENTINEL = -1


def topk_delta(local_leaves, global_leaves, residual_leaves=None):
    """The quantity top-k operates on: local - global (float leaves only;
    non-float leaves pass through as-is to ship dense), plus the error-
    feedback residual when given. Owning this here keeps the float-vs-
    dense-leaf convention in ONE module with its encode/decode inverses."""
    out = []
    for i, (w, g) in enumerate(zip(local_leaves, global_leaves)):
        w = np.asarray(w)
        if not np.issubdtype(w.dtype, np.floating):
            out.append(w)
            continue
        d = np.asarray(w, np.float32) - np.asarray(g, np.float32)
        if residual_leaves is not None:
            d = d + residual_leaves[i]
        out.append(d)
    return out


def topk_encode(delta_leaves, ratio: float):
    """Per-leaf top-k by |value|. Returns (idx_list, val_list) of flat
    int32 indices and their values; non-float leaves ship dense with the
    sentinel index."""
    if not (0.0 < ratio <= 1.0):
        raise ValueError(f"ratio must be in (0, 1], got {ratio}")
    idx_list, val_list = [], []
    for d in delta_leaves:
        d = np.asarray(d)
        if not np.issubdtype(d.dtype, np.floating):
            idx_list.append(np.array([_DENSE_SENTINEL], np.int32))
            val_list.append(d)
            continue
        flat = d.ravel()
        k = max(1, int(np.ceil(flat.size * ratio)))
        if k >= flat.size:
            sel = np.arange(flat.size, dtype=np.int32)
        else:
            sel = np.argpartition(np.abs(flat), flat.size - k)[-k:] \
                .astype(np.int32)
        idx_list.append(sel)
        val_list.append(flat[sel])
    return idx_list, val_list


def topk_residual(delta_leaves, idx_list):
    """What did NOT ship: the delta with transmitted entries zeroed —
    next round's error-feedback carryover."""
    out = []
    for d, sel in zip(delta_leaves, idx_list):
        d = np.asarray(d)
        if len(sel) == 1 and sel[0] == _DENSE_SENTINEL:  # shipped dense
            out.append(np.zeros_like(d))
            continue
        flat = np.array(d, np.float32).ravel()
        flat[sel] = 0.0
        out.append(flat.reshape(d.shape))
    return out


def topk_decode(global_leaves, idx_list, val_list):
    """Server side: global + sparse delta -> the client's effective model
    leaves (dense), ready for the unchanged weighted-average aggregator."""
    out = []
    for g, sel, vals in zip(global_leaves, idx_list, val_list):
        g = np.asarray(g)
        sel = np.asarray(sel)
        if len(sel) == 1 and sel[0] == _DENSE_SENTINEL:
            out.append(np.asarray(vals).reshape(g.shape))
            continue
        flat = np.array(g, np.float32).ravel()
        flat[sel] += np.asarray(vals, np.float32)
        out.append(flat.reshape(g.shape).astype(g.dtype))
    return out
