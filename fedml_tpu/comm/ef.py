"""Error feedback — the shared residual state every lossy uplink tier owns.

A lossy update codec (top-k sparsification, int8/1-bit quantization of
round deltas) throws information away on every upload. What preserves
convergence is *error feedback* (EF): the untransmitted mass

    residual_{t+1} = compensated_t - shipped_t,
    compensated_t  = delta_t + residual_t

stays client-side and rides in later rounds, so every coordinate's error
is bounded by one round's compression error instead of accumulating — the
Deep-Gradient-Compression / EF-SGD recipe. PR-8's top-k path carried its
own residual bookkeeping inside the client manager; this module is that
logic extracted into ONE object all lossy tiers share (topk, delta-int8,
delta-sign1 — comm/delta.py), so the conservation invariant

    shipped + residual == compensated        (float leaves, exactly)

is defined — and tested — in a single place.

Residuals are per-RANK, not per-client (the parameter-server convention,
inherited from the top-k path): under cross-device client reassignment a
rank's residual mixes the clients it hosted. That is acceptable in
practice (the residual is a correction term, not model state) and costs
zero extra protocol state; fixed-assignment cross-silo is the setting the
lossy tiers target. Documented in docs/PERFORMANCE.md §Wire efficiency.

Leaf convention (same as comm/sparse.py and comm/delta.py): only floating
leaves participate — integer leaves (step counters, embedding vocab ids)
ship dense and carry no residual.
"""

from __future__ import annotations

import numpy as np


def _is_float(arr) -> bool:
    return np.issubdtype(np.asarray(arr).dtype, np.floating)


class ErrorFeedback:
    """Per-rank residual accumulator for lossy update codecs.

    Usage (one instance per uploading rank, living across rounds):

        comp = ef.compensate(raw_delta)          # delta + residual
        payload = encode(comp)                   # any lossy tier
        ef.update(comp, decode(payload))         # residual = comp - shipped

    ``update_residual`` is the top-k shortcut: ``topk_residual`` already
    computes ``comp - shipped`` (the delta with transmitted entries
    zeroed), so the client hands it over instead of re-deriving it.
    """

    def __init__(self):
        self._residual: list[np.ndarray] | None = None

    def compensate(self, delta_leaves) -> list:
        """delta + residual per float leaf (non-float leaves pass through
        untouched — they ship dense and carry no residual)."""
        if self._residual is None:
            return [np.asarray(d) for d in delta_leaves]
        out = []
        for d, r in zip(delta_leaves, self._residual):
            d = np.asarray(d)
            out.append(d + r if _is_float(d) else d)
        return out

    def update(self, compensated_leaves, shipped_leaves) -> None:
        """Fold one round's compression error back in: residual =
        compensated - shipped (zeros for non-float leaves). ``shipped``
        must be the DECODED form of what went on the wire — the value the
        server will actually apply — so the residual tracks the server's
        view, not the client's intent.

        Poison containment: a non-finite round (diverged local fit, an
        adversary window) encodes with a NaN scale so the SERVER
        quarantines it — but folding that NaN into the residual would
        poison every later upload from this rank permanently. A
        non-finite residual update is therefore SKIPPED: the poison still
        ships (and dies at the gate), and the next honest round resumes
        from the pre-poison residual."""
        res = []
        for c, s in zip(compensated_leaves, shipped_leaves):
            c = np.asarray(c)
            if _is_float(c):
                res.append(np.asarray(c, np.float32) - np.asarray(s, np.float32))
            else:
                res.append(np.zeros_like(c))
        self._install(res)

    def update_residual(self, residual_leaves) -> None:
        """Install a residual computed elsewhere (the top-k path's
        ``topk_residual`` output is already ``compensated - shipped``).
        Same poison containment as :meth:`update`."""
        self._install([np.asarray(r) for r in residual_leaves])

    def _install(self, res: list) -> None:
        if any(_is_float(r) and not np.isfinite(r).all() for r in res):
            return  # keep the pre-poison residual (see update docstring)
        self._residual = res

    def reset(self) -> None:
        self._residual = None

    @property
    def residual(self) -> list | None:
        return self._residual
