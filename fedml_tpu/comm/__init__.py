"""L1 communication layer — pluggable cross-process backends.

TPU-native replacement of fedml_core/distributed/communication/. The SPMD
engine (fedml_tpu/algorithms) is the fast path when all simulated clients
live in one program; this layer exists for the reference's OTHER computing
paradigm — *distributed training* with one OS process per participant
(README.md:93-97) — i.e. real cross-silo/cross-device federation where
parties do not share an address space.

Backends:
- ``loopback`` — in-process queues (threads as ranks); the test transport.
- ``grpc``    — per-rank insecure gRPC server, port base+rank, ip-table
  routing (mirror of fedml_core/distributed/communication/gRPC/).
- ``mqtt``    — broker pub/sub (mirror of .../mqtt/); gated on paho-mqtt.

Unlike the reference there is no MPI backend: on TPU pods, intra-job
transport is XLA collectives over ICI (fedml_tpu/collectives); this layer
only carries *inter-job* traffic (DCN/ethernet), where gRPC is the native
choice.
"""

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.loopback import LoopbackCommManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.observer import Observer

__all__ = ["BaseCommManager", "LoopbackCommManager", "Message", "Observer"]
