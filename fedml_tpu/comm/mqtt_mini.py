"""Minimal MQTT 3.1.1 client + in-process broker (stdlib sockets only).

The reference's third transport is MQTT via paho + an external broker
(fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py). This image
bundles neither paho nor a broker binary, so the backend would be dead code
here; this module implements the small slice of MQTT 3.1.1 the FL managers
need — CONNECT/CONNACK, SUBSCRIBE/SUBACK (exact-match topics), PUBLISH QoS
0/1 with PUBACK, PINGREQ/PINGRESP, DISCONNECT — as a paho fallback, plus a
loopback broker so the pub/sub path is actually testable end-to-end.

Scope notes (deliberate): no wildcard topics (the fedml topic scheme uses
exact names), no QoS 2, no persistent sessions, no QoS-1 redelivery (TCP
ordering + the managers' idempotent handlers make at-most-once-per-
connection sufficient for tests; production deployments point the same
manager at a real broker via paho). Retained messages ARE implemented:
pub/sub has an inherent startup race (a publish to a topic nobody has
subscribed to yet is dropped), and parties boot in arbitrary order — the
server's init message is published with RETAIN so a later-subscribing
client still receives it.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading

log = logging.getLogger("fedml_tpu.comm.mqtt_mini")

CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
SUBSCRIBE, SUBACK, PINGREQ, PINGRESP, DISCONNECT = 8, 9, 12, 13, 14


def _encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("mqtt: peer closed")
        buf += chunk
    return buf


def _read_packet(sock: socket.socket) -> tuple[int, int, bytes]:
    """-> (type, flags, body). Blocks; raises ConnectionError on EOF."""
    h = _read_exact(sock, 1)[0]
    length, mult = 0, 1
    while True:
        b = _read_exact(sock, 1)[0]
        length += (b & 0x7F) * mult
        if not (b & 0x80):
            break
        mult *= 128
        if mult > 128**3:
            raise ValueError("mqtt: malformed varint")
    return h >> 4, h & 0x0F, _read_exact(sock, length) if length else b""


def _packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + _encode_varint(len(body)) + body


def _mqtt_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def retry_connect(connect, desc: str, deadline_s: float = 120.0):
    """Run ``connect()`` until it succeeds or ``deadline_s`` passes. Peers
    boot in arbitrary order — a rank that comes up before the broker (e.g.
    rank 0 hosting it via --serve_broker) must wait, not die on
    ConnectionRefused (the transport-level analogue of the gRPC backend's
    wait_for_ready). Shared by the mini client and the paho path; warnings
    are throttled to one per ~10 attempts."""
    import time

    deadline = time.monotonic() + deadline_s
    attempt = 0
    while True:
        try:
            return connect()
        except OSError as e:
            attempt += 1
            if time.monotonic() >= deadline:
                raise ConnectionError(
                    f"mqtt: {desc} unreachable for {deadline_s:.0f}s: {e}"
                ) from e
            if attempt % 10 == 1:
                log.warning("mqtt: %s not up yet, retrying", desc)
            time.sleep(1.0)


class MiniMqttClient:
    """Tiny synchronous-publish / threaded-receive MQTT 3.1.1 client."""

    def __init__(self, host: str, port: int, client_id: str,
                 on_message=None, keepalive: int = 0):
        # keepalive=0 disables the broker's inactivity timeout (MQTT 3.1.1
        # §3.1.2.10) — this client sends no PINGREQs, and FL rounds can be
        # minutes of silence between messages
        self.on_message = on_message
        self._sock = self._connect_with_retry(host, port)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._pid = 0
        body = (_mqtt_str("MQTT") + bytes([4]) + bytes([0x02])
                + struct.pack(">H", keepalive) + _mqtt_str(client_id))
        self._send(_packet(CONNECT, 0, body))
        t, _, b = _read_packet(self._sock)
        if t != CONNACK or (len(b) >= 2 and b[1] != 0):
            raise ConnectionError(f"mqtt: connect refused ({b!r})")
        self._alive = True
        self._thread = threading.Thread(target=self._reader, daemon=True)
        self._thread.start()

    @staticmethod
    def _connect_with_retry(host: str, port: int,
                            deadline_s: float = 120.0) -> socket.socket:
        return retry_connect(
            lambda: socket.create_connection((host, port), timeout=30),
            f"broker {host}:{port}", deadline_s)

    def _send(self, data: bytes) -> None:
        with self._wlock:
            self._sock.sendall(data)

    def _next_pid(self) -> int:
        self._pid = self._pid % 65535 + 1
        return self._pid

    def subscribe(self, topic: str, qos: int = 1) -> None:
        body = struct.pack(">H", self._next_pid()) + _mqtt_str(topic) + bytes([qos])
        self._send(_packet(SUBSCRIBE, 0x02, body))
        # SUBACK is consumed by the reader thread (no granted-qos check —
        # the broker below always grants)

    def publish(self, topic: str, payload: bytes, qos: int = 1,
                retain: bool = False) -> None:
        r = 0x01 if retain else 0x00
        if qos == 0:
            self._send(_packet(PUBLISH, r, _mqtt_str(topic) + payload))
            return
        body = _mqtt_str(topic) + struct.pack(">H", self._next_pid()) + payload
        self._send(_packet(PUBLISH, 0x02 | r, body))  # QoS1; PUBACK via reader

    def _reader(self) -> None:
        try:
            while self._alive:
                t, flags, body = _read_packet(self._sock)
                if t == PUBLISH:
                    tl = struct.unpack(">H", body[:2])[0]
                    topic = body[2 : 2 + tl].decode()
                    rest = body[2 + tl :]
                    qos = (flags >> 1) & 0x03
                    if qos:
                        pid, rest = struct.unpack(">H", rest[:2])[0], rest[2:]
                        self._send(_packet(PUBACK, 0, struct.pack(">H", pid)))
                    if self.on_message is not None:
                        self.on_message(topic, rest)
                elif t == PINGREQ:
                    self._send(_packet(PINGRESP, 0, b""))
                # SUBACK / PUBACK / PINGRESP: no client-side state to update
        except (ConnectionError, OSError) as e:
            if self._alive:  # unexpected death, not close(): say so
                log.error("mqtt: connection to broker lost: %s", e)

    def close(self) -> None:
        self._alive = False
        try:
            self._send(_packet(DISCONNECT, 0, b""))
            self._sock.close()
        except OSError:
            pass


class MiniMqttBroker:
    """Exact-topic-match loopback broker for tests and single-host runs."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.create_server((host, port))
        self.port = self._srv.getsockname()[1]
        self._subs: dict[str, set[socket.socket]] = {}
        self._retained: dict[str, bytes] = {}  # topic -> last retained payload
        self._socks: list[socket.socket] = []
        self._lock = threading.Lock()
        self._alive = True
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._lock:
                self._socks.append(conn)
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _send(self, sock: socket.socket, data: bytes) -> None:
        try:
            sock.sendall(data)
        except OSError:
            self._drop(sock)

    def _drop(self, sock: socket.socket) -> None:
        with self._lock:
            for subs in self._subs.values():
                subs.discard(sock)
            if sock in self._socks:
                self._socks.remove(sock)
        try:
            sock.close()
        except OSError:
            pass

    def _serve(self, sock: socket.socket) -> None:
        try:
            t, _, _ = _read_packet(sock)
            if t != CONNECT:
                return
            self._send(sock, _packet(CONNACK, 0, b"\x00\x00"))
            while self._alive:
                t, flags, body = _read_packet(sock)
                if t == SUBSCRIBE:
                    pid = struct.unpack(">H", body[:2])[0]
                    i, grants, retained = 2, [], []
                    while i < len(body):
                        tl = struct.unpack(">H", body[i : i + 2])[0]
                        topic = body[i + 2 : i + 2 + tl].decode()
                        grants.append(body[i + 2 + tl])
                        i += 3 + tl
                        # register + snapshot retained in ONE locked section:
                        # a publisher's (store retained, read subscribers) is
                        # also one section, so exactly one of live fan-out or
                        # retained delivery wins — never both (no dup init)
                        with self._lock:
                            self._subs.setdefault(topic, set()).add(sock)
                            payload = self._retained.get(topic)
                        if payload is not None:
                            retained.append((topic, payload))
                    self._send(sock, _packet(
                        SUBACK, 0, struct.pack(">H", pid) + bytes(grants)))
                    for topic, payload in retained:  # after SUBACK, flag set
                        self._send(sock, _packet(
                            PUBLISH, 0x01, _mqtt_str(topic) + payload))
                elif t == PUBLISH:
                    tl = struct.unpack(">H", body[:2])[0]
                    topic = body[2 : 2 + tl].decode()
                    rest = body[2 + tl :]
                    qos = (flags >> 1) & 0x03
                    if qos:
                        pid, rest = struct.unpack(">H", rest[:2])[0], rest[2:]
                        self._send(sock, _packet(PUBACK, 0, struct.pack(">H", pid)))
                    # store retained + snapshot subscribers in ONE locked
                    # section (see the SUBSCRIBE handler's dual invariant)
                    with self._lock:
                        if flags & 0x01:  # RETAIN: keep for late subscribers
                            if rest:
                                self._retained[topic] = rest
                            else:  # empty retained payload clears (spec 3.3.1.3)
                                self._retained.pop(topic, None)
                        targets = list(self._subs.get(topic, ()))
                    # deliver as QoS0 (subscriber PUBACK bookkeeping not needed)
                    out = _packet(PUBLISH, 0, _mqtt_str(topic) + rest)
                    for s in targets:  # includes the publisher if self-subscribed
                        self._send(s, out)
                elif t == PINGREQ:
                    self._send(sock, _packet(PINGRESP, 0, b""))
                elif t == DISCONNECT:
                    break
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            self._drop(sock)

    def close(self) -> None:
        self._alive = False
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            socks = list(self._socks)
        for s in socks:
            self._drop(s)
