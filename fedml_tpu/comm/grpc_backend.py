"""gRPC transport — one insecure server per rank, ip-table routing.

Mirror of fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:
each rank serves on port base+rank (reference: 50000+rank,
grpc_comm_manager.py:29,60); senders route via a rank->ip table
(fedml_api/distributed/utils/ip_config_utils.py reads grpc_ipconfig.csv).

Redesigns vs the reference:
- No protoc-generated stubs: the service is registered with a generic bytes
  handler (identity serializers), so the binary Message frame from
  message.py goes over the wire untouched — no JSON-ification of weights
  (reference sends weights as JSON nested lists, a ~10x size blowup).
- Channels are cached per destination instead of opened per message
  (reference opens and closes a channel every send, grpc_comm_manager.py:53-74).
- The inbound path enqueues into the blocking dispatch queue of
  BaseCommManager instead of a 0.1 s polling drain thread
  (grpc_comm_manager.py:86-97).
"""

from __future__ import annotations

import csv
import logging

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message

log = logging.getLogger("fedml_tpu.comm.grpc")

_SERVICE = "fedml_tpu.Comm"
_METHOD = "Send"
_MAX_MSG = 1024 * 1024 * 1024  # 1 GB (reference caps at 100 MB, :35-36)


def read_ip_config(path: str) -> dict[int, str]:
    """rank -> ip, from a csv with header (receiver_id, ip)."""
    table: dict[int, str] = {}
    with open(path) as f:
        for row in csv.DictReader(f):
            table[int(row["receiver_id"])] = row["ip"]
    return table


class GrpcCommManager(BaseCommManager):
    backend_name = "grpc"

    def __init__(
        self,
        rank: int,
        size: int,
        ip_table: dict[int, str] | str | None = None,
        base_port: int = 50000,
        host: str = "0.0.0.0",
        send_timeout_s: float = 600.0,
    ):
        super().__init__()
        import grpc

        self.rank, self.size, self.base_port = rank, size, base_port
        # per-send delivery deadline: generous by default (peers boot jax
        # in arbitrary order); elastic servers shrink it to the round
        # deadline so one dead peer cannot wedge the round loop
        self.send_timeout_s = float(send_timeout_s)
        if isinstance(ip_table, str):
            ip_table = read_ip_config(ip_table)
        self.ip_table = ip_table or {r: "127.0.0.1" for r in range(size)}
        self._channels: dict[int, object] = {}
        self._grpc = grpc
        self._send_seq = 0
        import secrets
        import threading

        # boot epoch: a restarted peer restarts seq at 0; keying the dedup
        # set by (src, epoch) keeps redelivery detection restart-safe (the
        # server checkpoint-resume path relaunches the process mid-job)
        self._epoch = secrets.randbits(64)
        # per-(src,epoch) dedup state: (seen-set, watermark). Everything at or
        # below the watermark is known-seen even after set eviction, so a
        # frame redelivered arbitrarily late can never be re-accepted — the
        # window violation is impossible, not just assumed away by in-order
        # sending.
        self._seen: dict[tuple[int, int], tuple[set[int], int]] = {}
        self._seen_lock = threading.Lock()
        self._send_lock = threading.Lock()
        # guards the channel cache: sender threads create channels in
        # _stub while the retry path pops them — without the lock a
        # reconnect could hand a half-registered channel to a concurrent
        # send to the same peer (or leak one that close() then misses)
        self._channels_lock = threading.Lock()

        from concurrent import futures

        def recv(request: bytes, context):
            # 24-byte transport prefix: (sender_rank, boot_epoch, seq) u64-LE.
            # Retries make delivery at-least-once (the connection can drop
            # after the handler ran but before 'ok' reached the sender); the
            # seen-set makes it exactly-once — a redelivered client upload
            # must NOT count toward the next round's aggregation. The epoch
            # distinguishes a restarted peer (fresh seq=1 stream) from a
            # duplicate of the previous process's frame 1.
            hdr, frame = request[:24], request[24:]
            src = int.from_bytes(hdr[:8], "little")
            epoch = int.from_bytes(hdr[8:16], "little")
            seq = int.from_bytes(hdr[16:], "little")
            from fedml_tpu.obs import comm_instrument as _obs

            # wire-level heartbeat: even a frame the dedup gate is about
            # to drop proves the peer process is alive
            _obs.record_rank_seen(src)
            if not self._accept_frame(src, epoch, seq):
                _obs.record_duplicate(self.backend_name)
                log.warning("drop duplicate frame %d from rank %d", seq, src)
                return b"dup"
            self._receive_frame(frame)
            return b"ok"

        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {_METHOD: grpc.unary_unary_rpc_method_handler(recv)},
        )
        opts = [
            ("grpc.max_send_message_length", _MAX_MSG),
            ("grpc.max_receive_message_length", _MAX_MSG),
        ]
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8), options=opts)
        self._server.add_generic_rpc_handlers((handler,))
        self._port = self._server.add_insecure_port(f"{host}:{base_port + rank}")
        if self._port == 0:
            raise RuntimeError(f"grpc: cannot bind {host}:{base_port + rank}")
        self._server.start()
        log.info("rank %d serving on %s:%d", rank, host, self._port)

    def _accept_frame(self, src: int, epoch: int, seq: int) -> bool:
        """Exactly-once gate. True = first delivery; False = duplicate.

        State per (src, epoch): (gap-set, watermark) where every seq <=
        watermark is known-seen. The watermark advances over contiguous
        prefixes (O(1) memory for in-order senders); if pathological gaps
        grow the set past 4096, the lowest half is evicted INTO the
        watermark, so evicted seqs remain known-seen — a frame redelivered
        arbitrarily late can never be re-accepted (the trade is that a
        genuinely new frame >4096 out of order is dropped, which in-order
        senders never produce)."""
        with self._seen_lock:
            seen, wm = self._seen.setdefault((src, epoch), (set(), -1))
            if seq <= wm or seq in seen:
                return False
            seen.add(seq)
            while wm + 1 in seen:
                wm += 1
                seen.discard(wm)
            if len(seen) > 4096:
                evicted = sorted(seen)[:2048]
                for s in evicted:
                    seen.discard(s)
                wm = max(wm, evicted[-1])
            self._seen[(src, epoch)] = (seen, wm)
            stale = [k for k in self._seen if k[0] == src and k != (src, epoch)]
            for k in stale[:-1]:  # keep at most the 2 newest epochs per src
                del self._seen[k]
        return True

    def _stub(self, dest: int):
        with self._channels_lock:
            ch = self._channels.get(dest)
            if ch is None:
                addr = f"{self.ip_table[dest]}:{self.base_port + dest}"
                opts = [
                    ("grpc.max_send_message_length", _MAX_MSG),
                    ("grpc.max_receive_message_length", _MAX_MSG),
                ]
                ch = self._grpc.insecure_channel(addr, options=opts)
                self._channels[dest] = ch
        return ch.unary_unary(f"/{_SERVICE}/{_METHOD}")

    # transient-retry policy: bounded exponential backoff (base doubling,
    # capped) with deterministic half-jitter — sha256 of (src, dst, seq,
    # attempt), not a shared RNG, so two ranks retrying the same dead peer
    # desynchronize without perturbing any seeded replay
    _RETRY_BASE_S = 0.25
    _RETRY_CAP_S = 5.0
    # per-attempt RPC deadline, ESCALATING per retry (30, 60, 120, ... up
    # to the remaining budget): a single attempt must not absorb the whole
    # send budget — or DEADLINE_EXCEEDED could only ever mean "budget
    # gone" and the retry path would never see a wedged stream as
    # transient — but a genuinely slow large-frame transfer must
    # eventually get a window as wide as the budget allows, or the cap
    # itself would starve links the uncapped sender handled fine
    _ATTEMPT_TIMEOUT_S = 30.0

    def _retry_reason(self, e) -> str | None:
        """Status-code label when ``e`` is transient (retry), else None
        (permanent — surface it). UNAVAILABLE = peer restarting/not yet
        listening; DEADLINE_EXCEEDED = one attempt timed out (congestion,
        a wedged stream) — the NEXT attempt on a fresh channel often
        lands. Everything else (UNIMPLEMENTED, INVALID_ARGUMENT, resource
        exhaustion) is a real error retries would only hide."""
        code = e.code() if hasattr(e, "code") else None
        if code == self._grpc.StatusCode.UNAVAILABLE:
            return "unavailable"
        if code == self._grpc.StatusCode.DEADLINE_EXCEEDED:
            return "deadline_exceeded"
        return None

    @staticmethod
    def _retry_jitter(src: int, dest: int, seq: int, attempt: int) -> float:
        """Uniform [0, 1) draw, pure in its arguments (the chaos plan's
        sha256-counter idiom)."""
        import hashlib

        h = hashlib.sha256(
            f"grpc-retry|{src}|{dest}|{seq}|{attempt}".encode()).digest()
        return int.from_bytes(h[:8], "little") / 2.0 ** 64

    def send_message(self, msg: Message) -> None:
        """Deliver one frame. ``wait_for_ready`` queues the RPC until the
        peer's server is actually listening (peers boot in arbitrary order —
        the reference sidesteps this only because mpirun barriers before
        main; a raw send here would fail fast with UNAVAILABLE while the
        receiver is still starting jax). Transient failures (UNAVAILABLE /
        DEADLINE_EXCEEDED) retry under bounded exponential backoff with
        deterministic jitter until ``send_timeout_s`` is spent — each retry
        counted in ``comm_send_retries_total{reason}`` — and a permanent
        failure raises loudly instead of wedging the rank."""
        import time

        dest = int(msg.get_receiver_id())
        with self._send_lock:
            self._send_seq += 1
            seq = self._send_seq
        frame = (self.rank.to_bytes(8, "little")
                 + self._epoch.to_bytes(8, "little")
                 + seq.to_bytes(8, "little") + self._encode(msg))
        deadline = time.monotonic() + self.send_timeout_s
        attempt = 0
        while True:
            try:
                attempt_cap = self._ATTEMPT_TIMEOUT_S * (2.0 ** attempt)
                self._stub(dest)(
                    frame,
                    timeout=max(1.0, min(attempt_cap,
                                         deadline - time.monotonic())),
                    wait_for_ready=True,
                )
                return
            except self._grpc.RpcError as e:
                reason = self._retry_reason(e)
                if reason is None or time.monotonic() >= deadline:
                    # permanent (or budget exhausted): the caller decides —
                    # the elastic server marks the rank undeliverable, a
                    # client dies visibly — but never a silent hang
                    log.error(
                        "send to rank %d failed permanently after %d "
                        "retr%s (%s)", dest, attempt,
                        "y" if attempt == 1 else "ies",
                        reason or getattr(e, "code", lambda: e)())
                    raise
                attempt += 1
                # wire accounting: _encode counted this frame once (logical
                # send); each retry moves the bytes again — plus the
                # per-reason attempt counter the flaky-link diagnosis needs
                from fedml_tpu.obs import comm_instrument as _obs

                _obs.record_send_retry(self.backend_name, reason)
                _obs.record_retransmit(self.backend_name, len(frame))
                log.warning("send to rank %d %s (attempt %d), retrying",
                            dest, reason, attempt)
                # Drop (don't close) the cached channel: a dead peer's channel
                # can linger in TRANSIENT_FAILURE with long reconnect backoff,
                # but close() would cancel another thread's in-flight RPC on
                # the same channel (CANCELLED is not retriable). The dropped
                # channel is finalized by GC once all calls on it finish.
                # Under _channels_lock so a concurrent _stub can't observe
                # (and cache a call on) the entry mid-replacement.
                with self._channels_lock:
                    self._channels.pop(dest, None)
                # wait_for_ready throttles only connection establishment; if
                # the peer accepts connections but fails RPCs (restart loop,
                # GOAWAY during shutdown) each attempt returns immediately —
                # the backoff bounds the spin, the jitter de-thunders it.
                back = min(self._RETRY_BASE_S * (2.0 ** (attempt - 1)),
                           self._RETRY_CAP_S)
                back *= 0.5 + 0.5 * self._retry_jitter(self.rank, dest, seq,
                                                       attempt)
                time.sleep(min(back, max(0.0,
                                         deadline - time.monotonic())))

    def stop_receive_message(self) -> None:
        super().stop_receive_message()
        with self._channels_lock:
            channels, self._channels = list(self._channels.values()), {}
        for ch in channels:
            ch.close()
        self._server.stop(grace=0.5)
