"""gRPC transport — one insecure server per rank, ip-table routing.

Mirror of fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:
each rank serves on port base+rank (reference: 50000+rank,
grpc_comm_manager.py:29,60); senders route via a rank->ip table
(fedml_api/distributed/utils/ip_config_utils.py reads grpc_ipconfig.csv).

Redesigns vs the reference:
- No protoc-generated stubs: the service is registered with a generic bytes
  handler (identity serializers), so the binary Message frame from
  message.py goes over the wire untouched — no JSON-ification of weights
  (reference sends weights as JSON nested lists, a ~10x size blowup).
- Channels are cached per destination instead of opened per message
  (reference opens and closes a channel every send, grpc_comm_manager.py:53-74).
- The inbound path enqueues into the blocking dispatch queue of
  BaseCommManager instead of a 0.1 s polling drain thread
  (grpc_comm_manager.py:86-97).
"""

from __future__ import annotations

import csv
import logging

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message

log = logging.getLogger("fedml_tpu.comm.grpc")

_SERVICE = "fedml_tpu.Comm"
_METHOD = "Send"
_MAX_MSG = 1024 * 1024 * 1024  # 1 GB (reference caps at 100 MB, :35-36)


def read_ip_config(path: str) -> dict[int, str]:
    """rank -> ip, from a csv with header (receiver_id, ip)."""
    table: dict[int, str] = {}
    with open(path) as f:
        for row in csv.DictReader(f):
            table[int(row["receiver_id"])] = row["ip"]
    return table


class GrpcCommManager(BaseCommManager):
    def __init__(
        self,
        rank: int,
        size: int,
        ip_table: dict[int, str] | str | None = None,
        base_port: int = 50000,
        host: str = "0.0.0.0",
    ):
        super().__init__()
        import grpc

        self.rank, self.size, self.base_port = rank, size, base_port
        if isinstance(ip_table, str):
            ip_table = read_ip_config(ip_table)
        self.ip_table = ip_table or {r: "127.0.0.1" for r in range(size)}
        self._channels: dict[int, object] = {}
        self._grpc = grpc

        from concurrent import futures

        def recv(request: bytes, context):
            self._enqueue(Message.from_bytes(request))
            return b"ok"

        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {_METHOD: grpc.unary_unary_rpc_method_handler(recv)},
        )
        opts = [
            ("grpc.max_send_message_length", _MAX_MSG),
            ("grpc.max_receive_message_length", _MAX_MSG),
        ]
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8), options=opts)
        self._server.add_generic_rpc_handlers((handler,))
        self._port = self._server.add_insecure_port(f"{host}:{base_port + rank}")
        if self._port == 0:
            raise RuntimeError(f"grpc: cannot bind {host}:{base_port + rank}")
        self._server.start()
        log.info("rank %d serving on %s:%d", rank, host, self._port)

    def _stub(self, dest: int):
        if dest not in self._channels:
            addr = f"{self.ip_table[dest]}:{self.base_port + dest}"
            opts = [
                ("grpc.max_send_message_length", _MAX_MSG),
                ("grpc.max_receive_message_length", _MAX_MSG),
            ]
            self._channels[dest] = self._grpc.insecure_channel(addr, options=opts)
        return self._channels[dest].unary_unary(f"/{_SERVICE}/{_METHOD}")

    def send_message(self, msg: Message) -> None:
        dest = int(msg.get_receiver_id())
        frame = msg.to_bytes()
        self._stub(dest)(frame, timeout=600)

    def stop_receive_message(self) -> None:
        super().stop_receive_message()
        for ch in self._channels.values():
            ch.close()
        self._channels.clear()
        self._server.stop(grace=0.5)
