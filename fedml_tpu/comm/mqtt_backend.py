"""MQTT transport — broker-mediated pub/sub for mobile/IoT federation.

Mirror of fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:
topic scheme preserved: the server (id 0) publishes to ``fedml0_<cid>`` and
subscribes to ``fedml_<cid>``; client cid publishes ``fedml_<cid>`` and
subscribes ``fedml0_<cid>`` (mqtt_comm_manager.py:47-70). Payloads are the
binary Message frame, not JSON.

Transport selection: paho-mqtt when installed (any MQTT 3.1.1 broker);
otherwise the bundled minimal client (mqtt_mini.py) — same topic scheme,
same Message frames — so the backend works and is testable in environments
without paho (pair it with mqtt_mini.MiniMqttBroker for loopback runs).

Retained-message discipline (persistent-broker safety): ONLY the server's
downlinks are retained — that is the documented startup-race fix (a client
that boots late still gets the init/sync). Client uplinks are never
retained: against a persistent broker a retained uplink outlives the job,
and a later run's server would count a stale final-round model upload
toward its round 0. On a clean server stop the retained downlinks are
cleared with empty retained payloads (MQTT 3.1.1 §3.3.1.3 tombstones), and
``job_id`` namespaces the topics so concurrent/successive jobs sharing a
broker cannot cross-talk at all.

An uplink published while the server is OFFLINE is dropped (no retained
copy, and clean-session semantics keep no queue — same as the reference's
paho default). That loss self-heals at the protocol layer: a restarted
server resumes from its round checkpoint and re-broadcasts the sync for
that round (distributed/fedavg/server_manager.py run/send_init_msg), and
stateless clients retrain and re-upload — the dropped frame belonged to a
round the server re-runs anyway.
"""

from __future__ import annotations

import logging
import uuid

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message

log = logging.getLogger("fedml_tpu.comm.mqtt")


class MqttCommManager(BaseCommManager):
    backend_name = "mqtt"

    def __init__(self, broker_host: str, broker_port: int, client_id: int,
                 client_num: int, job_id: str | None = None):
        super().__init__()
        self.client_id, self.client_num = client_id, client_num
        # job namespace: '' keeps the reference's exact topic scheme; a
        # launcher-provided job_id isolates runs sharing a persistent broker
        self._ns = f"{job_id}/" if job_id else ""
        self._retained_topics: set[str] = set()  # server downlinks to clear on stop
        name = f"fedml_tpu-{client_id}-{uuid.uuid4().hex[:6]}"
        try:
            import paho.mqtt.client as mqtt
        except ImportError:
            from fedml_tpu.comm.mqtt_mini import MiniMqttClient

            self._mini = MiniMqttClient(
                broker_host, broker_port, name,
                on_message=lambda topic, payload: self._on_payload(payload))
            self._client = None
            for t in self._sub_topics():
                self._mini.subscribe(t, qos=1)
            log.info("mqtt: paho absent, using bundled minimal client")
            return

        self._mini = None
        if hasattr(mqtt, "CallbackAPIVersion"):  # paho-mqtt >= 2.0
            self._client = mqtt.Client(mqtt.CallbackAPIVersion.VERSION2, client_id=name)
        else:  # paho-mqtt 1.x
            self._client = mqtt.Client(client_id=name)
        self._client.on_connect = self._on_connect
        self._client.on_message = self._on_message
        # same boot-order tolerance as the mini client (shared retry helper)
        from fedml_tpu.comm.mqtt_mini import retry_connect

        retry_connect(
            lambda: self._client.connect(broker_host, broker_port, keepalive=180),
            f"broker {broker_host}:{broker_port}")
        self._client.loop_start()

    # topic scheme parity (mqtt_comm_manager.py:47-70), optionally namespaced
    def _sub_topics(self):
        if self.client_id == 0:  # server listens to every client's uplink
            return [f"{self._ns}fedml_{cid}"
                    for cid in range(1, self.client_num + 1)]
        return [f"{self._ns}fedml0_{self.client_id}"]

    def _pub_topic(self, receiver_id: int) -> str:
        if self.client_id == 0:
            return f"{self._ns}fedml0_{receiver_id}"
        return f"{self._ns}fedml_{self.client_id}"

    def _on_connect(self, client, userdata, flags, rc, properties=None):
        # signature covers both paho v1 (4 args) and v2 (5 args) callbacks
        for t in self._sub_topics():
            client.subscribe(t, qos=1)

    def _on_payload(self, payload: bytes) -> None:
        if not payload:  # retained-clear tombstone (§3.3.1.3), not a frame
            return
        self._receive_frame(payload)

    def _on_message(self, client, userdata, m):
        self._on_payload(m.payload)

    def send_message(self, msg: Message) -> None:
        # Server downlinks are retained (parties boot in arbitrary order and
        # a pub/sub broker drops messages for not-yet-subscribed topics;
        # retaining the last sync frame lets a late client catch up — the
        # gRPC backend's wait_for_ready analogue; the reference leaves this
        # race unhandled). Client uplinks are NOT retained — see module
        # docstring (stale-upload corruption on persistent brokers). Clients
        # only publish after receiving the server's (retained) init, by which
        # point the server's uplink subscriptions are long established.
        topic = self._pub_topic(int(msg.get_receiver_id()))
        retain = self.client_id == 0
        if retain:
            self._retained_topics.add(topic)
        self._publish(topic, self._encode(msg), retain)

    def _publish(self, topic: str, payload: bytes, retain: bool):
        if self._mini is not None:
            self._mini.publish(topic, payload, qos=1, retain=retain)
            return None
        return self._client.publish(topic, payload=payload, qos=1, retain=retain)

    def stop_receive_message(self) -> None:
        super().stop_receive_message()
        # clear our retained downlinks so they cannot leak into a later run
        # sharing this (possibly persistent) broker. On paho, publish() only
        # QUEUES on the network loop — wait for each tombstone to go out
        # before loop_stop(), or the clear never reaches the broker.
        infos = []
        for topic in sorted(self._retained_topics):
            try:
                infos.append(self._publish(topic, b"", retain=True))
            except Exception:  # noqa: BLE001 — best-effort during teardown
                log.warning("mqtt: failed to clear retained topic %s", topic)
        for info in infos:
            if info is not None:  # paho MQTTMessageInfo
                try:
                    info.wait_for_publish(timeout=5)
                except Exception:  # noqa: BLE001
                    log.warning("mqtt: retained-clear flush timed out")
        if self._mini is not None:
            self._mini.close()
            return
        self._client.loop_stop()
        self._client.disconnect()
