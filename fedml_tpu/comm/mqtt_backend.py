"""MQTT transport — broker-mediated pub/sub for mobile/IoT federation.

Mirror of fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:
topic scheme preserved: the server (id 0) publishes to ``fedml0_<cid>`` and
subscribes to ``fedml_<cid>``; client cid publishes ``fedml_<cid>`` and
subscribes ``fedml0_<cid>`` (mqtt_comm_manager.py:47-70). Payloads are the
binary Message frame, not JSON.

Transport selection: paho-mqtt when installed (any MQTT 3.1.1 broker);
otherwise the bundled minimal client (mqtt_mini.py) — same topic scheme,
same Message frames — so the backend works and is testable in environments
without paho (pair it with mqtt_mini.MiniMqttBroker for loopback runs).
"""

from __future__ import annotations

import logging
import uuid

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message

log = logging.getLogger("fedml_tpu.comm.mqtt")


class MqttCommManager(BaseCommManager):
    def __init__(self, broker_host: str, broker_port: int, client_id: int, client_num: int):
        super().__init__()
        self.client_id, self.client_num = client_id, client_num
        name = f"fedml_tpu-{client_id}-{uuid.uuid4().hex[:6]}"
        try:
            import paho.mqtt.client as mqtt
        except ImportError:
            from fedml_tpu.comm.mqtt_mini import MiniMqttClient

            self._mini = MiniMqttClient(
                broker_host, broker_port, name,
                on_message=lambda topic, payload: self._enqueue(
                    Message.from_bytes(payload)))
            self._client = None
            for t in self._sub_topics():
                self._mini.subscribe(t, qos=1)
            log.info("mqtt: paho absent, using bundled minimal client")
            return

        self._mini = None
        if hasattr(mqtt, "CallbackAPIVersion"):  # paho-mqtt >= 2.0
            self._client = mqtt.Client(mqtt.CallbackAPIVersion.VERSION2, client_id=name)
        else:  # paho-mqtt 1.x
            self._client = mqtt.Client(client_id=name)
        self._client.on_connect = self._on_connect
        self._client.on_message = self._on_message
        # same boot-order tolerance as the mini client (shared retry helper)
        from fedml_tpu.comm.mqtt_mini import retry_connect

        retry_connect(
            lambda: self._client.connect(broker_host, broker_port, keepalive=180),
            f"broker {broker_host}:{broker_port}")
        self._client.loop_start()

    # topic scheme parity (mqtt_comm_manager.py:47-70)
    def _sub_topics(self):
        if self.client_id == 0:  # server listens to every client's uplink
            return [f"fedml_{cid}" for cid in range(1, self.client_num + 1)]
        return [f"fedml0_{self.client_id}"]

    def _pub_topic(self, receiver_id: int) -> str:
        if self.client_id == 0:
            return f"fedml0_{receiver_id}"
        return f"fedml_{self.client_id}"

    def _on_connect(self, client, userdata, flags, rc, properties=None):
        # signature covers both paho v1 (4 args) and v2 (5 args) callbacks
        for t in self._sub_topics():
            client.subscribe(t, qos=1)

    def _on_message(self, client, userdata, m):
        self._enqueue(Message.from_bytes(m.payload))

    def send_message(self, msg: Message) -> None:
        # retain=True on BOTH paths: parties boot in arbitrary order and a
        # pub/sub broker drops messages for not-yet-subscribed topics;
        # retaining the last frame per topic lets a late subscriber catch up
        # (the gRPC backend's wait_for_ready analogue). The reference has
        # this race unhandled (its CI boots the broker before all ranks).
        topic = self._pub_topic(int(msg.get_receiver_id()))
        if self._mini is not None:
            self._mini.publish(topic, msg.to_bytes(), qos=1, retain=True)
            return
        self._client.publish(topic, payload=msg.to_bytes(), qos=1, retain=True)

    def stop_receive_message(self) -> None:
        super().stop_receive_message()
        if self._mini is not None:
            self._mini.close()
            return
        self._client.loop_stop()
        self._client.disconnect()
