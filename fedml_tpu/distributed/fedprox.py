"""Distributed FedProx — the FedAvg cross-process runtime + proximal clients.

Mirror of fedml_api/distributed/fedprox/ (6-file pattern). The reference's
distributed trainer is byte-identical to FedAvg's, i.e. the proximal term is
NOT implemented there (SURVEY.md §2.2); here the client's local fit carries
the published mu/2 ||w - w_global||^2 term via LocalSpec.prox_mu — the same
jitted local update the SPMD FedProxAPI uses, so the two runtimes stay
numerically aligned. With mu=0 this is exactly distributed FedAvg (the
reference's de-facto behavior).
"""

from __future__ import annotations

from fedml_tpu.algorithms.fedavg import FedAvgConfig, make_client_optimizer
from fedml_tpu.core.local import LocalSpec
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.api import init_client
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated


def prox_spec(cfg: FedAvgConfig, mu: float) -> LocalSpec:
    return LocalSpec(optimizer=make_client_optimizer(cfg), epochs=cfg.epochs,
                     prox_mu=mu, remat=cfg.remat)


def run_simulated(dataset, task, cfg: FedAvgConfig, mu: float = 0.1,
                  backend="LOOPBACK", job_id="fedprox-sim", base_port=50000):
    """All ranks as threads (mpirun-on-localhost analogue); returns the
    aggregator with .net/.history."""
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port)
    aggregator = FedAvgAggregator(dataset, task, cfg, worker_num=size - 1)
    server = FedAvgServerManager(aggregator, rank=0, size=size, backend=backend, **kw)
    clients = [
        init_client(dataset, task, cfg, r, size, backend,
                    local_spec=prox_spec(cfg, mu), **kw)
        for r in range(1, size)
    ]
    launch_simulated(server, clients)
    return aggregator
