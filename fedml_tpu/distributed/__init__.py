"""Cross-process distributed FL — the reference's second computing paradigm.

One OS process (or thread, under the loopback backend) per participant,
coordinated by typed messages over fedml_tpu/comm. Mirrors
fedml_api/distributed/<algo>/'s 6-file pattern (API / Aggregator / Trainer /
ServerManager / ClientManager / message_define — SURVEY.md §2.2) with the
torch local loops replaced by the jitted local-fit from fedml_tpu/core.

When to use which runtime:
- all clients simulated in one TPU job  -> fedml_tpu/algorithms (SPMD, fast)
- real federation across silos/devices  -> this package (gRPC over DCN)
"""
