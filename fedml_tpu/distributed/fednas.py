"""Distributed FedNAS — federated DARTS search over the cross-process runtime.

Mirror of fedml_api/distributed/fednas/ (6-file pattern): clients run the
bilevel DARTS search locally (FedNASTrainer.search, FedNASTrainer.py:34-50),
the server averages weights AND alphas (FedNASAggregator.__aggregate_weight
:71, __aggregate_alpha :95 — both live in the same params pytree here so one
weighted average covers both) and records the discovered genotype per round
(record_model_global_architecture, :173).

The client's alternating w/alpha local update is the exact jitted program
the SPMD FedNASAPI builds (algorithms/fednas.py), borrowed via a no-mesh
API instance, so the two runtimes stay numerically aligned.
"""

from __future__ import annotations

import logging

import jax

from fedml_tpu.algorithms.fedavg import FedAvgConfig
from fedml_tpu.algorithms.fednas import FedNASAPI
from fedml_tpu.models.darts import extract_genotype
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.client_manager import FedAvgClientManager
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.distributed.fedavg.trainer import DistributedTrainer
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated

log = logging.getLogger("fedml_tpu.distributed.fednas")


class FedNASTrainer(DistributedTrainer):
    """DistributedTrainer whose local fit is the bilevel w/alpha search.

    ``fit`` packs the (train, held-out) stream PAIR through the SPMD
    engine's own packer (FedNASAPI._pack_pair) with identical seeds and
    batch budgets, so the cross-process search stays batch-identical to the
    in-process simulation."""

    def __init__(self, client_rank, dataset, cfg, api: FedNASAPI):
        super().__init__(client_rank, dataset, api.task, cfg)
        self.api = api
        self.local_update = jax.jit(api.local_update)

    def fit(self, round_idx: int) -> int:
        cb = self.api._pack_pair([self.client_index], round_idx)
        rng = jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), round_idx)
        rng = jax.random.fold_in(rng, self.client_index)
        take0 = lambda pair: tuple(a[0] for a in pair)
        self.net, _metrics = self.local_update(
            rng, self.net, take0(cb.x), take0(cb.y), take0(cb.mask))
        return int(cb.num_samples[0])


class FedNASAggregator(FedAvgAggregator):
    """FedAvg collection/average + per-round genotype recording."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.genotype_history: list = []

    def aggregate(self):
        out = super().aggregate()
        self.genotype_history.append(extract_genotype(self.net.params))
        log.info("round genotype: %s", self.genotype_history[-1])
        return out


def run_simulated(dataset, cfg: FedAvgConfig, backend="LOOPBACK",
                  job_id="fednas-sim", base_port=50000, arch_lr: float = 3e-3,
                  layers: int = 2, init_filters: int = 8):
    """All ranks as threads (mpirun-on-localhost analogue); returns the
    aggregator with .net/.history/.genotype_history."""
    api = FedNASAPI(dataset, cfg, arch_lr=arch_lr, layers=layers,
                    init_filters=init_filters)
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port)
    aggregator = FedNASAggregator(dataset, api.task, cfg, worker_num=size - 1)
    server = FedAvgServerManager(aggregator, rank=0, size=size, backend=backend, **kw)
    clients = [
        FedAvgClientManager(FedNASTrainer(r, dataset, cfg, api),
                            rank=r, size=size, backend=backend, **kw)
        for r in range(1, size)
    ]
    launch_simulated(server, clients)
    return aggregator
