"""Distributed classical vertical FL — guest/host logit–gradient exchange.

Mirror of fedml_api/distributed/classical_vertical_fl/ (vfl_api.py:16-42):
the guest (rank 0) holds the labels and its own feature slice; each host
rank holds a disjoint feature slice. Per batch the hosts send their logit
contributions (HostTrainer), the guest sums them with its own, computes the
loss, and returns dL/dlogits (GuestTrainer.py:10-50); each host backprops
that cotangent through its tower via the VJP it cached at forward time.
Labels never leave the guest; raw features never leave any party.

The joint objective is identical to the SPMD VFLAPI's fused step
(algorithms/vfl.py) — same batch order, same SGD — so the two runtimes
produce the same parameters (tested to float tolerance).
"""

from __future__ import annotations

import logging
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algorithms.vfl import VFLConfig
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated
from fedml_tpu.comm.message import Message, pack_pytree

log = logging.getLogger("fedml_tpu.distributed.vfl")


class VFLMessage:
    MSG_TYPE_G2H_BATCH = 1   # batch indices for the next forward
    MSG_TYPE_H2G_LOGITS = 2  # host logit contribution
    MSG_TYPE_G2H_GRADS = 3   # dL/dlogits cotangent
    MSG_TYPE_G2H_FINISH = 4
    MSG_TYPE_H2G_DONE = 5    # final host params (for evaluation only)

    ARG_SEL = "sel"
    ARG_LOGITS = "logits"
    ARG_GRADS = "grads"
    ARG_PARAMS = "params"


class VFLGuestManager(ServerManager):
    """Rank 0: owns labels + guest tower; drives epochs/batches event-style —
    each full set of host logits advances one SGD step."""

    def __init__(self, guest_module, x_guest, y, cfg: VFLConfig,
                 rank=0, size=0, backend="LOOPBACK", **kw):
        self.gm, self.cfg = guest_module, cfg
        self.xg = np.asarray(x_guest, np.float32)
        self.y = np.asarray(y, np.int64)
        if len(self.y) < cfg.batch_size:
            # same contract as VFLAPI: the epoch loop bound (n - bs + 1)
            # trains zero batches below one batch of data
            raise ValueError(
                f"dataset ({len(self.y)} samples) smaller than one batch "
                f"({cfg.batch_size}): zero steps per epoch")
        self.H = size - 1

        key = jax.random.PRNGKey(cfg.seed)
        kg, _ = jax.random.split(key)
        self.guest_params = guest_module.init(
            kg, jnp.asarray(self.xg[: cfg.batch_size]), train=False)["params"]
        self.gtx = optax.sgd(cfg.guest_lr)
        self.gopt = self.gtx.init(self.guest_params)

        gm = guest_module

        @jax.jit
        def guest_step(gp, gopt, xg, y, host_sum):
            def loss_fn(gp_, host_sum_):
                logits = gm.apply({"params": gp_}, xg, train=True) + host_sum_
                l = jnp.mean(
                    optax.softmax_cross_entropy_with_integer_labels(logits, y))
                acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
                return l, acc

            (l, acc), (gg, glog_grad) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(gp, host_sum)
            ug, gopt = self.gtx.update(gg, gopt, gp)
            return optax.apply_updates(gp, ug), gopt, glog_grad, l, acc

        self._guest_step = guest_step
        self._host_logits: dict[int, np.ndarray] = {}
        self.host_params_final: dict[int, list] = {}
        self._order_rng = np.random.RandomState(cfg.seed)
        self.epoch = 0
        self.batch_start = 0
        self.order = self._order_rng.permutation(len(self.y))
        self.history: list[dict] = []
        self._epoch_losses: list[float] = []
        self._epoch_accs: list[float] = []
        self._lock = threading.Lock()
        super().__init__(rank, size, backend, **kw)

    def run(self):
        self._send_batch()
        super().run()

    def _send_batch(self):
        sel = self.order[self.batch_start : self.batch_start + self.cfg.batch_size]
        self._sel = sel
        for rank in range(1, self.size):
            msg = Message(VFLMessage.MSG_TYPE_G2H_BATCH, self.rank, rank)
            msg.add_params(VFLMessage.ARG_SEL, np.asarray(sel, np.int64))
            self.send_message(msg)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            VFLMessage.MSG_TYPE_H2G_LOGITS, self.handle_host_logits)
        self.register_message_receive_handler(
            VFLMessage.MSG_TYPE_H2G_DONE, self.handle_host_done)

    def handle_host_logits(self, msg_params):
        with self._lock:
            sender = msg_params[Message.MSG_ARG_KEY_SENDER]
            self._host_logits[sender] = msg_params[VFLMessage.ARG_LOGITS]
            if len(self._host_logits) < self.H:
                return
            host_sum = jnp.sum(
                jnp.stack([jnp.asarray(self._host_logits[r])
                           for r in sorted(self._host_logits)]), axis=0)
            self._host_logits.clear()
            sel = self._sel
            self.guest_params, self.gopt, glog_grad, l, acc = self._guest_step(
                self.guest_params, self.gopt, jnp.asarray(self.xg[sel]),
                jnp.asarray(self.y[sel]), host_sum)
            self._epoch_losses.append(float(l))
            self._epoch_accs.append(float(acc))
            grads = np.asarray(glog_grad)
            for rank in range(1, self.size):
                msg = Message(VFLMessage.MSG_TYPE_G2H_GRADS, self.rank, rank)
                msg.add_params(VFLMessage.ARG_GRADS, grads)
                self.send_message(msg)
            self._advance()

    def _advance(self):
        cfg = self.cfg
        n, bs = len(self.y), cfg.batch_size
        self.batch_start += bs
        if self.batch_start > n - bs:  # epoch done (same bound as VFLAPI.train)
            self.history.append({
                "epoch": self.epoch,
                "loss": float(np.mean(self._epoch_losses)),
                "acc": float(np.mean(self._epoch_accs)),
            })
            self._epoch_losses, self._epoch_accs = [], []
            self.epoch += 1
            if self.epoch == cfg.epochs:
                for rank in range(1, self.size):
                    self.send_message(
                        Message(VFLMessage.MSG_TYPE_G2H_FINISH, self.rank, rank))
                return  # finish once every host returned its params
            self.batch_start = 0
            self.order = self._order_rng.permutation(n)
        self._send_batch()

    def handle_host_done(self, msg_params):
        with self._lock:
            sender = msg_params[Message.MSG_ARG_KEY_SENDER]
            self.host_params_final[sender] = msg_params[VFLMessage.ARG_PARAMS]
            if len(self.host_params_final) == self.H:
                self.finish()


class VFLHostManager(ClientManager):
    """Rank h: owns feature slice x_host and its tower; never sees labels."""

    def __init__(self, host_module, x_host, cfg: VFLConfig, rank, size,
                 backend="LOOPBACK", **kw):
        self.hm, self.cfg = host_module, cfg
        self.xh = np.asarray(x_host, np.float32)

        key = jax.random.PRNGKey(cfg.seed)
        _, kh = jax.random.split(key)
        self.host_params = host_module.init(
            jax.random.fold_in(kh, rank - 1),
            jnp.asarray(self.xh[: cfg.batch_size]), train=False)["params"]
        self.htx = optax.sgd(cfg.host_lr)
        self.hopt = self.htx.init(self.host_params)

        hm = host_module

        @jax.jit
        def forward(hp, xb):
            return hm.apply({"params": hp}, xb, train=True)

        @jax.jit
        def backward(hp, hopt, xb, cot):
            def fwd(hp_):
                return hm.apply({"params": hp_}, xb, train=True)

            _, vjp = jax.vjp(fwd, hp)
            (g,) = vjp(cot)
            u, hopt = self.htx.update(g, hopt, hp)
            return optax.apply_updates(hp, u), hopt

        self._forward, self._backward = forward, backward
        self._xb = None
        super().__init__(rank, size, backend, **kw)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            VFLMessage.MSG_TYPE_G2H_BATCH, self.handle_batch)
        self.register_message_receive_handler(
            VFLMessage.MSG_TYPE_G2H_GRADS, self.handle_grads)
        self.register_message_receive_handler(
            VFLMessage.MSG_TYPE_G2H_FINISH, self.handle_finish)

    def handle_batch(self, msg_params):
        sel = np.asarray(msg_params[VFLMessage.ARG_SEL])
        self._xb = jnp.asarray(self.xh[sel])
        logits = self._forward(self.host_params, self._xb)
        msg = Message(VFLMessage.MSG_TYPE_H2G_LOGITS, self.rank, 0)
        msg.add_params(VFLMessage.ARG_LOGITS, np.asarray(logits))
        self.send_message(msg)

    def handle_grads(self, msg_params):
        cot = jnp.asarray(msg_params[VFLMessage.ARG_GRADS])
        self.host_params, self.hopt = self._backward(
            self.host_params, self.hopt, self._xb, cot)

    def handle_finish(self, _msg):
        msg = Message(VFLMessage.MSG_TYPE_H2G_DONE, self.rank, 0)
        msg.add_params(VFLMessage.ARG_PARAMS, pack_pytree(self.host_params))
        self.send_message(msg)
        self.finish()


def run_simulated(guest_module, host_module, x_guest, x_hosts, y,
                  cfg: VFLConfig, backend="LOOPBACK",
                  job_id="vfl-sim", base_port=50000):
    """All parties as threads (mpirun-on-localhost analogue). Returns the
    guest manager: .guest_params, .host_params_final (by rank), .history."""
    H = np.asarray(x_hosts).shape[0]
    size = H + 1
    kw = backend_kwargs(backend, job_id, base_port)
    guest = VFLGuestManager(guest_module, x_guest, y, cfg, rank=0, size=size,
                            backend=backend, **kw)
    hosts = [
        VFLHostManager(host_module, np.asarray(x_hosts)[h], cfg,
                       rank=h + 1, size=size, backend=backend, **kw)
        for h in range(H)
    ]
    launch_simulated(guest, hosts)
    return guest
