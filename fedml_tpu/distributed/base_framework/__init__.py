"""Base framework — minimal centralized message-round skeleton.

Mirror of fedml_api/distributed/base_framework/ (algorithm_api.py,
central_manager.py — SURVEY.md §2.2 'template for new algorithms'): a
coordinator broadcasts a payload, workers apply a local function and reply,
the coordinator reduces and starts the next round. Subclass or pass
``local_fn``/``reduce_fn`` to prototype a new distributed algorithm without
touching transport code.
"""

from fedml_tpu.distributed.base_framework.framework import (
    BaseClientManager,
    BaseServerManager,
    run_base_framework,
)

__all__ = ["BaseClientManager", "BaseServerManager", "run_base_framework"]
