"""Minimal centralized round skeleton over the comm layer.

Message flow (mirror of base_framework/central_manager.py +
algorithm_api.py): coordinator (rank 0) broadcasts MSG_BCAST with a payload
array; every worker applies ``local_fn(payload, rank, round)`` and replies
MSG_RESULT; coordinator applies ``reduce_fn([results])`` and either starts
the next round or broadcasts MSG_FINISH.
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np

from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message

MSG_BCAST = "base_bcast"
MSG_RESULT = "base_result"
MSG_FINISH = "base_finish"
KEY_PAYLOAD = "payload"
KEY_ROUND = "round_idx"


class BaseServerManager(ServerManager):
    def __init__(self, payload0: np.ndarray, reduce_fn: Callable, num_rounds: int,
                 rank=0, size=0, backend="LOOPBACK", **kw):
        self.payload = np.asarray(payload0)
        self.reduce_fn = reduce_fn
        self.num_rounds = num_rounds
        self.round_idx = 0
        self.results: dict[int, np.ndarray] = {}
        super().__init__(rank, size, backend, **kw)

    def run(self):
        self._broadcast()
        super().run()

    def _broadcast(self):
        for rank in range(1, self.size):
            msg = Message(MSG_BCAST, self.rank, rank)
            msg.add_params(KEY_PAYLOAD, self.payload)
            msg.add_params(KEY_ROUND, self.round_idx)
            self.send_message(msg)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_RESULT, self._on_result)

    def _on_result(self, params):
        self.results[params[Message.MSG_ARG_KEY_SENDER]] = params[KEY_PAYLOAD]
        if len(self.results) < self.size - 1:
            return
        self.payload = np.asarray(self.reduce_fn(
            [self.results[r] for r in sorted(self.results)]
        ))
        self.results.clear()
        self.round_idx += 1
        if self.round_idx >= self.num_rounds:
            for rank in range(1, self.size):
                self.send_message(Message(MSG_FINISH, self.rank, rank))
            self.finish()
            return
        self._broadcast()


class BaseClientManager(ClientManager):
    def __init__(self, local_fn: Callable, rank, size, backend="LOOPBACK", **kw):
        self.local_fn = local_fn
        super().__init__(rank, size, backend, **kw)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_BCAST, self._on_bcast)
        self.register_message_receive_handler(MSG_FINISH, lambda _m: self.finish())

    def _on_bcast(self, params):
        result = self.local_fn(
            params[KEY_PAYLOAD], self.rank, int(params[KEY_ROUND])
        )
        msg = Message(MSG_RESULT, self.rank, 0)
        msg.add_params(KEY_PAYLOAD, np.asarray(result))
        self.send_message(msg)


def run_base_framework(payload0, local_fn, reduce_fn, num_workers: int,
                       num_rounds: int, backend="LOOPBACK", job_id="base-fw",
                       **kw):
    """All ranks as threads (the mpirun-on-localhost analogue). Returns the
    final reduced payload."""
    size = num_workers + 1
    bkw = {"job_id": job_id} if backend.upper() == "LOOPBACK" else kw
    server = BaseServerManager(payload0, reduce_fn, num_rounds, 0, size, backend, **bkw)
    clients = [BaseClientManager(local_fn, r, size, backend, **bkw)
               for r in range(1, size)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30)
    return server.payload
