"""Hierarchical 2-tier cross-process FedAvg — edge aggregation ranks.

``algorithms/hierarchical.py`` simulates nested aggregation inside one
SPMD program; THIS module is the real cross-process topology the
reference's ``hierarchical_fl`` sketches: a layer of EDGE AGGREGATOR
ranks between the workers and the root, so the root's per-round fan-in
is O(edges) instead of O(clients) — the piece that lets the wire runtime
scale past one server's inbox (ROADMAP open item 4).

Rank layout (world size ``1 + E + W``)::

    rank 0            root server   (HierFedAvgServerManager)
    ranks 1..E        edge aggregators (FedAvgEdgeManager)
    ranks E+1..E+W    workers       (stock FedAvgClientManager,
                                     server_rank = their edge)

Each edge owns a CONTIGUOUS block of ``C = W/E`` cohort slots. Per round:
the root sends ONE frame per edge (model + that block's client
assignments); the edge fans it out, collects its children's uplinks,
gates non-finite updates (``robust_agg.nonfinite_gate`` — per-slot, so
verdicts match a flat server's exactly), and forwards ONE pre-aggregated
frame: the canonical pairwise weighted SUM of the surviving updates plus
the weight total (never a mean — the division happens once, at the
root). The root pairwise-folds the edge partials and divides.

**Exactness.** ``C`` must be a power of two (enforced): the edge blocks
are then aligned sub-trees of the canonical pairwise fold
(``robust_agg.pairwise_sum``), so the tree aggregate is BITWISE the flat
pairwise aggregate over the same cohort — model bits AND quarantine
ledger (a flat run opts into the same association with
``sum_assoc='pairwise'``; test- and ci.sh-enforced). Sample weights ride
the partials unscaled, so elastic partial rounds stay sample-weight
exact.

**Two-phase cross-tier robust gating** (docs/ROBUSTNESS.md §Cross-tier
robust gating): with ``aggregator=``/``sanitize=`` armed, every PR-4
defense composes with the tree. The edge computes per-client sanitation
EVIDENCE locally (update norms, non-finite flags, a fixed-size
count-sketch of the flattened update — ``robust_agg.update_evidence``)
and forwards one compact ``e2s_evidence`` frame while HOLDING the
staged, still-unaggregated uploads; the root runs the cohort-global
gate + estimator selection over the gathered evidence
(``evidence_verdicts`` — the same math a flat two-phase server runs,
which is what makes ledger parity exact) and answers each edge with a
per-slot ``s2e_verdict`` frame; the edge then pairwise-sums ONLY the
survivors (zero-weight replaced-by-global slots — the PR-4 survivor-
reweighting rule) and forwards one ordinary partial. Steady root
ingress stays O(edges) update frames; only O(cohort) scalar evidence
ever reaches the root (measured: ``comm_bytes_total{direction=
evidence|verdict}``). A crashed/partitioned edge inside
``round_timeout_s`` degrades to an elastic zero-term partial with its
whole block ledgered ``edge_lost``; verdict frames are retried/deduped
under chaos like any FMT2 frame.

Chaos (comm-manager wrap), telemetry (comm counters per link) and
tracing (root round traces cover the edge tier — its direct children)
ride the ordinary machinery on BOTH tiers.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.comm.managers import DistributedManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.robust_agg import (
    EVIDENCE_SKETCH_DIM,
    apply_verdicts,
    combine_edge_partials,
    edge_partial,
    evidence_verdicts,
    make_verdict_estimator,
    update_evidence,
)
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.message_define import MyMessage
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.obs import comm_instrument as _obs
from fedml_tpu.obs import perf_instrument as _perf

log = logging.getLogger("fedml_tpu.distributed.hierarchy")

# the cross-tier control plane's bytes are separable from the update
# traffic they exist to bound: comm_bytes_total{direction=evidence} must
# stay within the documented per-client scalar budget (the sketch row +
# norm/finite/weight), and {direction=verdict} within per-slot f32+i32
_obs.register_direction_override(
    MyMessage.MSG_TYPE_E2S_SEND_EVIDENCE_TO_SERVER, "evidence")
_obs.register_direction_override(
    MyMessage.MSG_TYPE_S2E_SEND_VERDICT_TO_EDGE, "verdict")


@dataclasses.dataclass(frozen=True)
class EdgeTopology:
    """The 2-tier rank map. ``workers % edges == 0`` and the block size
    ``workers // edges`` must be a power of two — that alignment is what
    makes tree ≡ flat bitwise (see module docstring)."""

    edges: int
    workers: int

    def __post_init__(self):
        if self.edges < 1 or self.workers < 1:
            raise ValueError(f"edges={self.edges} workers={self.workers} "
                             "must both be >= 1")
        if self.workers % self.edges:
            raise ValueError(
                f"workers={self.workers} not divisible by "
                f"edges={self.edges} — edge blocks must be equal")
        c = self.block
        if c & (c - 1):
            raise ValueError(
                f"edge block size {c} (= {self.workers}/{self.edges}) "
                "must be a power of two: blocks are then aligned "
                "sub-trees of the canonical pairwise fold, which is what "
                "keeps tree == flat bitwise")

    @property
    def block(self) -> int:
        return self.workers // self.edges

    @property
    def world_size(self) -> int:
        return 1 + self.edges + self.workers

    def edge_rank(self, edge_idx: int) -> int:
        return 1 + int(edge_idx)

    def worker_rank(self, slot: int) -> int:
        """Cohort slot (0-based) -> transport rank."""
        return 1 + self.edges + int(slot)

    def slot_of(self, worker_rank: int) -> int:
        return int(worker_rank) - 1 - self.edges

    def edge_of_slot(self, slot: int) -> int:
        return int(slot) // self.block

    def slots_of_edge(self, edge_idx: int) -> range:
        return range(int(edge_idx) * self.block,
                     (int(edge_idx) + 1) * self.block)


class HierFedAvgAggregator(FedAvgAggregator):
    """Root-side aggregator over EDGE partials: slots are edges, not
    workers; ``aggregate()`` pairwise-folds the staged (wsum, weight)
    pairs and divides once. Quarantine verdicts arrive pre-attributed by
    cohort slot, so the ledger matches a flat run entry-for-entry.

    ``aggregator=``/``sanitize=`` arm the two-phase cross-tier robust
    protocol (module docstring): this class then owns the phase-2 verdict
    computation — the jitted ``evidence_verdicts`` over the cohort
    evidence the server manager gathers — with the SAME estimator budget
    defaults as the flat ``FedAvgAggregator``."""

    def __init__(self, dataset, task, cfg, topology: EdgeTopology,
                 aggregator: str | None = None,
                 aggregator_params: dict | None = None,
                 sanitize: bool | float | None = None,
                 sketch_dim: int = EVIDENCE_SKETCH_DIM):
        if cfg.client_num_per_round != topology.workers:
            raise ValueError(
                f"client_num_per_round={cfg.client_num_per_round} != "
                f"topology workers={topology.workers}")
        super().__init__(dataset, task, cfg, worker_num=topology.edges)
        self.topology = topology
        # edge slot -> (wtotal, reasons, slots, clients); model_dict keeps
        # the wsum leaves so the inherited barrier bookkeeping applies
        self._edge_meta: dict[int, tuple] = {}
        self.fanin_history: list[int] = []
        self._combine = jax.jit(combine_edge_partials)
        # two-phase robust gating: same sanitize semantics as the flat
        # aggregator (None = armed iff a robust estimator is; the
        # non-finite rejection is unconditional either way — in plain
        # tree mode it runs at the edges, in robust mode at the gate)
        if sanitize is None:
            sanitize = aggregator is not None
        self.robust_mode = bool(aggregator is not None or sanitize)
        # the mean/sanitize-only verdict estimator reads no distances —
        # edges ship zero sketch bytes (norm/finite/weight only)
        self.sketch_dim = int(sketch_dim) if aggregator is not None else 0
        self._verdict_jit = None
        self.last_round_rejected: list[int] | None = None
        if self.robust_mode:
            from fedml_tpu.core.robust_agg import DEFAULT_NORM_MULT

            mult = (float("inf") if sanitize is False
                    else DEFAULT_NORM_MULT if sanitize is True
                    else float(sanitize))
            est = make_verdict_estimator(
                aggregator or "mean", n=topology.workers,
                **(aggregator_params or {}))
            self._verdict_jit = jax.jit(partial(
                evidence_verdicts, verdict_fn=est, norm_mult=mult))

    def add_edge_result(self, edge_idx: int, wsum_leaves, wtotal: float,
                        reasons, slots, clients,
                        round_idx: int | None = None,
                        samples: float | None = None) -> None:
        """Slot one edge's pre-aggregated uplink (the e2s_agg frame).
        Same stale/unknown rejection semantics as the per-worker path.
        ``wtotal`` is the FOLD total (the division's denominator half —
        verdict-weight mass under two-phase gating); ``samples`` the raw
        client-reported mass for telemetry (defaults to ``wtotal`` for
        frames from pre-cross-tier edges)."""
        if edge_idx not in self.flag_client_model_uploaded:
            from fedml_tpu.obs import comm_instrument as _obs

            _obs.record_stale_upload("unknown_rank")
            log.warning("reject edge partial for unknown edge index %s "
                        "(edges 0..%d)", edge_idx, self.worker_num - 1)
            return
        if round_idx is not None and int(round_idx) != self.current_round:
            from fedml_tpu.obs import comm_instrument as _obs

            _obs.record_stale_upload("stale")
            log.warning("reject out-of-round edge partial from edge %s "
                        "(tagged round %s, current %d)",
                        edge_idx, round_idx, self.current_round)
            return
        self.model_dict[edge_idx] = self._stage_upload(list(wsum_leaves))
        self.sample_num_dict[edge_idx] = float(
            wtotal if samples is None else samples)
        self._edge_meta[edge_idx] = (
            float(wtotal), np.asarray(reasons, np.int32),
            [int(s) for s in slots], [int(c) for c in clients])
        self.flag_client_model_uploaded[edge_idx] = True

    def _aggregate_core(self):
        import time as _time

        from fedml_tpu.comm.message import pack_pytree, unpack_pytree

        t0 = _time.perf_counter()
        edges = sorted(self.model_dict)
        if not edges:
            log.warning("round %d: no edge partials — keeping the "
                        "current global model", self.current_round)
            return
        # edge-failure elasticity: a block whose partial never arrived
        # (crashed/partitioned edge rank — the round already degraded to
        # an elastic zero-term partial) is ledgered slot-by-slot as
        # 'edge_lost' with the clients that block would have trained, so
        # the loss is attributable and counted
        # (fed_updates_rejected_total{reason=edge_lost})
        missing = [e for e in range(self.topology.edges)
                   if e not in self.model_dict]
        if missing:
            ids = self.client_sampling(self.current_round)
            for e in missing:
                for s in self.topology.slots_of_edge(e):
                    self.quarantine.record(self.current_round, s + 1,
                                           "edge_lost", client=int(ids[s]))
                    _obs.record_update_rejected("edge_lost")
            log.warning("round %d: edge partial(s) %s lost — their blocks "
                        "fold as zero terms (ledgered edge_lost)",
                        self.current_round, missing)
        # per-edge rejection counts for the round record's hier block: a
        # reporting edge contributes its verdict rejects, a lost edge its
        # whole block
        self.last_round_rejected = [
            int(np.count_nonzero(self._edge_meta[e][1]))
            if e in self._edge_meta else self.topology.block
            for e in range(self.topology.edges)]
        stacked = [
            jnp.stack([jnp.asarray(self.model_dict[e][i]) for e in edges])
            for i in range(len(self.model_dict[edges[0]]))
        ]
        # the combine's denominator is the FOLD total each edge shipped
        # (verdict-weight mass under two-phase gating) — sample_num_dict
        # holds the raw telemetry mass and must never steer the division
        totals = jnp.asarray([self._edge_meta[e][0] for e in edges],
                             jnp.float32)
        global_leaves = [jnp.asarray(v) for v in pack_pytree(self.net)]
        avg_leaves, total_w = self._combine(stacked, totals, global_leaves)
        self.fanin_history.append(len(edges))
        _perf.record_agg_bytes(self._state_placement,
                               self._model_nbytes * len(edges))
        # fold every edge's per-child verdicts into the root ledger with
        # the COHORT-SLOT rank (slot + 1) — the same attribution the flat
        # aggregator records, so tree and flat ledgers compare equal
        for e in edges:
            _, reasons, slots, clients = self._edge_meta[e]
            if reasons.any():
                self.quarantine.record_codes(
                    self.current_round, reasons,
                    clients=clients, ranks=[s + 1 for s in slots])
        if float(total_w) == 0.0 and any(
                self._edge_meta[e][1].any() for e in edges):
            log.warning("round %d: every child quarantined — keeping the "
                        "current global model", self.current_round)
        self.net = unpack_pytree(self.net, avg_leaves)
        self.model_dict.clear()
        self.sample_num_dict.clear()
        self._edge_meta.clear()
        log.info("hier aggregate (%d edge partials): %.3fs",
                 len(edges), _time.perf_counter() - t0)


class FedAvgEdgeManager(DistributedManager):
    """One edge aggregator rank: relay downlinks to its worker block,
    tree-reduce their sanitized uplinks, forward one partial to the root.

    Stateless across rounds except the held broadcast (the gate's
    replacement value) — a restarted edge rejoins at the next broadcast.
    With ``round_timeout_s`` armed, a stalled block forwards a PARTIAL
    (missing children carry zero weight and the global value — zero terms
    in the canonical fold), the edge-tier analogue of elastic partial
    aggregation."""

    def __init__(self, rank: int, topology: EdgeTopology,
                 backend: str = "LOOPBACK",
                 round_timeout_s: float | None = None,
                 robust: bool = False,
                 sketch_dim: int = EVIDENCE_SKETCH_DIM,
                 fused: bool = False, **kw):
        self.topology = topology
        self.edge_idx = rank - 1
        if not 0 <= self.edge_idx < topology.edges:
            raise ValueError(f"rank {rank} is not an edge rank "
                             f"(edges are 1..{topology.edges})")
        self._slots = list(topology.slots_of_edge(self.edge_idx))
        self._round: int | None = None
        self._global = None          # held broadcast leaves (gate value)
        self._clients: list[int] = []  # this block's client assignment
        self._uploads: dict[int, tuple] = {}  # local idx -> (leaves, n)
        self._forwarded = False
        self._lock = threading.Lock()
        self._partial = jax.jit(edge_partial)
        # fused on-device ingest at the edge tier (docs/PERFORMANCE.md
        # §Fused aggregation): each child upload folds (plain) or stages
        # with its evidence row (robust) in the per-arrival jit, so the
        # block never materializes a host stack — the uplink frames are
        # bit-identical to the stacked edge's (flush_block_partial /
        # block_evidence replay the _stack_block hole fill at position)
        self.fused = bool(fused)
        self._fused_round = None     # rebuilt per downlink (new global)
        self._fused_ingest = None    # jit, built once (static leaf meta)
        self._sketch_dim = int(sketch_dim)
        # two-phase robust gating (module docstring): this edge forwards
        # EVIDENCE first, holds the staged uploads, and folds only the
        # survivors the root's verdict frame names
        self.robust = bool(robust)
        self._evidence_jit = jax.jit(partial(update_evidence,
                                             sketch_dim=int(sketch_dim)))
        self._apply_jit = jax.jit(apply_verdicts)
        self._evidence_sent = False
        self._staged: tuple | None = None  # (stacked, global) held for phase 3
        self._last_partial: tuple | None = None  # retransmit cache
        # fleet plane (obs/fleet.py): the root's downlink marker arms the
        # lazy digest emitter; children's uplink digests fold into ONE
        # blob on this edge's partial so root ingress stays O(edges)
        self._fleet_marker: dict | None = None
        self._digest = None
        self._child_digests: dict[int, dict] = {}
        ts = kw.pop("timeout_s", None)
        self.round_timeout_s = round_timeout_s
        super().__init__(rank, topology.world_size, backend,
                         timeout_s=round_timeout_s or ts, **kw)

    def send_message(self, msg) -> None:
        """Elastic sends on the edge tier: with ``round_timeout_s`` armed,
        an unreachable CHILD (crashed worker — chaos raises
        ConnectionError) just misses this round's fan-out and the elastic
        block partial covers it; an unreachable ROOT drops this uplink and
        the root watchdog's re-broadcast owns recovery. Without a round
        deadline, delivery failures stay fatal (same policy as the flat
        server manager)."""
        try:
            super().send_message(msg)
        except Exception as e:
            if self.round_timeout_s is None or \
                    not FedAvgServerManager._is_transport_error(e):
                raise
            log.warning("edge %d: dropping undeliverable send to rank %s",
                        self.edge_idx, msg.get_receiver_id(), exc_info=True)

    # ------------------------------------------------------------ handlers
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG,
            partial(self._handle_downlink,
                    MyMessage.MSG_TYPE_S2C_INIT_CONFIG))
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            partial(self._handle_downlink,
                    MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT))
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self._handle_child_upload)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2E_SEND_VERDICT_TO_EDGE,
            self._handle_verdict)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_RESUME_PROBE,
            self._handle_resume_probe)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self._handle_finish)

    def _handle_downlink(self, msg_type: str, msg_params) -> None:
        """Root -> edge: hold the model, fan the SAME frame type out to
        this block's workers (each with its own client assignment)."""
        with self._lock:
            self._round = int(msg_params[MyMessage.MSG_ARG_KEY_ROUND])
            self._global = list(
                msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS])
            self._clients = [
                int(c) for c in
                msg_params[MyMessage.MSG_ARG_KEY_CHILD_CLIENTS]]
            self._uploads = {}
            self._forwarded = False
            self._evidence_sent = False
            self._staged = None
            self._last_partial = None
            if self.fused:
                from fedml_tpu.core import fused_agg as _fused

                glob = [jnp.asarray(g) for g in self._global]
                if self._fused_ingest is None:
                    meta = _fused._leaf_meta(glob)
                    self._fused_meta = meta
                    # edge uplinks are dense by protocol (the encoded-
                    # uplink refusal below), so ONE jit covers every
                    # child — built once, leaf meta is round-invariant
                    self._fused_ingest = (
                        _fused.make_fused_robust_ingest(
                            "dense", meta, self._sketch_dim)
                        if self.robust else
                        _fused.make_fused_ingest("dense", meta))
                self._fused_round = _fused.FusedRoundIngest(
                    glob, self._fused_meta, staged=self.robust)
            # fleet marker: the edge REBUILDS worker frames, so the
            # enablement marker must be explicitly relayed (like every
            # other side-band key) or the workers never start digesting
            tmark = msg_params.get(MyMessage.MSG_ARG_KEY_TELEMETRY)
            self._fleet_marker = tmark if isinstance(tmark, dict) else None
            self._child_digests = {}
            if self._fleet_marker is not None:
                if self._digest is None:
                    from fedml_tpu.obs.fleet import DigestEmitter

                    self._digest = DigestEmitter(self.rank)
                self._digest.on_downlink(self._fleet_marker)
        for i, slot in enumerate(self._slots):
            msg = Message(msg_type, self.rank,
                          self.topology.worker_rank(slot))
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, self._global)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                           self._clients[i])
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self._round)
            if self._fleet_marker is not None:
                msg.add_params(MyMessage.MSG_ARG_KEY_TELEMETRY,
                               self._fleet_marker)
            self.send_message(msg)

    def _handle_child_upload(self, msg_params) -> None:
        sender = int(msg_params[Message.MSG_ARG_KEY_SENDER])
        slot = self.topology.slot_of(sender)
        with self._lock:
            if self._round is None:
                return
            # fleet digest: collected on ARRIVAL, before any round/dedup
            # gate — even a stale or late upload proves the rank is alive,
            # and the fold below only keeps the latest blob per child
            dig = msg_params.get(MyMessage.MSG_ARG_KEY_TELEMETRY)
            if isinstance(dig, dict):
                self._child_digests[sender] = dig
            tag = msg_params.get(MyMessage.MSG_ARG_KEY_ROUND, self._round)
            if int(tag) != self._round:
                from fedml_tpu.obs import comm_instrument as _obs

                _obs.record_stale_upload("stale")
                log.warning("edge %d: drop stale upload from rank %d "
                            "(round %s, now %d)", self.edge_idx, sender,
                            tag, self._round)
                return
            local = slot - self._slots[0]
            if not 0 <= local < len(self._slots):
                from fedml_tpu.obs import comm_instrument as _obs

                _obs.record_stale_upload("unknown_rank")
                log.warning("edge %d: upload from rank %d outside this "
                            "block (slots %s)", self.edge_idx, sender,
                            self._slots)
                return
            if local in self._uploads or self._forwarded:
                return  # chaos-duplicated upload: exactly-once folding
            if self._evidence_sent:
                # the evidence cut already happened: the root's verdicts
                # were computed over a snapshot that scored this slot
                # absent (weight 0) — folding it now would desync the
                # partial from the verdict frame
                _obs.record_stale_upload("stale")
                log.warning("edge %d: drop upload from rank %d — arrived "
                            "after the round %s evidence cut", self.edge_idx,
                            sender, self._round)
                return
            if (MyMessage.MSG_ARG_KEY_SPARSE_IDX in msg_params
                    or MyMessage.MSG_ARG_KEY_UPDATE_CODEC in msg_params):
                raise RuntimeError(
                    "encoded uplinks (top-k / delta / quantized) are not "
                    "wired through edge aggregators — run the flat "
                    "topology or the dense protocol")
            nsamp = float(msg_params[MyMessage.MSG_ARG_KEY_NUM_SAMPLES])
            if self.fused:
                # fold (plain) / stage+evidence (robust) on device at
                # arrival; the host keeps only the (arrived, nsamp)
                # bookkeeping the completion check and frame need
                self._fused_round.add(
                    local, self._fused_ingest,
                    list(msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS]),
                    None, None, nsamp)
                self._uploads[local] = (None, nsamp)
            else:
                self._uploads[local] = (
                    list(msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS]),
                    nsamp)
            if len(self._uploads) == len(self._slots):
                if self.robust:
                    self._forward_evidence()
                else:
                    self._forward_partial()

    def _stack_block(self):
        """(stacked, global, weights) over this block's slots — missing
        children (elastic timeout) carry zero weight and the global value,
        exact zero terms in any downstream fold. Caller holds _lock."""
        C = len(self._slots)
        stacked = []
        for i, g in enumerate(self._global):
            g = np.asarray(g)
            rows = [np.asarray(self._uploads[local][0][i])
                    if local in self._uploads else g
                    for local in range(C)]
            stacked.append(jnp.stack([jnp.asarray(r) for r in rows]))
        weights = jnp.asarray(
            [self._uploads[local][1] if local in self._uploads else 0.0
             for local in range(C)], jnp.float32)
        return stacked, [jnp.asarray(g) for g in self._global], weights

    def _send_partial_frame(self, wsum, total, reasons) -> None:
        """One e2s_agg frame to the root — the same shape whether the
        verdicts came from the local non-finite gate (single-phase) or the
        root's cross-tier verdict frame (two-phase). The payload is cached
        so a verdict retry can retransmit it bit-identically (a dropped
        PARTIAL heals through the same retry that heals a dropped
        verdict). Caller holds _lock."""
        self._last_partial = (wsum, total, reasons)
        msg = Message(MyMessage.MSG_TYPE_E2S_SEND_AGG_TO_SERVER,
                      self.rank, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_WSUM,
                       [np.asarray(v) for v in wsum])
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_WEIGHT, float(total))
        # telemetry: the raw sample mass that ARRIVED (pre-gate/verdict),
        # so the root's round record reads client-reported samples like a
        # flat run's, whatever the verdict weights folded to
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_SAMPLES,
                       float(sum(u[1] for u in self._uploads.values())))
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_REASONS,
                       np.asarray(reasons, np.int32))
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_SLOTS,
                       [int(s) for s in self._slots])
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_CLIENTS,
                       list(self._clients))
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self._round)
        if self._fleet_marker is not None and self._digest is not None:
            # the folded blob: this edge's own digest + its block's child
            # digests under "block" — ONE side-band payload per edge frame,
            # so the root ingests the whole block while its ingress stays
            # O(edges). Built here (not cached) so a verdict-retry
            # retransmit carries fresh liveness; the model payload above
            # is still the cached bit-identical partial.
            from fedml_tpu.obs.fleet import attach_digest

            blob = self._digest.digest(self._round)
            blob["block"] = list(self._child_digests.values())
            attach_digest(msg, blob)
        self._forwarded = True
        self.send_message(msg)

    def _forward_partial(self) -> None:
        """Single-phase (no robust gating): local non-finite gate + the
        canonical pairwise partial over this block. Caller holds _lock."""
        if self.fused:
            # the per-arrival folds already happened; collapse with the
            # _stack_block hole fill at position — bitwise the stacked
            # edge's partial (zero-weight terms are exact f32 zeros)
            wsum, total, reasons = self._fused_round.flush_block_partial(
                len(self._slots))
        else:
            stacked, glob, weights = self._stack_block()
            wsum, total, reasons = self._partial(stacked, glob, weights)
        self._send_partial_frame(wsum, total, reasons)

    def _forward_evidence(self) -> None:
        """Phase 1 of the two-phase protocol: per-slot sanitation evidence
        to the root; the staged uploads stay HERE until the verdict frame
        names the survivors. Caller holds _lock."""
        if self.fused:
            # per-arrival rows assembled with zero-filled holes — bitwise
            # the stacked edge's update_evidence over the _stack_block
            # fill (a global-model slot's norm/sketch/weight are exact
            # +0.0; finite True). The raw staged slots stay device-
            # resident for phase 3 (block_stacked at verdict receipt).
            ev = self._fused_round.block_evidence(len(self._slots),
                                                  self._sketch_dim)
            self._staged = ("fused", None)
        else:
            stacked, glob, weights = self._stack_block()
            self._staged = (stacked, glob)
            ev = self._evidence_jit(stacked, glob, weights)
        msg = Message(MyMessage.MSG_TYPE_E2S_SEND_EVIDENCE_TO_SERVER,
                      self.rank, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_EVIDENCE_NORM,
                       np.asarray(ev["norm"], np.float32))
        msg.add_params(MyMessage.MSG_ARG_KEY_EVIDENCE_FINITE,
                       np.asarray(ev["finite"], np.int32))
        msg.add_params(MyMessage.MSG_ARG_KEY_EVIDENCE_SKETCH,
                       np.asarray(ev["sketch"], np.float32))
        msg.add_params(MyMessage.MSG_ARG_KEY_EVIDENCE_WEIGHT,
                       np.asarray(ev["weight"], np.float32))
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_SLOTS,
                       [int(s) for s in self._slots])
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_CLIENTS,
                       list(self._clients))
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self._round)
        self._evidence_sent = True
        self.send_message(msg)

    def _handle_verdict(self, msg_params) -> None:
        """Phase 3: fold ONLY the survivors the root's verdict names
        (zero-weight slots replaced by the held global — the PR-4
        survivor-reweighting rule) and forward the ordinary partial.
        Stale verdicts are dropped by the round tag; a RETRIED verdict
        for a round this edge already folded retransmits the cached
        partial verbatim instead — the root's retry cannot tell a
        dropped verdict from a dropped partial, and the fold must stay
        exactly-once either way (add_edge_result re-slots the identical
        bits; a superseded round's copy dies at the root's round gate)."""
        with self._lock:
            if self._round is None:
                return
            tag = msg_params.get(MyMessage.MSG_ARG_KEY_ROUND, self._round)
            if int(tag) != self._round:
                _obs.record_stale_upload("stale")
                log.warning("edge %d: drop stale verdict (round %s, now "
                            "%d)", self.edge_idx, tag, self._round)
                return
            if self._forwarded:
                if self._last_partial is not None:
                    log.warning("edge %d: verdict retry for round %d — "
                                "retransmitting the cached partial",
                                self.edge_idx, self._round)
                    self._send_partial_frame(*self._last_partial)
                return
            if not self._evidence_sent or self._staged is None:
                log.warning("edge %d: verdict for round %d before this "
                            "edge sent evidence — dropped (root retry "
                            "covers it)", self.edge_idx, self._round)
                return
            vw = np.asarray(
                msg_params[MyMessage.MSG_ARG_KEY_VERDICT_WEIGHTS],
                np.float32)
            reasons = np.asarray(
                msg_params[MyMessage.MSG_ARG_KEY_VERDICT_REASONS], np.int32)
            if self.fused:
                stacked = self._fused_round.block_stacked(len(self._slots))
                glob = [jnp.asarray(g) for g in self._global]
            else:
                stacked, glob = self._staged
            wsum, total = self._apply_jit(stacked, glob, jnp.asarray(vw))
            self._staged = None
            self._send_partial_frame(wsum, total, reasons)

    def on_timeout(self, idle_s: float) -> None:
        """Elastic edge tier: a block stalled past round_timeout_s
        forwards the partial (or, in two-phase mode, its EVIDENCE — the
        missing children score absent and the verdict round proceeds)
        over the children that DID report."""
        with self._lock:
            if (self._round is None or self._forwarded
                    or self.round_timeout_s is None):
                return
            if self.robust and self._evidence_sent:
                # phase 2 wait: the verdict frame is the root's to retry
                # (its watchdog re-sends to edges whose partial is missing)
                log.warning("edge %d: round %d evidence sent %.1fs ago, "
                            "no verdict yet — waiting (root watchdog owns "
                            "the retry)", self.edge_idx, self._round,
                            idle_s)
                return
            if not self._uploads:
                log.error("edge %d: round %d stalled %.1fs with no child "
                          "uploads — waiting (root watchdog owns "
                          "recovery)", self.edge_idx, self._round, idle_s)
                return
            missing = [self._slots[0] + i for i in range(len(self._slots))
                       if i not in self._uploads]
            log.warning("edge %d: elastic %s over %d/%d children "
                        "(missing slots %s after %.1fs)", self.edge_idx,
                        "evidence" if self.robust else "partial",
                        len(self._uploads), len(self._slots), missing,
                        idle_s)
            if self.robust:
                self._forward_evidence()
            else:
                self._forward_partial()

    def _handle_resume_probe(self, msg_params) -> None:
        """A recovered root probes EVERY rank (edges included — the root
        can't tell tiers apart at probe time). Answer with this edge's
        last-seen round; workers answer the same probe directly (their
        ack goes to the probe's sender, rank 0, not through this edge)."""
        with self._lock:
            last = -1 if self._round is None else int(self._round)
        msg = Message(MyMessage.MSG_TYPE_C2S_RESUME_ACK, self.rank, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_LAST_SEEN_ROUND, last)
        msg.add_params(MyMessage.MSG_ARG_KEY_LAST_SEEN_WAVE, -1)
        self.send_message(msg)

    def _handle_finish(self, _msg) -> None:
        self.finish()


class HierFedAvgServerManager(FedAvgServerManager):
    """The root of the 2-tier topology: broadcasts one frame per EDGE and
    advances rounds on E edge partials. Everything else — elastic
    timeout, checkpoint/resume, telemetry, tracing (the edge tier is the
    traced cohort) — is the stock server manager."""

    def __init__(self, aggregator: HierFedAvgAggregator, topology=None,
                 **kw):
        if not isinstance(aggregator, HierFedAvgAggregator):
            raise TypeError("HierFedAvgServerManager needs a "
                            "HierFedAvgAggregator")
        self.topology = topology or aggregator.topology
        for flag, name in ((kw.get("async_buffer_k"), "async_buffer_k"),
                           (kw.get("delta_broadcast"), "delta_broadcast"),
                           (kw.get("heartbeat_max_age_s"),
                            "heartbeat_max_age_s"),
                           # rank-level churn: the tree's edge/worker
                           # ranks are infrastructure slots, not devices —
                           # client-level churn (cfg.churn_trace, cohort
                           # sampling) is the axis that composes with it
                           (kw.get("churn_trace"), "churn_trace")):
            if flag:
                raise ValueError(
                    f"{name} is not wired through edge aggregators — run "
                    "the flat topology for that mode")
        # two-phase robust gating state (all touched under _round_lock):
        # per-edge staged evidence, whether this round's verdicts went
        # out (and when — the hier record's verdict round-trip latency),
        # and the one-retry latch for chaos-dropped verdict frames
        self._robust = aggregator.robust_mode
        self._edge_evidence: dict[int, dict] = {}
        self._verdict_pack = None       # (vweights [K], reasons [K])
        self._verdict_sent = False
        self._verdict_retried = False
        self._verdict_t: float | None = None
        self._last_verdict_rtt: float | None = None
        super().__init__(aggregator, **kw)

    def _validate_world_size(self, size: int) -> None:
        if size != self.topology.world_size:
            raise ValueError(
                f"world size {size} != 1 + {self.topology.edges} edges + "
                f"{self.topology.workers} workers")

    def register_message_receive_handlers(self):
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_E2S_SEND_AGG_TO_SERVER,
            self.handle_message_edge_partial)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_E2S_SEND_EVIDENCE_TO_SERVER,
            self.handle_message_edge_evidence)

    def _round_record_extra(self) -> dict:
        hist = self.aggregator.fanin_history
        hier = {"edges": self.topology.edges,
                "block": self.topology.block,
                "fan_in": hist[-1] if hist else 0}
        # per-edge rejection counts (verdict rejects; a lost edge counts
        # its whole block) and the verdict round-trip latency — absent on
        # pre-cross-tier logs, and report.py hides the columns then
        rej = self.aggregator.last_round_rejected
        if rej is not None:
            hier["rejected"] = list(rej)
        if self._robust and self._last_verdict_rtt is not None:
            hier["verdict_rtt_s"] = round(self._last_verdict_rtt, 6)
        return {"hier": hier, **super()._round_record_extra()}

    def _broadcast_model(self, msg_type: str, global_params) -> None:
        """One frame per EDGE (fan-out O(edges)): the model + that edge
        block's client assignments + the round tag."""
        from fedml_tpu.comm.message import codec_roundtrip
        from fedml_tpu.obs.tracing import TRACE_KEY

        # same crash/journal choreography as the flat broadcast: the
        # between-commits point fires BEFORE any frame leaves, the round
        # opening is journaled so recovery knows round r was in flight
        self._maybe_crash("broadcast")
        if self.wal is not None:
            self.wal.append("broadcast", sync=True, round=self.round_idx)
        self._uploads_this_round = 0
        topo = self.topology
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        self._round_ids = [int(c) for c in client_indexes]
        self.aggregator.begin_round(self.round_idx)
        # fresh verdict phase: a re-broadcast of a stalled round restarts
        # the evidence gathering from scratch (edges reset on downlink)
        self._edge_evidence = {}
        self._verdict_pack = None
        self._verdict_sent = False
        self._verdict_retried = False
        self._verdict_t = None
        # stash AS CLIENTS SEE IT, like the flat path (frame codec round
        # trip) — tree mode refuses encoded uplinks, but the stash keeps
        # the versioned-base bookkeeping uniform
        self._bcast_leaves = codec_roundtrip(global_params)
        self._stash_version(self.round_idx, self._bcast_leaves)
        tr = self._dtracer
        if tr is not None:
            tr.begin_round(self.round_idx)
        for e in range(topo.edges):
            rank = topo.edge_rank(e)
            msg = Message(msg_type, self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                           global_params)
            msg.add_params(
                MyMessage.MSG_ARG_KEY_CHILD_CLIENTS,
                [int(client_indexes[s]) for s in topo.slots_of_edge(e)])
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            if tr is not None:
                msg.add_params(TRACE_KEY, tr.broadcast_ctx(rank))
            if self._fleet is not None:
                msg.add_params(MyMessage.MSG_ARG_KEY_TELEMETRY,
                               self._fleet.marker())
            self.send_message(msg)
        if tr is not None:
            tr.end_broadcast()
        # broadcast out, zero partials accepted — the after_uploads=0 point
        self._maybe_crash("post_broadcast")

    def handle_message_edge_evidence(self, msg_params) -> None:
        """Phase 2 intake: stage one edge's per-slot evidence; once every
        edge reported (the elastic watchdog covers the rest), run the
        cohort-global verdict computation and answer each reporting edge
        with its block's verdict frame."""
        with self._round_lock:
            sender = int(msg_params[Message.MSG_ARG_KEY_SENDER])
            edge_idx = sender - 1
            msg_round = msg_params.get(MyMessage.MSG_ARG_KEY_ROUND,
                                       self.round_idx)
            if int(msg_round) != self.round_idx:
                _obs.record_stale_upload("stale")
                log.warning("drop stale edge evidence from rank %d "
                            "(round %s, now %d)", sender, msg_round,
                            self.round_idx)
                return
            if not 0 <= edge_idx < self.topology.edges:
                _obs.record_stale_upload("unknown_rank")
                log.warning("drop evidence from non-edge rank %d", sender)
                return
            if self._verdict_sent or edge_idx in self._edge_evidence:
                # chaos duplicate, or evidence limping in after an elastic
                # partial-evidence verdict round — exactly-once staging
                _obs.record_stale_upload("stale")
                log.warning("drop late/duplicate evidence from edge %d "
                            "(round %d)", edge_idx, self.round_idx)
                return
            self._edge_evidence[edge_idx] = {
                "norm": np.asarray(
                    msg_params[MyMessage.MSG_ARG_KEY_EVIDENCE_NORM],
                    np.float32),
                "finite": np.asarray(
                    msg_params[MyMessage.MSG_ARG_KEY_EVIDENCE_FINITE],
                    np.int32),
                "sketch": np.asarray(
                    msg_params[MyMessage.MSG_ARG_KEY_EVIDENCE_SKETCH],
                    np.float32),
                "weight": np.asarray(
                    msg_params[MyMessage.MSG_ARG_KEY_EVIDENCE_WEIGHT],
                    np.float32),
            }
            if len(self._edge_evidence) == self.topology.edges:
                self._send_verdicts()

    def _send_verdicts(self) -> None:
        """Run ``evidence_verdicts`` over the gathered cohort evidence —
        the SAME jitted math a flat two-phase server runs, over the same
        [K]-shaped inputs, which is the bitwise half of the tree ≡ flat
        ledger contract — and fan one verdict frame out per reporting
        edge. Blocks with no evidence (crashed edge) score absent: zero
        weight, reasons OK here, ledgered edge_lost at aggregate time.
        Caller holds _round_lock."""
        import time as _time

        topo = self.topology
        K = topo.workers
        some = next(iter(self._edge_evidence.values()))
        norm = np.zeros((K,), np.float32)
        finite = np.ones((K,), bool)
        sketch = np.zeros((K, some["sketch"].shape[1]), np.float32)
        weight = np.zeros((K,), np.float32)
        for e, ev in self._edge_evidence.items():
            sl = slice(e * topo.block, (e + 1) * topo.block)
            norm[sl] = ev["norm"]
            finite[sl] = ev["finite"] != 0
            sketch[sl] = ev["sketch"]
            weight[sl] = ev["weight"]
        vw, reasons = self.aggregator._verdict_jit(
            {"norm": jnp.asarray(norm), "finite": jnp.asarray(finite),
             "sketch": jnp.asarray(sketch), "weight": jnp.asarray(weight)})
        self._verdict_pack = (np.asarray(vw, np.float32),
                              np.asarray(reasons, np.int32))
        for e in sorted(self._edge_evidence):
            self._send_verdict_frame(e)
        self._verdict_sent = True
        self._verdict_t = _time.monotonic()

    def _send_verdict_frame(self, edge_idx: int) -> None:
        """One s2e_verdict frame: that block's per-slot survivor weights +
        reason codes. Re-sent verbatim by the watchdog retry (the edge's
        _forwarded flag dedups). Caller holds _round_lock."""
        vw, reasons = self._verdict_pack
        topo = self.topology
        sl = slice(edge_idx * topo.block, (edge_idx + 1) * topo.block)
        msg = Message(MyMessage.MSG_TYPE_S2E_SEND_VERDICT_TO_EDGE,
                      self.rank, topo.edge_rank(edge_idx))
        msg.add_params(MyMessage.MSG_ARG_KEY_VERDICT_WEIGHTS, vw[sl])
        msg.add_params(MyMessage.MSG_ARG_KEY_VERDICT_REASONS, reasons[sl])
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
        self.send_message(msg)

    def handle_message_edge_partial(self, msg_params) -> None:
        from fedml_tpu.obs.tracing import TRACE_KEY

        with self._round_lock:
            sender = int(msg_params[Message.MSG_ARG_KEY_SENDER])
            msg_round = msg_params.get(MyMessage.MSG_ARG_KEY_ROUND,
                                       self.round_idx)
            if int(msg_round) != self.round_idx:
                _obs.record_stale_upload("stale")
                log.warning("drop stale edge partial from rank %d "
                            "(round %s, now %d)", sender, msg_round,
                            self.round_idx)
                return
            if self._dtracer is not None:
                self._dtracer.on_upload(sender,
                                        msg_params.get(TRACE_KEY))
            if self._fleet is not None:
                self._fleet.ingest(
                    msg_params.get(MyMessage.MSG_ARG_KEY_TELEMETRY))
            samples = msg_params.get(MyMessage.MSG_ARG_KEY_EDGE_SAMPLES)
            already = bool(self.aggregator.flag_client_model_uploaded.get(
                sender - 1))
            self.aggregator.add_edge_result(
                sender - 1,
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_WSUM],
                float(msg_params[MyMessage.MSG_ARG_KEY_EDGE_WEIGHT]),
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_REASONS],
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_SLOTS],
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_CLIENTS],
                round_idx=int(msg_round),
                samples=None if samples is None else float(samples))
            if (not already and self.aggregator
                    .flag_client_model_uploaded.get(sender - 1)):
                # the accepted partial is this tier's "upload": journal it
                # (fsync'd) so a crash before the commit ledgers the edge's
                # slot server_restart on recovery — and feed the
                # after_uploads crash points, which count edge partials in
                # tree mode (a verdict-retry retransmit stays dedup'd by
                # the `already` flag)
                self._uploads_this_round += 1
                if self.wal is not None:
                    self.wal.append("upload", sync=True,
                                    round=int(msg_round), rank=int(sender))
                self._maybe_crash("upload")
            if self._robust and self._verdict_t is not None:
                import time as _time

                # verdict round-trip latency: verdict fan-out -> the last
                # partial's arrival (the slowest edge's turn-around)
                self._last_verdict_rtt = _time.monotonic() - self._verdict_t
            if not self.aggregator.check_whether_all_receive():
                return
            self._advance_round()

    def on_timeout(self, idle_s: float):
        """Two-phase elastic recovery on top of the stock watchdog: a
        round stalled in phase 1 computes verdicts over the PARTIAL
        evidence (missing blocks score absent — the elastic zero-term
        partial); one stalled in phase 2 re-sends the verdict frames once
        (chaos may have dropped them — the edge dedups). Only then does
        the stock elastic machinery take over (partial aggregate over the
        partials that DID land, or the no-uploads re-broadcast)."""
        if self._robust:
            with self._round_lock:
                if (self.round_timeout_s is not None
                        and not self._finished.is_set()
                        and self.round_idx < self.round_num):
                    if self._edge_evidence and not self._verdict_sent:
                        missing = [e for e in range(self.topology.edges)
                                   if e not in self._edge_evidence]
                        log.warning(
                            "round %d: elastic verdicts over %d/%d edges' "
                            "evidence (missing edges %s after %.1fs)",
                            self.round_idx, len(self._edge_evidence),
                            self.topology.edges, missing, idle_s)
                        self._send_verdicts()
                        return
                    if self._verdict_sent and not self._verdict_retried:
                        waiting = [e for e in sorted(self._edge_evidence)
                                   if e not in self.aggregator.model_dict]
                        if waiting:
                            log.warning(
                                "round %d: verdict sent %.1fs ago, no "
                                "partial from edges %s — re-sending "
                                "verdict frames once", self.round_idx,
                                idle_s, waiting)
                            self._verdict_retried = True
                            for e in waiting:
                                self._send_verdict_frame(e)
                            return
        super().on_timeout(idle_s)


def run_simulated_hierarchical(
    dataset, task, cfg, edges: int, backend: str = "LOOPBACK",
    job_id: str = "fedavg-hier-sim", base_port: int = 50000,
    broker_host: str = "127.0.0.1", broker_port: int = 1883,
    ckpt_dir: str | None = None, telemetry=None, chaos_plan=None,
    round_timeout_s: float | None = None, adversary_plan=None,
    warmup: bool = False, aggregator: str | None = None,
    aggregator_params: dict | None = None,
    sanitize: bool | float | None = None,
    fused_agg: bool = False,
) -> HierFedAvgAggregator:
    """The 2-tier analogue of ``run_simulated``: 1 root + E edges + W
    workers as threads over the loopback (or localhost-gRPC) backend.
    ``cfg.client_num_per_round`` is W; worker slot s trains
    ``client_sampling(round)[s]`` exactly like the flat runtime, so the
    tree and flat cohorts coincide round-for-round.

    ``aggregator=``/``sanitize=`` arm the two-phase cross-tier robust
    protocol (module docstring) with the same semantics as the flat
    ``run_simulated`` — and an ``adversary_plan``'s 1-based ranks match
    workers by COHORT SLOT (slot + 1), not transport rank, so ONE plan
    drives a flat and a tree run identically."""
    from fedml_tpu import chaos as _chaos
    from fedml_tpu.distributed.fedavg.client_manager import (
        FedAvgClientManager,
    )
    from fedml_tpu.distributed.fedavg.trainer import DistributedTrainer
    from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated

    topo = EdgeTopology(edges=edges, workers=cfg.client_num_per_round)
    kw = backend_kwargs(backend, job_id, base_port, broker_host,
                        broker_port)
    if chaos_plan is not None:
        _chaos.install_plan(chaos_plan)
    try:
        # chaos crash rules naming rank 0 are supervised server restarts,
        # same contract as the flat driver: kill at the scheduled point
        # (SimulatedServerCrash — no farewell frames), recover a FRESH
        # root through checkpoint + WAL; edges reset their round state on
        # the recovered root's next downlink, so the tree needs no extra
        # resume protocol of its own
        active = _chaos.active_plan()
        crash_points = (active.server_crash_points()
                        if active is not None else [])
        if crash_points and ckpt_dir is None:
            raise ValueError(
                "a chaos crash rule naming rank 0 (server restart) needs "
                "ckpt_dir= — recovery replays checkpoint + WAL")

        def build_server():
            root_agg = HierFedAvgAggregator(
                dataset, task, cfg, topo, aggregator=aggregator,
                aggregator_params=aggregator_params, sanitize=sanitize)
            return HierFedAvgServerManager(
                root_agg, rank=0, size=topo.world_size, backend=backend,
                ckpt_dir=ckpt_dir, round_timeout_s=round_timeout_s,
                telemetry=telemetry, **kw)

        server = build_server()
        # the edge tier arms its elastic watchdog at HALF the root
        # deadline: tier-2 elasticity (a stalled block's evidence/partial)
        # resolves strictly before the root's own timeout acts, so the
        # chaos replay contract stays a property of the SEEDED schedule,
        # never of which watchdog thread happened to fire first
        edge_timeout = (round_timeout_s / 2.0
                        if round_timeout_s is not None else None)
        edge_mgrs = [
            # fused_agg is an EDGE-tier property in the tree: edges do
            # the fan-in ingest (the root folds O(edges) partial frames,
            # already cheap), and the fused block frames are bitwise the
            # stacked edge's, so the root is none the wiser
            FedAvgEdgeManager(topo.edge_rank(e), topo, backend=backend,
                              round_timeout_s=edge_timeout,
                              robust=server.aggregator.robust_mode,
                              sketch_dim=server.aggregator.sketch_dim,
                              fused=fused_agg,
                              **kw)
            for e in range(topo.edges)
        ]
        clients = []
        for slot in range(topo.workers):
            rank = topo.worker_rank(slot)
            trainer = DistributedTrainer(rank, dataset, task, cfg)
            clients.append(FedAvgClientManager(
                trainer, rank=rank, size=topo.world_size, backend=backend,
                server_rank=topo.edge_rank(topo.edge_of_slot(slot)),
                adversary_plan=adversary_plan,
                adversary_rank=slot + 1, **kw))
        if warmup and clients:
            from fedml_tpu.utils.metrics import enable_compile_cache

            enable_compile_cache()
            # one rank compiles, every sibling deserializes from disk
            clients[0].warmup()
        if not crash_points:
            launch_simulated(server, edge_mgrs + clients)
        else:
            # same supervision loop as the flat driver: edges and workers
            # run ONCE, spanning every root generation
            from fedml_tpu.distributed.fedavg.api import (
                run_supervised_simulated,
            )

            server = run_supervised_simulated(
                server, edge_mgrs + clients, crash_points, build_server)
    finally:
        if chaos_plan is not None:
            _chaos.install_plan(None)
    return server.aggregator
