"""Hierarchical 2-tier cross-process FedAvg — edge aggregation ranks.

``algorithms/hierarchical.py`` simulates nested aggregation inside one
SPMD program; THIS module is the real cross-process topology the
reference's ``hierarchical_fl`` sketches: a layer of EDGE AGGREGATOR
ranks between the workers and the root, so the root's per-round fan-in
is O(edges) instead of O(clients) — the piece that lets the wire runtime
scale past one server's inbox (ROADMAP open item 4).

Rank layout (world size ``1 + E + W``)::

    rank 0            root server   (HierFedAvgServerManager)
    ranks 1..E        edge aggregators (FedAvgEdgeManager)
    ranks E+1..E+W    workers       (stock FedAvgClientManager,
                                     server_rank = their edge)

Each edge owns a CONTIGUOUS block of ``C = W/E`` cohort slots. Per round:
the root sends ONE frame per edge (model + that block's client
assignments); the edge fans it out, collects its children's uplinks,
gates non-finite updates (``robust_agg.nonfinite_gate`` — per-slot, so
verdicts match a flat server's exactly), and forwards ONE pre-aggregated
frame: the canonical pairwise weighted SUM of the surviving updates plus
the weight total (never a mean — the division happens once, at the
root). The root pairwise-folds the edge partials and divides.

**Exactness.** ``C`` must be a power of two (enforced): the edge blocks
are then aligned sub-trees of the canonical pairwise fold
(``robust_agg.pairwise_sum``), so the tree aggregate is BITWISE the flat
pairwise aggregate over the same cohort — model bits AND quarantine
ledger (a flat run opts into the same association with
``sum_assoc='pairwise'``; test- and ci.sh-enforced). Sample weights ride
the partials unscaled, so elastic partial rounds stay sample-weight
exact. The norm-outlier gate and robust estimators need the full stacked
cohort and are refused in tree mode (docs/ROBUSTNESS.md §Hierarchical
tiers).

Chaos (comm-manager wrap), telemetry (comm counters per link) and
tracing (root round traces cover the edge tier — its direct children)
ride the ordinary machinery on BOTH tiers.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.comm.managers import DistributedManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.robust_agg import combine_edge_partials, edge_partial
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.message_define import MyMessage
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.obs import perf_instrument as _perf

log = logging.getLogger("fedml_tpu.distributed.hierarchy")


@dataclasses.dataclass(frozen=True)
class EdgeTopology:
    """The 2-tier rank map. ``workers % edges == 0`` and the block size
    ``workers // edges`` must be a power of two — that alignment is what
    makes tree ≡ flat bitwise (see module docstring)."""

    edges: int
    workers: int

    def __post_init__(self):
        if self.edges < 1 or self.workers < 1:
            raise ValueError(f"edges={self.edges} workers={self.workers} "
                             "must both be >= 1")
        if self.workers % self.edges:
            raise ValueError(
                f"workers={self.workers} not divisible by "
                f"edges={self.edges} — edge blocks must be equal")
        c = self.block
        if c & (c - 1):
            raise ValueError(
                f"edge block size {c} (= {self.workers}/{self.edges}) "
                "must be a power of two: blocks are then aligned "
                "sub-trees of the canonical pairwise fold, which is what "
                "keeps tree == flat bitwise")

    @property
    def block(self) -> int:
        return self.workers // self.edges

    @property
    def world_size(self) -> int:
        return 1 + self.edges + self.workers

    def edge_rank(self, edge_idx: int) -> int:
        return 1 + int(edge_idx)

    def worker_rank(self, slot: int) -> int:
        """Cohort slot (0-based) -> transport rank."""
        return 1 + self.edges + int(slot)

    def slot_of(self, worker_rank: int) -> int:
        return int(worker_rank) - 1 - self.edges

    def edge_of_slot(self, slot: int) -> int:
        return int(slot) // self.block

    def slots_of_edge(self, edge_idx: int) -> range:
        return range(int(edge_idx) * self.block,
                     (int(edge_idx) + 1) * self.block)


class HierFedAvgAggregator(FedAvgAggregator):
    """Root-side aggregator over EDGE partials: slots are edges, not
    workers; ``aggregate()`` pairwise-folds the staged (wsum, weight)
    pairs and divides once. Quarantine verdicts arrive pre-attributed by
    cohort slot, so the ledger matches a flat run entry-for-entry."""

    def __init__(self, dataset, task, cfg, topology: EdgeTopology):
        if cfg.client_num_per_round != topology.workers:
            raise ValueError(
                f"client_num_per_round={cfg.client_num_per_round} != "
                f"topology workers={topology.workers}")
        super().__init__(dataset, task, cfg, worker_num=topology.edges)
        self.topology = topology
        # edge slot -> (wtotal, reasons, slots, clients); model_dict keeps
        # the wsum leaves so the inherited barrier bookkeeping applies
        self._edge_meta: dict[int, tuple] = {}
        self.fanin_history: list[int] = []
        self._combine = jax.jit(combine_edge_partials)

    def add_edge_result(self, edge_idx: int, wsum_leaves, wtotal: float,
                        reasons, slots, clients,
                        round_idx: int | None = None) -> None:
        """Slot one edge's pre-aggregated uplink (the e2s_agg frame).
        Same stale/unknown rejection semantics as the per-worker path."""
        if edge_idx not in self.flag_client_model_uploaded:
            from fedml_tpu.obs import comm_instrument as _obs

            _obs.record_stale_upload("unknown_rank")
            log.warning("reject edge partial for unknown edge index %s "
                        "(edges 0..%d)", edge_idx, self.worker_num - 1)
            return
        if round_idx is not None and int(round_idx) != self.current_round:
            from fedml_tpu.obs import comm_instrument as _obs

            _obs.record_stale_upload("stale")
            log.warning("reject out-of-round edge partial from edge %s "
                        "(tagged round %s, current %d)",
                        edge_idx, round_idx, self.current_round)
            return
        self.model_dict[edge_idx] = self._stage_upload(list(wsum_leaves))
        self.sample_num_dict[edge_idx] = float(wtotal)
        self._edge_meta[edge_idx] = (
            np.asarray(reasons, np.int32),
            [int(s) for s in slots], [int(c) for c in clients])
        self.flag_client_model_uploaded[edge_idx] = True

    def _aggregate_core(self):
        import time as _time

        from fedml_tpu.comm.message import pack_pytree, unpack_pytree

        t0 = _time.perf_counter()
        edges = sorted(self.model_dict)
        if not edges:
            log.warning("round %d: no edge partials — keeping the "
                        "current global model", self.current_round)
            return
        stacked = [
            jnp.stack([jnp.asarray(self.model_dict[e][i]) for e in edges])
            for i in range(len(self.model_dict[edges[0]]))
        ]
        totals = jnp.asarray([self.sample_num_dict[e] for e in edges],
                             jnp.float32)
        global_leaves = [jnp.asarray(v) for v in pack_pytree(self.net)]
        avg_leaves, total_w = self._combine(stacked, totals, global_leaves)
        self.fanin_history.append(len(edges))
        _perf.record_agg_bytes(self._state_placement,
                               self._model_nbytes * len(edges))
        # fold every edge's per-child verdicts into the root ledger with
        # the COHORT-SLOT rank (slot + 1) — the same attribution the flat
        # aggregator records, so tree and flat ledgers compare equal
        for e in edges:
            reasons, slots, clients = self._edge_meta[e]
            if reasons.any():
                self.quarantine.record_codes(
                    self.current_round, reasons,
                    clients=clients, ranks=[s + 1 for s in slots])
        if float(total_w) == 0.0 and any(
                self._edge_meta[e][0].any() for e in edges):
            log.warning("round %d: every child quarantined — keeping the "
                        "current global model", self.current_round)
        self.net = unpack_pytree(self.net, avg_leaves)
        self.model_dict.clear()
        self.sample_num_dict.clear()
        self._edge_meta.clear()
        log.info("hier aggregate (%d edge partials): %.3fs",
                 len(edges), _time.perf_counter() - t0)


class FedAvgEdgeManager(DistributedManager):
    """One edge aggregator rank: relay downlinks to its worker block,
    tree-reduce their sanitized uplinks, forward one partial to the root.

    Stateless across rounds except the held broadcast (the gate's
    replacement value) — a restarted edge rejoins at the next broadcast.
    With ``round_timeout_s`` armed, a stalled block forwards a PARTIAL
    (missing children carry zero weight and the global value — zero terms
    in the canonical fold), the edge-tier analogue of elastic partial
    aggregation."""

    def __init__(self, rank: int, topology: EdgeTopology,
                 backend: str = "LOOPBACK",
                 round_timeout_s: float | None = None, **kw):
        self.topology = topology
        self.edge_idx = rank - 1
        if not 0 <= self.edge_idx < topology.edges:
            raise ValueError(f"rank {rank} is not an edge rank "
                             f"(edges are 1..{topology.edges})")
        self._slots = list(topology.slots_of_edge(self.edge_idx))
        self._round: int | None = None
        self._global = None          # held broadcast leaves (gate value)
        self._clients: list[int] = []  # this block's client assignment
        self._uploads: dict[int, tuple] = {}  # local idx -> (leaves, n)
        self._forwarded = False
        self._lock = threading.Lock()
        self._partial = jax.jit(edge_partial)
        ts = kw.pop("timeout_s", None)
        self.round_timeout_s = round_timeout_s
        super().__init__(rank, topology.world_size, backend,
                         timeout_s=round_timeout_s or ts, **kw)

    # ------------------------------------------------------------ handlers
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG,
            partial(self._handle_downlink,
                    MyMessage.MSG_TYPE_S2C_INIT_CONFIG))
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            partial(self._handle_downlink,
                    MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT))
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self._handle_child_upload)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self._handle_finish)

    def _handle_downlink(self, msg_type: str, msg_params) -> None:
        """Root -> edge: hold the model, fan the SAME frame type out to
        this block's workers (each with its own client assignment)."""
        with self._lock:
            self._round = int(msg_params[MyMessage.MSG_ARG_KEY_ROUND])
            self._global = list(
                msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS])
            self._clients = [
                int(c) for c in
                msg_params[MyMessage.MSG_ARG_KEY_CHILD_CLIENTS]]
            self._uploads = {}
            self._forwarded = False
        for i, slot in enumerate(self._slots):
            msg = Message(msg_type, self.rank,
                          self.topology.worker_rank(slot))
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, self._global)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                           self._clients[i])
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self._round)
            self.send_message(msg)

    def _handle_child_upload(self, msg_params) -> None:
        sender = int(msg_params[Message.MSG_ARG_KEY_SENDER])
        slot = self.topology.slot_of(sender)
        with self._lock:
            if self._round is None:
                return
            tag = msg_params.get(MyMessage.MSG_ARG_KEY_ROUND, self._round)
            if int(tag) != self._round:
                from fedml_tpu.obs import comm_instrument as _obs

                _obs.record_stale_upload("stale")
                log.warning("edge %d: drop stale upload from rank %d "
                            "(round %s, now %d)", self.edge_idx, sender,
                            tag, self._round)
                return
            local = slot - self._slots[0]
            if not 0 <= local < len(self._slots):
                from fedml_tpu.obs import comm_instrument as _obs

                _obs.record_stale_upload("unknown_rank")
                log.warning("edge %d: upload from rank %d outside this "
                            "block (slots %s)", self.edge_idx, sender,
                            self._slots)
                return
            if local in self._uploads or self._forwarded:
                return  # chaos-duplicated upload: exactly-once folding
            if (MyMessage.MSG_ARG_KEY_SPARSE_IDX in msg_params
                    or MyMessage.MSG_ARG_KEY_UPDATE_CODEC in msg_params):
                raise RuntimeError(
                    "encoded uplinks (top-k / delta / quantized) are not "
                    "wired through edge aggregators — run the flat "
                    "topology or the dense protocol")
            self._uploads[local] = (
                list(msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS]),
                float(msg_params[MyMessage.MSG_ARG_KEY_NUM_SAMPLES]))
            if len(self._uploads) == len(self._slots):
                self._forward_partial()

    def _forward_partial(self) -> None:
        """Gate + canonical pairwise partial over this block, one frame to
        the root. Caller holds _lock. Missing children (elastic timeout)
        carry zero weight and the global value — exact zero terms."""
        C = len(self._slots)
        stacked = []
        for i, g in enumerate(self._global):
            g = np.asarray(g)
            rows = [np.asarray(self._uploads[local][0][i])
                    if local in self._uploads else g
                    for local in range(C)]
            stacked.append(jnp.stack([jnp.asarray(r) for r in rows]))
        weights = jnp.asarray(
            [self._uploads[local][1] if local in self._uploads else 0.0
             for local in range(C)], jnp.float32)
        glob = [jnp.asarray(g) for g in self._global]
        wsum, total, reasons = self._partial(stacked, glob, weights)
        msg = Message(MyMessage.MSG_TYPE_E2S_SEND_AGG_TO_SERVER,
                      self.rank, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_WSUM,
                       [np.asarray(v) for v in wsum])
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_WEIGHT, float(total))
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_REASONS,
                       np.asarray(reasons, np.int32))
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_SLOTS,
                       [int(s) for s in self._slots])
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_CLIENTS,
                       list(self._clients))
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self._round)
        self._forwarded = True
        self.send_message(msg)

    def on_timeout(self, idle_s: float) -> None:
        """Elastic edge tier: a block stalled past round_timeout_s
        forwards the partial over the children that DID report."""
        with self._lock:
            if (self._round is None or self._forwarded
                    or self.round_timeout_s is None):
                return
            if not self._uploads:
                log.error("edge %d: round %d stalled %.1fs with no child "
                          "uploads — waiting (root watchdog owns "
                          "recovery)", self.edge_idx, self._round, idle_s)
                return
            missing = [self._slots[0] + i for i in range(len(self._slots))
                       if i not in self._uploads]
            log.warning("edge %d: elastic partial over %d/%d children "
                        "(missing slots %s after %.1fs)", self.edge_idx,
                        len(self._uploads), len(self._slots), missing,
                        idle_s)
            self._forward_partial()

    def _handle_finish(self, _msg) -> None:
        self.finish()


class HierFedAvgServerManager(FedAvgServerManager):
    """The root of the 2-tier topology: broadcasts one frame per EDGE and
    advances rounds on E edge partials. Everything else — elastic
    timeout, checkpoint/resume, telemetry, tracing (the edge tier is the
    traced cohort) — is the stock server manager."""

    def __init__(self, aggregator: HierFedAvgAggregator, topology=None,
                 **kw):
        if not isinstance(aggregator, HierFedAvgAggregator):
            raise TypeError("HierFedAvgServerManager needs a "
                            "HierFedAvgAggregator")
        self.topology = topology or aggregator.topology
        for flag, name in ((kw.get("async_buffer_k"), "async_buffer_k"),
                           (kw.get("delta_broadcast"), "delta_broadcast"),
                           (kw.get("heartbeat_max_age_s"),
                            "heartbeat_max_age_s")):
            if flag:
                raise ValueError(
                    f"{name} is not wired through edge aggregators — run "
                    "the flat topology for that mode")
        super().__init__(aggregator, **kw)

    def _validate_world_size(self, size: int) -> None:
        if size != self.topology.world_size:
            raise ValueError(
                f"world size {size} != 1 + {self.topology.edges} edges + "
                f"{self.topology.workers} workers")

    def register_message_receive_handlers(self):
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_E2S_SEND_AGG_TO_SERVER,
            self.handle_message_edge_partial)

    def _round_record_extra(self) -> dict:
        hist = self.aggregator.fanin_history
        return {"hier": {"edges": self.topology.edges,
                         "block": self.topology.block,
                         "fan_in": hist[-1] if hist else 0}}

    def _broadcast_model(self, msg_type: str, global_params) -> None:
        """One frame per EDGE (fan-out O(edges)): the model + that edge
        block's client assignments + the round tag."""
        from fedml_tpu.comm.message import codec_roundtrip
        from fedml_tpu.obs.tracing import TRACE_KEY

        topo = self.topology
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        self._round_ids = [int(c) for c in client_indexes]
        self.aggregator.begin_round(self.round_idx)
        # stash AS CLIENTS SEE IT, like the flat path (frame codec round
        # trip) — tree mode refuses encoded uplinks, but the stash keeps
        # the versioned-base bookkeeping uniform
        self._bcast_leaves = codec_roundtrip(global_params)
        self._stash_version(self.round_idx, self._bcast_leaves)
        tr = self._dtracer
        if tr is not None:
            tr.begin_round(self.round_idx)
        for e in range(topo.edges):
            rank = topo.edge_rank(e)
            msg = Message(msg_type, self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                           global_params)
            msg.add_params(
                MyMessage.MSG_ARG_KEY_CHILD_CLIENTS,
                [int(client_indexes[s]) for s in topo.slots_of_edge(e)])
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            if tr is not None:
                msg.add_params(TRACE_KEY, tr.broadcast_ctx(rank))
            self.send_message(msg)
        if tr is not None:
            tr.end_broadcast()

    def handle_message_edge_partial(self, msg_params) -> None:
        from fedml_tpu.obs.tracing import TRACE_KEY

        with self._round_lock:
            sender = int(msg_params[Message.MSG_ARG_KEY_SENDER])
            msg_round = msg_params.get(MyMessage.MSG_ARG_KEY_ROUND,
                                       self.round_idx)
            if int(msg_round) != self.round_idx:
                from fedml_tpu.obs import comm_instrument as _obs

                _obs.record_stale_upload("stale")
                log.warning("drop stale edge partial from rank %d "
                            "(round %s, now %d)", sender, msg_round,
                            self.round_idx)
                return
            if self._dtracer is not None:
                self._dtracer.on_upload(sender,
                                        msg_params.get(TRACE_KEY))
            self.aggregator.add_edge_result(
                sender - 1,
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_WSUM],
                float(msg_params[MyMessage.MSG_ARG_KEY_EDGE_WEIGHT]),
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_REASONS],
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_SLOTS],
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_CLIENTS],
                round_idx=int(msg_round))
            if not self.aggregator.check_whether_all_receive():
                return
            self._advance_round()


def run_simulated_hierarchical(
    dataset, task, cfg, edges: int, backend: str = "LOOPBACK",
    job_id: str = "fedavg-hier-sim", base_port: int = 50000,
    broker_host: str = "127.0.0.1", broker_port: int = 1883,
    ckpt_dir: str | None = None, telemetry=None, chaos_plan=None,
    round_timeout_s: float | None = None, adversary_plan=None,
    warmup: bool = False,
) -> HierFedAvgAggregator:
    """The 2-tier analogue of ``run_simulated``: 1 root + E edges + W
    workers as threads over the loopback (or localhost-gRPC) backend.
    ``cfg.client_num_per_round`` is W; worker slot s trains
    ``client_sampling(round)[s]`` exactly like the flat runtime, so the
    tree and flat cohorts coincide round-for-round."""
    from fedml_tpu import chaos as _chaos
    from fedml_tpu.distributed.fedavg.client_manager import (
        FedAvgClientManager,
    )
    from fedml_tpu.distributed.fedavg.trainer import DistributedTrainer
    from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated

    topo = EdgeTopology(edges=edges, workers=cfg.client_num_per_round)
    kw = backend_kwargs(backend, job_id, base_port, broker_host,
                        broker_port)
    if chaos_plan is not None:
        _chaos.install_plan(chaos_plan)
    try:
        aggregator = HierFedAvgAggregator(dataset, task, cfg, topo)
        server = HierFedAvgServerManager(
            aggregator, rank=0, size=topo.world_size, backend=backend,
            ckpt_dir=ckpt_dir, round_timeout_s=round_timeout_s,
            telemetry=telemetry, **kw)
        edge_mgrs = [
            FedAvgEdgeManager(topo.edge_rank(e), topo, backend=backend,
                              round_timeout_s=round_timeout_s, **kw)
            for e in range(topo.edges)
        ]
        clients = []
        for slot in range(topo.workers):
            rank = topo.worker_rank(slot)
            trainer = DistributedTrainer(rank, dataset, task, cfg)
            clients.append(FedAvgClientManager(
                trainer, rank=rank, size=topo.world_size, backend=backend,
                server_rank=topo.edge_rank(topo.edge_of_slot(slot)),
                adversary_plan=adversary_plan, **kw))
        if warmup and clients:
            from fedml_tpu.utils.metrics import enable_compile_cache

            enable_compile_cache()
            # one rank compiles, every sibling deserializes from disk
            clients[0].warmup()
        launch_simulated(server, edge_mgrs + clients)
    finally:
        if chaos_plan is not None:
            _chaos.install_plan(None)
    return aggregator
