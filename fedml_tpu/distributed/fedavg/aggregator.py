"""Server-side aggregator: collect per-client results, weighted-average, eval.

Mirror of fedml_api/distributed/fedavg/FedAVGAggregator.py — add_local_
trained_result (:44-48), check_whether_all_receive (:50-56), aggregate
(:58-87, per-key sample-weighted sum), client_sampling (:89-97, np.random
seeded by round), test_on_server_for_all_clients (:109-163).

The average itself is one jitted pytree op on stacked leaves rather than a
python loop over state_dict keys.

Hardening beyond the reference (docs/ROBUSTNESS.md §Byzantine-robust
aggregation):

- **upload slotting is stamped**: ``add_local_trained_result`` rejects
  out-of-round and unknown-rank uploads (``comm_stale_uploads_total``)
  instead of silently overwriting whatever index arrives;
- **sanitation gate, always on for non-finite**: the binary wire ships
  float32 bits verbatim (comm/message.py clamps only inside the lossy
  f16/q8 re-encoders), so ``aggregate`` is the last stop before a NaN
  upload hits ``tree_weighted_mean`` — any non-finite update is dropped,
  counted, and quarantined unconditionally; the norm-outlier rule arms
  with ``sanitize=``;
- **pluggable robust aggregation**: ``aggregator=`` swaps the weighted
  mean for a core/robust_agg estimator (median / trimmed_mean / krum /
  multi_krum / geometric_median) over the same stacked-leaf layout,
  sharing the exact jitted code the standalone engine runs so the two
  runtimes' quarantine ledgers agree entry-for-entry.

Wire-efficiency composition (docs/PERFORMANCE.md §Wire efficiency): the
encoded uplink tiers (top-k, delta, int8/1-bit quantized) are DECODED TO
DENSE F32 by the server manager (``_decode_upload``, against the
version-stamped broadcast stash) before they reach
``add_local_trained_result`` — so everything here, the gate included,
sees the same stacked-leaf layout whatever rode the wire, and weighted-
mean over decoded ±scale sign updates IS scaled-sign aggregation.
Decoded quantized garbage (NaN scales from a poisoned client, corrupt
payloads surviving CRC) either arrives non-finite and dies at the
unconditional gate or never arrives at all (quarantined ``undecodable``
at decode).

Fused alternative (``fused_agg=True``, docs/PERFORMANCE.md §Fused
aggregation): the decode→gate→sum chain moves on device — uploads stage
as their raw quantized leaves and one jit per arrival densifies against
the device-resident broadcast stash (core/fused_agg.py). Plain mean
(gate disarmed) folds arrivals into canonical pairwise partials and the
flush merges O(log fan-in) partials; robust estimators and the armed
norm-outlier gate run the STAGED fused mode — per-arrival evidence rows,
device-resident slots, one verdict-composition jit at flush
(``robust_agg.verdict_flush``, the same composition ``gated_aggregate``
runs). Both are bitwise the ``sum_assoc='pairwise'`` stacked route,
model bits AND quarantine ledger; sharded server state, the async
buffer, and edge tiers all compose (the one remaining refusal is
host-representation aggregates — TurboAggregate keeps its own mod-p
fused path).
"""

from __future__ import annotations

import logging
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgConfig
from fedml_tpu.comm.message import pack_pytree, unpack_pytree
from fedml_tpu.core.client_data import FederatedData, batch_global
from fedml_tpu.core.local import Task, make_eval_fn
from fedml_tpu.core.robust_agg import (
    COORDINATEWISE,
    DEFAULT_NORM_MULT,
    REASON_OK,
    QuarantineLedger,
    gated_aggregate,
    make_robust_aggregator,
)
from fedml_tpu.core.partition_rules import tree_bytes as _tree_bytes
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.obs import comm_instrument as _obs
from fedml_tpu.obs import perf_instrument as _perf

log = logging.getLogger("fedml_tpu.distributed.fedavg")


class FedAvgAggregator:
    def __init__(self, dataset: FederatedData, task: Task, cfg: FedAvgConfig,
                 worker_num: int, aggregator: str | None = None,
                 aggregator_params: dict | None = None,
                 sanitize: bool | float | None = None,
                 shard_server_state: bool = False,
                 partition_rules=None,
                 sum_assoc: str = "auto",
                 fused_agg: bool = False):
        if cfg.sampling != "uniform":
            # this runtime's client_sampling + weighted aggregate implement
            # the uniform scheme only — refuse rather than silently ignore
            raise ValueError(
                f"sampling={cfg.sampling!r} is not wired for the "
                "cross-process runtime; use uniform")
        self.dataset, self.task, self.cfg = dataset, task, cfg
        self.worker_num = worker_num
        self.model_dict: dict[int, list] = {}
        self.sample_num_dict: dict[int, int] = {}
        self.flag_client_model_uploaded = {i: False for i in range(worker_num)}
        # the round uploads are currently being accepted FOR — stamped by
        # the server manager at broadcast (begin_round); uploads tagged
        # with any other round are rejected, never slotted
        self.current_round = 0
        # heartbeat-driven cohort admission (docs/ROBUSTNESS.md
        # §Asynchronous buffered rounds): worker INDICES the server manager
        # excluded from this round's cohort (heartbeat age past the
        # threshold) — the barrier does not wait for them, but an excluded
        # rank that uploads anyway (it just resumed) is still folded in
        self.excluded: set[int] = set()
        # async buffered flush (server_manager async mode): slot ->
        # (1-based worker rank, trained client id) for ledger attribution —
        # buffered slots are arrival positions, not worker indices, and a
        # buffer may fold several waves of one rank into one aggregate
        self._async_meta: dict[int, tuple[int, int]] | None = None
        self._async_discounts: dict[int, float] | None = None

        # same init-key derivation as FedAvgAPI/DistributedTrainer so every
        # party (and the standalone oracle) starts from identical weights
        _, init_key = jax.random.split(jax.random.PRNGKey(cfg.seed))
        from fedml_tpu.core.client_source import ClientDataSource

        x_init = (dataset.init_batch(cfg.batch_size)
                  if isinstance(dataset, ClientDataSource)
                  else dataset.train_x[: cfg.batch_size])
        self.net = task.init(init_key, jnp.asarray(x_init))
        self.eval_fn = make_eval_fn(task)
        self._test_cache = None
        self.history: list[dict] = []
        # robust aggregation + sanitation gate: the SAME core/robust_agg
        # functions (and default weighted-mean formula) the SPMD engine
        # jits, applied to the stacked wire leaves (= jax.tree.leaves of
        # the engine's stacked NetState, so sorts/distances see identical
        # values in identical order and the runtimes cannot drift)
        robust = None
        if aggregator is not None:
            robust = make_robust_aggregator(
                aggregator, n=worker_num, **(aggregator_params or {}))
        if sanitize is None:
            sanitize = aggregator is not None
        self._sanitize_mult = (
            None if sanitize is False
            else DEFAULT_NORM_MULT if sanitize is True else float(sanitize))
        # Fused on-device aggregation (core/fused_agg.py, docs/
        # PERFORMANCE.md §Fused aggregation): uploads stage as their raw
        # quantized leaves and one jit per arrival runs decode -> densify
        # against the device-resident broadcast stash — no per-client f32
        # tree on host, decode overlapped with the wire wait. Plain mean
        # (gate disarmed) folds arrivals into the canonical pairwise
        # partials (O(log fan-in) live nodes); robust estimators and the
        # armed norm-outlier gate can't fold at arrival (cohort verdicts
        # need the full survivor set) so they run the STAGED fused mode:
        # per-arrival evidence rows, device-resident slots, ONE verdict-
        # composition jit at flush (robust_agg.verdict_flush — the same
        # composition gated_aggregate's verdict branch runs, shared so the
        # two cannot drift). Bitwise the stacked sum_assoc='pairwise'
        # route either way, model bits AND ledger (test-enforced). The
        # sole remaining refusal is host-representation aggregates
        # (TurboAggregate ships its own mod-p fused path).
        if fused_agg:
            if not type(self)._stage_uploads_on_arrival:
                raise ValueError(
                    f"{type(self).__name__} aggregates on the HOST "
                    "representation — fused_agg needs the device-staged "
                    "float path (run the stacked route)")
            if sum_assoc == "auto":
                # the fused fold IS the canonical pairwise association —
                # there is no fused twin of the historical tensordot
                sum_assoc = "pairwise"
        self.fused_agg = bool(fused_agg)
        self._fused_staged = bool(fused_agg) and (
            aggregator is not None or self._sanitize_mult is not None)
        self._fused = None  # FusedRoundIngest of the active round
        self._fused_ingest: dict[str, object] = {}
        self._last_flush: dict | None = None
        # gate -> estimator -> suspected merge -> all-rejected fallback:
        # the ONE jittable composition both runtimes share
        # (core/robust_agg.gated_aggregate). The gate runs every
        # aggregate: norm_mult=inf disarms the outlier rule but the
        # non-finite rejection is unconditional (see module docstring —
        # the float wire path performs no clamping).
        mult = (self._sanitize_mult if self._sanitize_mult is not None
                else float("inf"))
        # sum_assoc='pairwise': replace the weighted mean's tensordot with
        # the canonical balanced-binary association (robust_agg.pairwise_
        # sum) — the flat run becomes bitwise-comparable with any 2-tier
        # edge topology over the same cohort (docs/ROBUSTNESS.md
        # §Hierarchical tiers). 'auto' (default) keeps the historical
        # association, so every existing bitwise contract is untouched.
        # pairwise + a robust estimator = the TWO-PHASE composition
        # (evidence -> verdicts -> survivor fold, robust_agg.make_verdict_
        # estimator): the flat twin of cross-tier robust gating, bitwise-
        # comparable with a 2-tier robust run over the same cohort
        # (docs/ROBUSTNESS.md §Cross-tier robust gating). The 'auto'
        # robust path keeps the full-stack estimators untouched.
        if sum_assoc not in ("auto", "pairwise"):
            raise ValueError(f"sum_assoc={sum_assoc!r} "
                             "(expected 'auto' or 'pairwise')")
        self.sum_assoc = sum_assoc
        verdict_fn = None
        if sum_assoc == "pairwise" and aggregator is not None:
            from fedml_tpu.core.robust_agg import make_verdict_estimator

            verdict_fn = make_verdict_estimator(
                aggregator, n=worker_num, **(aggregator_params or {}))
            robust = None
        self._gagg = jax.jit(partial(
            gated_aggregate, robust_fn=robust, norm_mult=mult,
            verdict_fn=verdict_fn,
            pairwise=sum_assoc == "pairwise" and verdict_fn is None))
        self.quarantine = QuarantineLedger()
        # Mesh-sharded server state on the cross-process server (the
        # standalone engine's shard_server_state, wired to the wire path):
        # the global model lives partitioned over this process's local
        # devices, arriving uploads are staged straight to their shard's
        # device placement (decode-on-arrival lands each leaf already
        # distributed), the jitted gated aggregate runs under GSPMD with
        # the output re-partitioned, and the gather happens only at
        # broadcast-pack time (get_global_model_params). Values are
        # bit-exact either way — the layout changes, the math does not.
        self._partitioner = None
        self._upload_shardings = None
        self._rep_sharding = None
        if shard_server_state:
            devs = jax.local_devices()
            if len(devs) > 1:
                from jax.sharding import Mesh, NamedSharding
                from jax.sharding import PartitionSpec as P

                from fedml_tpu.core.partition_rules import (
                    ServerStatePartitioner,
                )

                # same axis NAME as the standalone engine's mesh, so an
                # explicit rule table (specs naming 'clients') is portable
                # between the two runtimes; here the axis only ever plays
                # the server-shard role
                mesh = Mesh(np.asarray(devs), ("clients",))
                self._partitioner = ServerStatePartitioner(
                    mesh, rules=partition_rules)
                self.net = self._partitioner.shard(self.net)
                # (leaf shape, shard placement) per wire slot — staging
                # matches by shape so codec-transformed leaves (sparse
                # idx/val pairs) fall back to the plain device_put
                self._upload_shardings = [
                    (np.shape(v), sh) for v, sh in zip(
                        jax.tree.leaves(self.net),
                        jax.tree.leaves(
                            self._partitioner.shardings(self.net)))]
                # coordinate-wise estimators run shard-local here too
                # (COORDINATEWISE, same as the standalone engine): the
                # stacked wire leaves get the partitioner's stacked layout
                # — client axis replicated, param dim sharded — before the
                # sorts; leaf-list mode with the shape guard so
                # codec-transformed leaves pass through unconstrained
                reshard = None
                if isinstance(aggregator, str) and \
                        aggregator in COORDINATEWISE:
                    reshard = self._partitioner.stacked_constrainer(
                        self.net, leaf_list=True, shape_guard=True)
                # pin the jitted aggregate's outputs to the rule-table
                # layout: the new global model lands sharded INSIDE the
                # compiled program — no eager tree-wide re-partitioning
                # pass afterwards (resharding moves bits, never rounds, so
                # parity is unaffected; weights/reason codes are tiny and
                # naturally replicated)
                # sum_assoc='pairwise' / the two-phase verdict composition
                # compose as pure layout: the verdict branch returns
                # before reshard_fn is consulted (its estimator reads
                # evidence rows, not the stack), so the sharded layout
                # comes from the staged inputs + these out_shardings — XLA
                # lowers the survivor fold into reduce-scatters landing in
                # the rule-table placement, no gather-then-reshard
                rep = NamedSharding(mesh, P())
                self._rep_sharding = rep
                self._gagg = jax.jit(
                    partial(gated_aggregate, robust_fn=robust,
                            norm_mult=mult, reshard_fn=reshard,
                            verdict_fn=verdict_fn,
                            pairwise=sum_assoc == "pairwise"
                            and verdict_fn is None),
                    out_shardings=([sh for _, sh in self._upload_shardings],
                                   rep, rep))
            else:
                log.warning("shard_server_state ignored: one local device "
                            "(nothing to partition over)")
        self._state_placement = ("sharded" if self._partitioner is not None
                                 else "replicated")
        self._model_nbytes = _tree_bytes(self.net)
        if self.fused_agg:
            from fedml_tpu.core import fused_agg as _fused_mod

            self._fused_meta = _fused_mod._leaf_meta(
                jax.tree.leaves(self.net))
            self._fused_term_nbytes = _fused_mod.term_nbytes(
                self._fused_meta)
            # mesh-sharded server state: pin each ingested slot's leaves
            # to the rule-table placement, so accumulator partials /
            # staged slots already carry the sharded layout and the
            # flush's folds lower into reduce-scatters (layout moves
            # bytes, never values — the bitwise contract is unaffected)
            self._fused_stage_fn = None
            if self._upload_shardings is not None:
                shardings = [sh for _, sh in self._upload_shardings]

                def _pin(leaves, _sh=shardings):
                    return [jax.device_put(v, s)
                            for v, s in zip(leaves, _sh)]

                self._fused_stage_fn = _pin
            if self._fused_staged:
                from fedml_tpu.core.robust_agg import (
                    EVIDENCE_SKETCH_DIM,
                    make_verdict_estimator,
                )

                # sketches feed distance-based estimators only; the armed-
                # sanitize mean verdict reads none (ship zero-width rows)
                self._fused_sketch_dim = (
                    EVIDENCE_SKETCH_DIM if verdict_fn is not None else 0)
                fvf = verdict_fn
                if fvf is None:
                    # armed sanitize without an estimator: the mean
                    # verdict behind the armed gate IS sanitize_updates'
                    # composition — gate weights are the sanitize weights
                    # and apply_verdicts performs the identical global-
                    # model replacement (bitwise, test-enforced)
                    fvf = make_verdict_estimator("mean", n=worker_num)
                out_sh = None
                if self._upload_shardings is not None:
                    out_sh = ([sh for _, sh in self._upload_shardings],
                              self._rep_sharding, self._rep_sharding)
                # built ONCE: the flush jit retraces per realized cohort
                # size (like the stacked gagg), never per round
                self._fused_flush = _fused_mod.make_fused_robust_flush(
                    fvf, norm_mult=mult, out_shardings=out_sh)
        self._record_server_state_bytes()

    def _record_server_state_bytes(self, opt_state=()) -> None:
        """Export fed_server_state_bytes{placement} (PER-DEVICE bytes of
        model + server optimizer state). Subclasses that carry server
        optimizer state re-call this with it once built (FedOptAggregator)
        — the gauge must count the whole server plane, or a FedOpt-Adam
        server would report a third of its real footprint. Sized
        component-by-component — wrapping (net, opt_state) in one tuple
        would prefix every leaf path with '0/'/'1/' and anchored custom
        rules would resolve differently here than in shard()."""
        if self._partitioner is not None:
            per_dev = (self._partitioner.bytes_per_device(self.net)
                       + self._partitioner.bytes_per_device(opt_state))
        else:
            per_dev = _tree_bytes((self.net, opt_state))
        _perf.set_server_state_bytes(self._state_placement, per_dev)

    def get_global_model_params(self):
        return pack_pytree(self.net)

    # ------------------------------------------------------------- receive
    # Decode-on-arrival: float upload leaves move to device as each frame
    # arrives (jax.device_put is async — the H2D overlaps the clients still
    # training) instead of all K at the round barrier, where ``aggregate``
    # used to serialize every transfer under the round lock. Values are
    # bit-exact either way. Subclasses whose aggregate works on the HOST
    # representation (TurboAggregate's int64 Shamir shares, the robust
    # clip's unpack/re-pack loop) opt out via the class attribute.
    _stage_uploads_on_arrival = True

    def _stage_upload(self, wire_leaves):
        if not self._stage_uploads_on_arrival:
            return wire_leaves
        if self._upload_shardings is not None and \
                len(wire_leaves) == len(self._upload_shardings):
            # sharded server state: each float leaf goes straight to its
            # shard's device placement as the frame arrives — the H2D is
            # already distributed over the local devices by the time the
            # round barrier trips (non-float and codec-transformed leaves
            # whose shape no longer matches the model pass through plain)
            def put(v, shp, sh):
                if not (isinstance(v, np.ndarray) and v.dtype == np.float32):
                    return v
                return jax.device_put(v, sh if np.shape(v) == shp else None)

            return [put(v, shp, sh)
                    for v, (shp, sh) in zip(wire_leaves,
                                            self._upload_shardings)]
        return [jax.device_put(v)
                if isinstance(v, np.ndarray) and v.dtype == np.float32
                else v
                for v in wire_leaves]

    def begin_round(self, round_idx: int) -> None:
        """Stamp the round uploads are now accepted for (called by the
        server manager right before each broadcast)."""
        self.current_round = int(round_idx)
        # fused ingest state is per round: a fresh accumulator against the
        # round's OWN global model (arrivals gate/replace against it)
        self._fused = None

    def _admit_upload(self, index: int, round_idx: int | None) -> bool:
        """The shared upload-slotting admission rule (see
        :meth:`add_local_trained_result` for the reject vocabulary)."""
        if index not in self.flag_client_model_uploaded:
            _obs.record_stale_upload("unknown_rank")
            log.warning("reject upload for unknown worker index %s "
                        "(workers 0..%d)", index, self.worker_num - 1)
            return False
        if round_idx is not None and int(round_idx) != self.current_round:
            _obs.record_stale_upload("stale")
            log.warning("reject out-of-round upload from index %s "
                        "(tagged round %s, current %d)",
                        index, round_idx, self.current_round)
            return False
        return True

    def add_local_trained_result(self, index: int, wire_leaves,
                                 sample_num: int,
                                 round_idx: int | None = None) -> None:
        """Slot one client upload. Rejects (counted in
        ``comm_stale_uploads_total{reason}``, never slotted):

        - ``unknown_rank`` — ``index`` outside the worker table (a stray
          or forged sender id must not grow the dict unboundedly);
        - ``stale`` — ``round_idx`` given and != the stamped current
          round (a straggler's superseded upload must not overwrite a
          fresh one after elastic partial aggregation moved on).

        ``round_idx=None`` (legacy caller) skips the round check only.
        """
        if not self._admit_upload(index, round_idx):
            return
        self.model_dict[index] = self._stage_upload(wire_leaves)
        self.sample_num_dict[index] = sample_num
        self.flag_client_model_uploaded[index] = True

    def add_fused_result(self, index: int, kind: str, payload, scales,
                         sample_num, round_idx: int | None,
                         base_leaves) -> None:
        """Fused twin of :meth:`add_local_trained_result` (docs/
        PERFORMANCE.md §Fused aggregation): the upload arrives as its RAW
        wire payload (``kind`` one of core/fused_agg.FUSED_KINDS) plus the
        device-resident broadcast stash it encoded against, and one jitted
        ingest decodes, gates, and folds it into the round's canonical
        pairwise partials — no host densify, no per-slot stacking. Same
        admission rule and barrier bookkeeping as the stacked path."""
        if not self._admit_upload(index, round_idx):
            return
        if self._fused is None:
            self._fused = self._make_fused_round()
        self._fused.add(index, self._fused_ingest_fn(kind), payload,
                        scales, base_leaves, float(sample_num))
        self.sample_num_dict[index] = sample_num
        self.flag_client_model_uploaded[index] = True

    def _make_fused_round(self):
        """Fresh per-round ingest state against the round's own global
        model (staged mode for robust/armed-sanitize; the sharding pin
        when the server plane is partitioned)."""
        from fedml_tpu.core import fused_agg as _fused_mod

        return _fused_mod.FusedRoundIngest(
            jax.tree.leaves(self.net), self._fused_meta,
            staged=self._fused_staged, stage_fn=self._fused_stage_fn)

    def _fused_ingest_fn(self, kind: str):
        """The per-kind arrival jit, built once and cached (plain
        decode→gate fold, or decode→evidence in staged mode)."""
        fn = self._fused_ingest.get(kind)
        if fn is None:
            from fedml_tpu.core import fused_agg as _fused_mod

            if self._fused_staged:
                fn = _fused_mod.make_fused_robust_ingest(
                    kind, self._fused_meta, self._fused_sketch_dim)
            else:
                fn = _fused_mod.make_fused_ingest(kind, self._fused_meta)
            self._fused_ingest[kind] = fn
        return fn

    def load_buffered(self, entries, weights, discounts=None) -> None:
        """Populate the aggregation slots from an async buffer drain
        (server_manager async mode): slot i carries ``entries[i]``'s staged
        leaves with its staleness-DISCOUNTED weight, and the (rank, client)
        side table routes quarantine verdicts to the true worker rank. The
        next ``aggregate()`` call — the SUBCLASS composition, so FedOpt's
        server step and the robust clip/noise passes apply to the buffered
        aggregate unchanged — consumes and clears the slots as usual.
        With constant discount the weights are bitwise the sample counts,
        which is the weight half of the K=cohort sync-parity contract.
        ``discounts`` is the bare per-slot staleness multiplier — kept
        aside for aggregates that must REPLACE the sample-count half of
        the weight without losing the staleness half (the DP uniform
        average, fedavg_robust.py)."""
        self.model_dict.clear()
        self.sample_num_dict.clear()
        self._async_meta = {}
        self._async_discounts = (None if discounts is None
                                 else {i: float(d)
                                       for i, d in enumerate(discounts)})
        if self.fused_agg:
            # fused async drain: entries arrive PRE-DENSIFIED (the server
            # manager's arrival jit decoded them against the version-
            # stamped device stash, overlapping the buffer fill), so the
            # drain folds at the door — one dense ingest per slot against
            # the CURRENT global with the staleness-discounted weight: no
            # host densify, no decode burst under the flush lock. Gate /
            # evidence run here, not at arrival, because the reference
            # global for replacement is the flush-time model — exactly
            # when the stacked route gates its staged entries.
            self._fused = self._make_fused_round()
            fn = self._fused_ingest_fn("dense")
            for slot, (e, w) in enumerate(zip(entries, weights)):
                self._fused.add(slot, fn, e.payload, None, None, float(w))
                self.sample_num_dict[slot] = float(w)
                self._async_meta[slot] = (int(e.rank), int(e.client))
            return
        for slot, (e, w) in enumerate(zip(entries, weights)):
            self.model_dict[slot] = e.payload
            self.sample_num_dict[slot] = float(w)
            self._async_meta[slot] = (int(e.rank), int(e.client))

    def check_whether_all_receive(self) -> bool:
        if any(not v for i, v in self.flag_client_model_uploaded.items()
               if i not in self.excluded):
            # heartbeat-excluded indices never block the barrier; everyone
            # else must report (or the elastic watchdog trips)
            return False
        for i in self.flag_client_model_uploaded:
            self.flag_client_model_uploaded[i] = False
        return True

    # ----------------------------------------------------------- aggregate
    def aggregate(self):
        self._aggregate_core()
        return pack_pytree(self.net)

    def _stack_uploads(self, ranks) -> list:
        """The ``[K, ...]`` estimator layout per leaf, stacked DIRECTLY
        from the staged placements: a staged device leaf enters the stack
        as-is (re-wrapping each in ``jnp.asarray`` per rank per leaf cost a
        dispatch apiece for nothing), a host (numpy) leaf transfers once
        inside the stack. Regression-pinned by a no-transfer assertion
        over the staged path (tests/test_fused_bf16.py)."""
        n_leaves = len(self.model_dict[ranks[0]])
        return [jnp.stack([self.model_dict[r][i] for r in ranks])
                for i in range(n_leaves)]

    def _aggregate_fused(self):
        """The fused flush (docs/PERFORMANCE.md §Fused aggregation):
        arrivals already decoded on device — plain mode merges the
        pairwise partials and divides once; staged (robust) mode runs the
        ONE verdict-composition jit over the staged slots. Bitwise the
        stacked ``sum_assoc='pairwise'`` route over the same arrived
        slots, ledger included (test-enforced)."""
        t0 = time.perf_counter()
        fr, self._fused = self._fused, None
        if fr is None or not fr.slots:
            log.warning("round %d: no decodable uploads — keeping the "
                        "current global model", self.current_round)
            self.sample_num_dict.clear()
            return
        slots = sorted(fr.slots)
        if fr.staged_mode:
            avg_leaves, _vw, reasons_dev = fr.flush_robust(
                self._fused_flush)
            # memory honesty: staged slots are O(K), not O(log K) — the
            # stacked route's stack bytes plus the evidence rows, under
            # their own gauge mode so the budget pin can tell them apart
            mode = "fused_staged"
            stack_bytes = fr.peak_terms * (
                self._fused_term_nbytes
                + 4 * (self._fused_sketch_dim + 3))
        else:
            avg_leaves, reasons_dev = fr.flush()
            mode = "fused"
            stack_bytes = fr.peak_terms * self._fused_term_nbytes
        _perf.record_agg_bytes(self._state_placement,
                               self._model_nbytes * len(slots))
        _perf.set_agg_stack_bytes(mode, stack_bytes)
        reasons = np.asarray(reasons_dev)
        if reasons.any():
            if self._async_meta is not None:
                # async buffered flush: slots are arrival positions — the
                # (rank, client) attribution rides the side table the
                # server manager staged with the buffer entries
                rank_l = [self._async_meta[s][0] for s in slots]
                client_l = [self._async_meta[s][1] for s in slots]
            else:
                ids = self.client_sampling(self.current_round)
                rank_l = [s + 1 for s in slots]
                client_l = [int(ids[s]) for s in slots]
            self.quarantine.record_codes(
                self.current_round, reasons,
                clients=client_l, ranks=rank_l)
            if (reasons != REASON_OK).all():
                log.warning("round %d: all %d uploads quarantined — "
                            "keeping the current global model",
                            self.current_round, len(slots))
        self.net = unpack_pytree(self.net, avg_leaves)
        self.sample_num_dict.clear()
        flush_s = time.perf_counter() - t0
        _perf.record_flush_seconds(flush_s)
        self._last_flush = {"fused": True, "flush_s": round(flush_s, 6),
                            "stack_bytes": int(stack_bytes)}
        log.info("fused aggregate time cost: %.3fs (%d %s peak)",
                 flush_s, fr.peak_terms,
                 "staged slots" if fr.staged_mode else "partials")

    def agg_record(self) -> dict:
        """The ``agg`` block the server manager rides on telemetry round
        records (report.py renders ``flush_s``/``prec``; absent on pre-PR
        logs): server-state placement, the last flush's
        mode/latency/staging bytes, and the cfg's client-compute
        precision policy (both runtimes share the cfg, so the stamp holds
        for the clients this server dispatched)."""
        rec = {"mode": self._state_placement}
        if getattr(self.cfg, "precision", "f32") not in ("f32", "float32"):
            rec["prec"] = self.cfg.precision
        if self._last_flush is not None:
            rec.update(self._last_flush)
        return rec

    def _aggregate_core(self):
        """Gate + estimate + update ``self.net`` WITHOUT packing it for the
        wire — subclasses that transform the state further before broadcast
        (FedOpt's server step, the robust noise pass) call this and pack
        once at the end, so a sharded server plane is gathered exactly once
        per round (the gather belongs at broadcast-pack time only)."""
        # getattr: partially-built instances (tests, legacy subclass
        # constructions) predate the fused attribute and mean stacked
        if getattr(self, "fused_agg", False):
            return self._aggregate_fused()
        t0 = time.perf_counter()
        ranks = sorted(self.model_dict)
        if not ranks:
            # every upload this round was discarded before slotting (e.g.
            # all structurally undecodable under a codec tier) — keep the
            # current global model, exactly like the all-quarantined case
            log.warning("round %d: no decodable uploads — keeping the "
                        "current global model", self.current_round)
            return
        stacked = self._stack_uploads(ranks)
        weights = jnp.asarray([self.sample_num_dict[r] for r in ranks], jnp.float32)

        # the shared composition: gate (non-finite unconditionally; norm
        # outliers when armed) -> estimator -> suspected merge -> keep the
        # global model when every upload was quarantined. Sharded server
        # state hands the jit the device-resident partitioned leaves
        # directly (pack_pytree would gather to host every round — the
        # gather belongs at broadcast-pack time only).
        if self._partitioner is not None:
            global_leaves = list(jax.tree.leaves(self.net))
        else:
            global_leaves = [jnp.asarray(v) for v in pack_pytree(self.net)]
        avg_leaves, new_w, reasons = self._gagg(stacked, global_leaves,
                                                weights)
        # (sharded server state: _gagg's out_shardings already pin the new
        # model to the rule-table layout — nothing to re-partition here)
        # bytes actually folded this round: elastic partial aggregation may
        # stack fewer than worker_num uploads — count the realized cohort
        _perf.record_agg_bytes(self._state_placement,
                               self._model_nbytes * len(ranks))
        reasons = np.asarray(reasons)
        if reasons.any():
            if self._async_meta is not None:
                # async buffered flush: slots are arrival positions — the
                # (rank, client) attribution rides the side table the
                # server manager staged with the buffer entries
                rank_l = [self._async_meta[r][0] for r in ranks]
                client_l = [self._async_meta[r][1] for r in ranks]
            else:
                # slot i holds worker index ranks[i] -> 1-based rank + the
                # client id that rank trained this round
                ids = self.client_sampling(self.current_round)
                rank_l = [r + 1 for r in ranks]
                client_l = [int(ids[r]) for r in ranks]
            self.quarantine.record_codes(
                self.current_round, reasons,
                clients=client_l, ranks=rank_l)
            # all-quarantined flag from the reason codes the ledger just
            # pulled to host — float(jnp.sum(new_w)) here was a BLOCKING
            # device fetch on the hot path (fedlint host-sync now pins the
            # pattern); new_w stays a device value end to end
            if (reasons != REASON_OK).all():
                log.warning("round %d: all %d uploads quarantined — "
                            "keeping the current global model",
                            self.current_round, len(ranks))
        self.net = unpack_pytree(self.net, avg_leaves)
        self.model_dict.clear()
        self.sample_num_dict.clear()
        flush_s = time.perf_counter() - t0
        _perf.record_flush_seconds(flush_s)
        _perf.set_agg_stack_bytes("stacked", self._model_nbytes * len(ranks))
        self._last_flush = {"fused": False, "flush_s": round(flush_s, 6),
                            "stack_bytes": int(self._model_nbytes
                                               * len(ranks))}
        log.info("aggregate time cost: %.3fs", flush_s)

    # ------------------------------------------------------------ sampling
    def client_sampling(self, round_idx: int) -> np.ndarray:
        trace = getattr(self.cfg, "churn_trace", None)
        if trace is not None:
            from fedml_tpu.core.sampling import sample_available

            ids = sample_available(self.cfg, round_idx, trace)
            k = self.cfg.client_num_per_round
            if len(ids) < k:
                # the cross-process cohort is one client per worker RANK —
                # slots must stay fully populated. In a diurnal trough the
                # available cohort legitimately re-assigns the same client
                # to multiple ranks (cycle-pad, deterministic); rank-level
                # scheduled-offline skipping is what actually shrinks the
                # realized round
                ids = np.resize(ids, k)
            return ids
        return sample_clients(
            round_idx, self.cfg.client_num_in_total, self.cfg.client_num_per_round,
            self.cfg.seed,
        )

    # ----------------------------------------------------------------- eval
    ci_eval_cap = 512  # --ci truncation (FedAVGAggregator.py:126-131)

    def test_on_server_for_all_clients(self, round_idx: int) -> None:
        cfg = self.cfg
        if round_idx % cfg.frequency_of_the_test != 0 and round_idx != cfg.comm_round - 1:
            return
        if self._test_cache is None:
            tx, ty = self.dataset.test_x, self.dataset.test_y
            if (cfg.eval_max_samples is not None
                    and len(tx) > cfg.eval_max_samples):
                # seeded validation subset — the reference server's 10k
                # stackoverflow cap (_generate_validation_set, :99-107)
                sel = np.random.RandomState(cfg.seed).choice(
                    len(tx), cfg.eval_max_samples, replace=False)
                tx, ty = tx[sel], ty[sel]
            n = len(tx)
            if cfg.ci:
                n = min(n, self.ci_eval_cap)
            self._test_cache = tuple(
                jnp.asarray(a)
                for a in batch_global(tx[:n], ty[:n], cfg.eval_batch_size)
            )
        self._record_eval(round_idx)

    def _record_eval(self, round_idx: int) -> None:
        """Metric hook over the cached test batches (subclasses override)."""
        ev = self.eval_fn(self.net, *self._test_cache)
        rec = {"round": round_idx, "test_loss": float(ev["loss"]), "test_acc": float(ev["acc"])}
        self.history.append(rec)
        log.info("server eval %s", rec)
