"""Server-side aggregator: collect per-client results, weighted-average, eval.

Mirror of fedml_api/distributed/fedavg/FedAVGAggregator.py — add_local_
trained_result (:44-48), check_whether_all_receive (:50-56), aggregate
(:58-87, per-key sample-weighted sum), client_sampling (:89-97, np.random
seeded by round), test_on_server_for_all_clients (:109-163).

The average itself is one jitted pytree op on stacked leaves rather than a
python loop over state_dict keys.
"""

from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgConfig
from fedml_tpu.comm.message import pack_pytree, unpack_pytree
from fedml_tpu.core.client_data import FederatedData, batch_global
from fedml_tpu.core.local import Task, make_eval_fn
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.utils.tree import tree_weighted_mean

log = logging.getLogger("fedml_tpu.distributed.fedavg")


class FedAvgAggregator:
    def __init__(self, dataset: FederatedData, task: Task, cfg: FedAvgConfig, worker_num: int):
        if cfg.sampling != "uniform":
            # this runtime's client_sampling + weighted aggregate implement
            # the uniform scheme only — refuse rather than silently ignore
            raise ValueError(
                f"sampling={cfg.sampling!r} is not wired for the "
                "cross-process runtime; use uniform")
        self.dataset, self.task, self.cfg = dataset, task, cfg
        self.worker_num = worker_num
        self.model_dict: dict[int, list] = {}
        self.sample_num_dict: dict[int, int] = {}
        self.flag_client_model_uploaded = {i: False for i in range(worker_num)}

        # same init-key derivation as FedAvgAPI/DistributedTrainer so every
        # party (and the standalone oracle) starts from identical weights
        _, init_key = jax.random.split(jax.random.PRNGKey(cfg.seed))
        self.net = task.init(init_key, jnp.asarray(dataset.train_x[: cfg.batch_size]))
        self.eval_fn = make_eval_fn(task)
        self._test_cache = None
        self.history: list[dict] = []
        # same formula (and code) as the SPMD engine's aggregation so the
        # two runtimes cannot drift numerically
        self._wavg = jax.jit(tree_weighted_mean)

    def get_global_model_params(self):
        return pack_pytree(self.net)

    # ------------------------------------------------------------- receive
    def add_local_trained_result(self, index: int, wire_leaves, sample_num: int) -> None:
        self.model_dict[index] = wire_leaves
        self.sample_num_dict[index] = sample_num
        self.flag_client_model_uploaded[index] = True

    def check_whether_all_receive(self) -> bool:
        if not all(self.flag_client_model_uploaded.values()):
            return False
        for i in self.flag_client_model_uploaded:
            self.flag_client_model_uploaded[i] = False
        return True

    # ----------------------------------------------------------- aggregate
    def aggregate(self):
        t0 = time.perf_counter()
        ranks = sorted(self.model_dict)
        stacked = [
            jnp.stack([jnp.asarray(self.model_dict[r][i]) for r in ranks])
            for i in range(len(self.model_dict[ranks[0]]))
        ]
        weights = jnp.asarray([self.sample_num_dict[r] for r in ranks], jnp.float32)
        avg_leaves = self._wavg(stacked, weights)
        self.net = unpack_pytree(self.net, avg_leaves)
        self.model_dict.clear()
        self.sample_num_dict.clear()
        log.info("aggregate time cost: %.3fs", time.perf_counter() - t0)
        return pack_pytree(self.net)

    # ------------------------------------------------------------ sampling
    def client_sampling(self, round_idx: int) -> np.ndarray:
        return sample_clients(
            round_idx, self.cfg.client_num_in_total, self.cfg.client_num_per_round,
            self.cfg.seed,
        )

    # ----------------------------------------------------------------- eval
    ci_eval_cap = 512  # --ci truncation (FedAVGAggregator.py:126-131)

    def test_on_server_for_all_clients(self, round_idx: int) -> None:
        cfg = self.cfg
        if round_idx % cfg.frequency_of_the_test != 0 and round_idx != cfg.comm_round - 1:
            return
        if self._test_cache is None:
            tx, ty = self.dataset.test_x, self.dataset.test_y
            if (cfg.eval_max_samples is not None
                    and len(tx) > cfg.eval_max_samples):
                # seeded validation subset — the reference server's 10k
                # stackoverflow cap (_generate_validation_set, :99-107)
                sel = np.random.RandomState(cfg.seed).choice(
                    len(tx), cfg.eval_max_samples, replace=False)
                tx, ty = tx[sel], ty[sel]
            n = len(tx)
            if cfg.ci:
                n = min(n, self.ci_eval_cap)
            self._test_cache = tuple(
                jnp.asarray(a)
                for a in batch_global(tx[:n], ty[:n], cfg.eval_batch_size)
            )
        self._record_eval(round_idx)

    def _record_eval(self, round_idx: int) -> None:
        """Metric hook over the cached test batches (subclasses override)."""
        ev = self.eval_fn(self.net, *self._test_cache)
        rec = {"round": round_idx, "test_loss": float(ev["loss"]), "test_acc": float(ev["acc"])}
        self.history.append(rec)
        log.info("server eval %s", rec)
