"""Per-process client trainer: jitted local fit over this rank's client shard.

Mirror of fedml_api/distributed/fedavg/FedAVGTrainer.py:6-40 +
MyModelTrainer.py:19-49, with the epochs x batches torch loop replaced by the
lax.scan local_update from fedml_tpu/core/local.py — the whole local fit is
one compiled program, re-used every round (static shapes via pack_clients).
"""

from __future__ import annotations

import jax
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgConfig, resolve_local_spec
from fedml_tpu.comm.message import pack_pytree, unpack_pytree
from fedml_tpu.core.client_data import FederatedData, pack_clients
from fedml_tpu.core.local import LocalSpec, Task, make_local_update


def num_batches_for(max_count: int, cfg: FedAvgConfig) -> int:
    """The per-client batch-depth formula every party must agree on: the
    natural depth for the largest client, capped by cfg.max_batches.
    Shared with the secure-aggregation server (distributed/
    turboaggregate.py), which reproduces the clients' deterministic
    sample caps to compute the public cohort weight total — a fork here
    would silently mis-scale the decoded elastic mean."""
    b_needed = int(np.ceil(max_count / cfg.batch_size))
    return min(cfg.max_batches or b_needed, b_needed)


class DistributedTrainer:
    def __init__(self, client_rank: int, dataset: FederatedData, task: Task,
                 cfg: FedAvgConfig, local_spec: LocalSpec | None = None):
        self.dataset, self.task, self.cfg = dataset, task, cfg
        self.client_index = client_rank - 1  # re-assigned per round by the server

        from fedml_tpu.core.client_source import ClientDataSource

        self._source = dataset if isinstance(dataset, ClientDataSource) \
            else None
        if self._source is not None:
            max_count = int(np.max(self._source.client_sizes))
        else:
            max_count = max(len(v) for v in dataset.train_idx_map.values())
        self.num_batches = num_batches_for(max_count, cfg)

        # same cfg.precision resolution as the SPMD engine so the two
        # runtimes run identical local-fit programs (bf16 included)
        spec = resolve_local_spec(local_spec, cfg)
        self.local_update = jax.jit(make_local_update(task, spec))

        # template NetState for wire unpacking; derive the init key exactly
        # like the SPMD engine (FedAvgAPI.__init__: split(PRNGKey(seed))[1])
        # so distributed and standalone start from identical weights.
        _, init_key = jax.random.split(jax.random.PRNGKey(cfg.seed))
        import jax.numpy as jnp

        x_init = (self._source.init_batch(cfg.batch_size)
                  if self._source is not None
                  else dataset.train_x[: cfg.batch_size])
        self.net = task.init(init_key, jnp.asarray(x_init))

    def warmup(self) -> dict:
        """AOT-compile the local-fit program before the first broadcast
        arrives, through the persistent compile cache (enable_compile_cache)
        — the engine.warmup() analogue for the cross-process client: rank
        1's warm-up populates the disk cache, so the N-1 sibling ranks of a
        launch (and every later run) deserialize instead of recompiling.

        fit() packs the ASSIGNED client's own batch depth (pack_clients
        caps B per client), so heterogeneous partitions dispatch several
        distinct shapes; warm the <=4 most-common depths (deepest kept, so
        the max-size clients are always covered) — the long tail of rare
        depths compiles lazily. Returns the compile report (see
        core/pipeline.compile_concurrently)."""
        from collections import Counter

        import jax as _jax

        from fedml_tpu.core.pipeline import compile_concurrently

        if not getattr(_jax.config, "jax_compilation_cache_dir", None):
            from fedml_tpu.utils.metrics import enable_compile_cache

            enable_compile_cache()
        bs = self.cfg.batch_size
        if self._source is not None:
            sizes = [int(s) for s in self._source.client_sizes]
            # round-invariant shapes/dtypes from metadata — no payload read
            (xshape, xdtype), (yshape, ydtype) = self._source.row_meta()
        else:
            sizes = [len(ix) for ix in self.dataset.train_idx_map.values()]
            tx, ty = self.dataset.train_x, self.dataset.train_y
            (xshape, xdtype), (yshape, ydtype) = (
                (tx.shape[1:], tx.dtype), (ty.shape[1:], ty.dtype))
        counts = Counter(min(self.num_batches, -(-n // bs)) for n in sizes)
        counts.pop(0, None)  # empty clients dispatch nothing
        depths = sorted(counts, key=lambda b: (-counts[b], -b))[:4]
        deepest = max(counts) if counts else self.num_batches
        if deepest not in depths:
            depths = depths[:-1] + [deepest] if depths else [deepest]
        rng = jax.random.PRNGKey(0)
        lowered = {
            f"local_fit_b{B}": self.local_update.lower(
                rng, self.net,
                np.zeros((B, bs) + tuple(xshape), xdtype),
                np.zeros((B, bs) + tuple(yshape), ydtype),
                np.zeros((B, bs), np.float32))
            for B in sorted(depths)}
        rep = compile_concurrently(lowered)
        rep.pop("executables", None)
        return rep

    def update_model(self, wire_leaves) -> None:
        self.net = unpack_pytree(self.net, wire_leaves)

    def update_dataset(self, client_index: int) -> None:
        self.client_index = int(client_index)

    def fit(self, round_idx: int) -> int:
        """Run the local fit on the currently assigned client's data
        (result in self.net); returns the local sample count."""
        if self._source is not None:
            from fedml_tpu.core.client_source import pack_clients_source

            cb = pack_clients_source(
                self._source, [self.client_index], self.cfg.batch_size,
                max_batches=self.num_batches, seed=self.cfg.seed,
                round_idx=round_idx)
        else:
            cb = pack_clients(
                self.dataset, [self.client_index], self.cfg.batch_size,
                max_batches=self.num_batches, seed=self.cfg.seed,
                round_idx=round_idx,
            )
        rng = jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), round_idx)
        rng = jax.random.fold_in(rng, self.client_index)
        self.net, _metrics = self.local_update(rng, self.net, cb.x[0], cb.y[0], cb.mask[0])
        return int(cb.num_samples[0])

    def train(self, round_idx: int):
        """Returns (wire_leaves, local_sample_number)."""
        n = self.fit(round_idx)
        return pack_pytree(self.net), n
