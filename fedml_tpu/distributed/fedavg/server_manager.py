"""FedAvg server manager — round coordination over the comm layer.

Mirror of fedml_api/distributed/fedavg/FedAvgServerManager.py: send_init_msg
(:31-39), handle_message_receive_model_from_client (:45-82, aggregate when
all received, eval, resample, sync), send_message_sync_model_to_client
(:90-95).

Elastic extension (absent in the reference — SURVEY.md §5 'failure
detection: none'): with ``round_timeout_s`` set, a round that stalls past
the deadline aggregates over the subset of clients that DID report
(sample-weighted, so the average stays exact over the participants) and
moves on; late uploads from superseded rounds are round-tagged and dropped.
A crashed client therefore degrades throughput instead of hanging the job.

Checkpoint/resume (also absent in the reference): with ``ckpt_dir`` set the
server saves (net, opt state, round) after every aggregate and, on
construction, resumes from the latest checkpoint — a server restart
continues the job exactly where it stopped (clients are stateless between
rounds: they receive the global model each sync), so crash-resume ≡ an
uninterrupted run (tested).

Buffered-async mode (``async_buffer_k=K`` — docs/ROBUSTNESS.md
§Asynchronous buffered rounds) replaces the barrier with an event-driven
loop: each upload is admitted (staleness bound / non-finite quarantine),
staged into a bounded buffer, and its rank immediately re-dispatched;
K staged arrivals (or ``buffer_deadline_s``) flush one staleness-
discounted buffered aggregate through the aggregator's usual
composition. ``heartbeat_max_age_s`` arms heartbeat-driven cohort
admission on BOTH modes.
"""

from __future__ import annotations

import logging
import os
import threading

from fedml_tpu.comm.managers import ServerManager
from fedml_tpu.comm.message import Message, codec_roundtrip
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.message_define import MyMessage
from fedml_tpu.obs import comm_instrument as _obs
from fedml_tpu.obs import goodput as _goodput
from fedml_tpu.obs.tracing import TRACE_KEY

log = logging.getLogger("fedml_tpu.distributed.fedavg")


class SimulatedServerCrash(BaseException):
    """Deterministic SIGKILL analogue for loopback supervision (chaos
    ``crash`` rules naming rank 0 — docs/ROBUSTNESS.md §Server crash
    recovery): raised at a journaled crash point and deliberately a
    BaseException so no elastic/chaos ``except Exception`` swallows it.
    Only the supervision driver (``run_simulated``) catches it: the dead
    manager's transport is abandoned without any farewell frame and a
    FRESH manager boots through the real checkpoint + WAL recovery
    path."""

    def __init__(self, round_idx: int, point: str):
        super().__init__(f"simulated server crash at round {round_idx} "
                         f"({point})")
        self.round_idx, self.point = round_idx, point


class FedAvgServerManager(ServerManager):
    def __init__(self, aggregator: FedAvgAggregator, rank=0, size=0,
                 backend="LOOPBACK", round_timeout_s: float | None = None,
                 ckpt_dir: str | None = None, telemetry=None,
                 wal_dir: str | None = None,
                 async_buffer_k: int | None = None,
                 staleness="constant", staleness_bound: int | None = None,
                 buffer_deadline_s: float | None = None,
                 buffer_capacity: int | None = None,
                 heartbeat_max_age_s: float | None = None,
                 delta_broadcast: bool = False, churn_trace=None, **kw):
        self.aggregator = aggregator
        # scheduled availability (chaos/churn.py ChurnTrace, or None): the
        # trace's RANK stream decides which worker ranks are scheduled-
        # offline each round's window. Offline ranks are skipped SILENTLY
        # — no send, no suspect/undeliverable bookkeeping, no reprobe or
        # backoff churn — and subtracted from the quorum denominators;
        # only a rank the trace expects here rides the suspected-dead
        # paths (docs/ROBUSTNESS.md §Fleet campaigns & client churn).
        self.churn_trace = churn_trace
        self._offline_now: set[int] = set()
        # ranks whose dispatch was skipped for scheduled offline — the
        # flush-time reprobe re-dispatches them the moment the trace
        # brings them back (async mode's "resume on the next arrival")
        self._offline_skipped: set[int] = set()
        self._idle_rounds = 0
        self._idle_logged_round: int | None = None
        if churn_trace is not None:
            # pre-register the churn families at zero so a churn-driven
            # run's export always carries them; trace-less runs keep a
            # byte-identical export
            _obs.ensure_churn_families()
        self.round_num = aggregator.cfg.comm_round
        self.round_idx = 0
        self._bcast_leaves = None  # latest decoded broadcast (legacy alias)
        # version -> the broadcast AS CLIENTS HOLD IT (decoded through the
        # frame codec; under delta_broadcast the exact chain value). Every
        # encoded uplink (top-k, delta, quantized — comm/delta.py) names
        # the version it encoded against via its ROUND tag, and densifies
        # against THIS table — which is what lets sparsified/quantized
        # uplinks compose with buffered-async dispatch waves. Bounded: old
        # versions are pruned; an upload whose base was evicted is shed as
        # stale (async requeues it), while a version NEVER stashed is a
        # loud protocol error.
        self._version_pack: dict[int, list] = {}
        # fused on-device aggregation (docs/PERFORMANCE.md §Fused
        # aggregation): the same versioned stash, but placed ON DEVICE once
        # per broadcast — every encoded arrival densifies against it inside
        # the aggregator's per-arrival jit instead of a host numpy pass
        self._fused = bool(getattr(aggregator, "fused_agg", False))
        self._version_dev: dict[int, list] = {}
        # per-kind arrival densify jits for the ASYNC fused path: an
        # arrival only decodes against its version stash and answers the
        # door's finiteness question (one scalar readback); the gate /
        # evidence row waits for the drain, where the flush-time global
        # is the replacement reference — exactly when the stacked route
        # gates its staged entries
        self._fused_densify: dict[str, object] = {}
        # per-round fused per-arrival ingest seconds. These jits run in
        # the window the server would otherwise spend blocked on the
        # wire, but they are AGGREGATION work — goodput attributes them
        # to agg_flush, not wire_wait (see _goodput_extra), so a fused
        # A/B moves the right bucket.
        self._gp_fused_ingest_s = 0.0
        # rank -> the version its last upload PROVED it holds (the upload's
        # round tag: a client can only have encoded against a broadcast it
        # decoded). Drives the delta-broadcast warm set — optimistic
        # send-side tracking would desync after a dropped/corrupt frame,
        # proof-based tracking self-heals to the dense fallback.
        self._rank_version: dict[int, int] = {}
        # round-delta downlink (docs/ROBUSTNESS.md §Delta broadcast): warm
        # ranks get global@r - global@r-1, cold ranks (joiners, reprobes,
        # ranks that missed a round) the dense fallback. Sync mode only —
        # async dispatch is per-rank at arbitrary versions, so it stays
        # dense (warned below).
        self.delta_broadcast = bool(delta_broadcast)
        self.round_timeout_s = round_timeout_s
        self.ckpt_dir = ckpt_dir
        # Buffered-async mode (docs/ROBUSTNESS.md §Asynchronous buffered
        # rounds): ``async_buffer_k`` arms the event-driven loop — clients
        # train continuously against possibly-stale globals, each upload is
        # admitted (staleness bound; non-finite quarantined at the door),
        # staged into a bounded AsyncBuffer (overflow sheds the stalest,
        # counted, never blocks), and a full buffer (or deadline) flushes a
        # staleness-discounted gated aggregate, after which the uploading
        # ranks are immediately re-dispatched with the fresh global.
        # ``round_idx`` then counts GLOBAL UPDATES (buffer flushes), so the
        # checkpoint/eval/telemetry cadence carries over unchanged. None =
        # the synchronous barrier, untouched.
        self._async = async_buffer_k is not None
        self._buffer = None
        # fused × async composes: arrivals densify on device against the
        # version-stamped stash (_decode_upload_fused) and the buffered
        # entry carries dense device leaves; the drain folds each entry
        # at the door with its staleness-discounted weight (aggregator
        # load_buffered fused branch). The staleness discount is known
        # at arrival, but the GATE runs at drain against the flush-time
        # global — bound-0 / K=cohort parity with the sync barrier holds
        # bitwise (pinned in tests/test_async_buffer.py).
        if self._async and self.delta_broadcast:
            log.warning("delta_broadcast ignored in async buffered mode: "
                        "per-rank dispatch holds arbitrary versions, so "
                        "downlinks stay dense (uplink delta/quantized "
                        "tiers still apply)")
            self.delta_broadcast = False
        self._staleness_bound = staleness_bound
        if self._async:
            from fedml_tpu.core.async_buffer import (AsyncBuffer,
                                                     StalenessPolicy)

            self._staleness = StalenessPolicy.from_spec(
                staleness, bound=staleness_bound)
            self._discount_np = self._staleness.discount_np()
            self._buffer = AsyncBuffer(int(async_buffer_k),
                                       capacity=buffer_capacity)
            self.buffer_deadline_s = buffer_deadline_s
            self._buffer_epoch = 0
            self._buffer_first_t: float | None = None
            # per-rank dispatch counters (the sampling key: rank r's n-th
            # dispatch trains client_sampling(n)[r-1], the same structure
            # the sync round loop uses), outstanding-dispatch set (dedups
            # chaos-duplicated uploads and drives the reprobe), and the
            # bound-0 parking lot (see StalenessPolicy.synchronous)
            self._dispatch_wave: dict[int, int] = {}
            # rank -> the ONE outstanding dispatch's wave: the upload gate
            # folds exactly the wave it awaits, so a reprobe's superseded
            # twin (or a chaos duplicate) is dropped instead of spawning a
            # second self-perpetuating dispatch stream
            self._awaiting: dict[int, int] = {}
            self._parked: list[int] = []
            self._last_dispatch_version: dict[int, int] = {}
            self._bcast_version = -1
            self._bcast_pack = None
            # graceful drain: after the last flush the server keeps its
            # receive loop up until every outstanding dispatch's upload
            # landed (and was discarded), so in-flight clients never race
            # a torn-down transport; a grace timer bounds the wait when a
            # rank crashed mid-dispatch
            self._draining = False
            self._drain_grace_s = round_timeout_s or 2.0
            # reprobe grace is WALL-CLOCK, not versions: with small K and a
            # large fleet, _DEAD_RANK_REPROBE_ROUNDS global updates can
            # elapse faster than one slow rank's honest fit — declaring its
            # wave lost on version age alone would drop every upload it
            # ever produces (permanent starvation). A wave is only declared
            # lost after this many SECONDS since its dispatch.
            self._reprobe_grace_s = (round_timeout_s or buffer_deadline_s
                                     or 30.0)
            self._last_dispatch_t: dict[int, float] = {}
            # per-JOB shed tally for round records (the registry counter is
            # process-cumulative: soak campaigns run many jobs per process,
            # and trial N's records must not carry trial N-1's sheds)
            self._shed_counts: dict[str, int] = {}
            from fedml_tpu.obs import perf_instrument as _perf

            # pre-register every shed reason so the Prometheus export
            # carries the full fed_async_shed_total family (zeros
            # included) the moment async mode is armed
            _perf.ensure_async_shed_families()
        self.heartbeat_max_age_s = heartbeat_max_age_s
        # rank -> round its delivery last failed. Initialized HERE, not
        # lazily at first failure: two sender paths (round loop + watchdog
        # thread) can fail concurrently, and a hasattr-then-create race
        # would lose one rank's failure record.
        self._undeliverable: dict[int, int] = {}
        # obs.Telemetry: per-round event records (sampled ids, aggregate/eval
        # span timings, update norm, comm byte/message deltas). None = the
        # seed behavior, zero extra work.
        self.telemetry = telemetry
        self._round_ids: list[int] = []
        # cross-rank tracer (obs/tracing.py): present only when the
        # Telemetry bundle opted in (trace_dir / trace=True). None = no
        # __trace params on any frame — the wire is byte-identical.
        self._dtracer = telemetry.tracer if telemetry is not None else None
        # fleet observability plane (obs/fleet.py): present only when the
        # bundle armed a collector (Telemetry(fleet=True)). None = no
        # __telemetry marker on any frame — the wire is byte-identical.
        self._fleet = getattr(telemetry, "fleet", None)
        if self._async and self._dtracer is not None:
            # the per-round distributed-trace model is sequential
            # (begin_round..finish_round); async flushes overlap in-flight
            # client work — same policy as the pipelined drivers: say so
            # loudly, emit no round traces (round records still carry the
            # async staleness/shed block)
            log.warning("async buffered mode emits no per-round distributed "
                        "traces (client work overlaps flushes; the trace "
                        "model is sequential) — run synchronously for "
                        "trace-dir runs")
            self._dtracer = None
        if telemetry is not None:
            import dataclasses

            from fedml_tpu.obs.tracing import RoundTracer

            from fedml_tpu.data import dataset_source

            self._tracer = RoundTracer(sink=self._dtracer)
            telemetry.run_header(dataclasses.asdict(aggregator.cfg),
                                 engine="distributed", backend=backend,
                                 world_size=size,
                                 dataset_source=dataset_source(
                                     aggregator.dataset),
                                 tracing=self._dtracer is not None)
        # ---- server crash recovery (docs/ROBUSTNESS.md §Server crash
        # recovery): a ckpt_dir implies the durable round WAL next to it
        # (override with wal_dir). Boot order matters: replay FIRST (the
        # restart epoch and the open-round evidence), then open the log
        # for append and journal this boot, then restore state.
        self.wal = None
        self._wal_replay = None
        self._restart_epoch = 0
        self._resume_round: int | None = None
        self._resume_pending: set[int] = set()
        self._resume_acks: dict[int, tuple[int, int]] = {}
        self._crash_plan: list[tuple[int, int | None]] = []
        self._sim_crash: SimulatedServerCrash | None = None
        self._uploads_this_round = 0
        if wal_dir is None and ckpt_dir is not None:
            wal_dir = os.path.join(ckpt_dir, "wal")
        if wal_dir is not None:
            from fedml_tpu.core.wal import RoundWAL
            from fedml_tpu.obs import perf_instrument as _perf

            self._wal_replay = RoundWAL.replay(wal_dir)
            self._restart_epoch = self._wal_replay.restart_epochs
            self.wal = RoundWAL(wal_dir)
            self.wal.append("restart", sync=True,
                            epoch=self._restart_epoch)
            _perf.ensure_restart_families()
            _perf.sync_server_restarts(self._restart_epoch)
            # the aggregator journals what the WAL must witness: DP
            # pre-charges (fsync'd BEFORE noise is drawn — ε can never be
            # under-reported) and quarantine verdicts (forensic trail; the
            # ledger's commit-time authority is quarantine.json)
            self.aggregator.wal = self.wal
            if hasattr(self.aggregator, "quarantine"):
                self.aggregator.quarantine.journal = (
                    lambda e: self.wal.append("quarantine", **e))
            if self._buffer is not None:
                # async buffer membership rides the WAL: recovery ledgers
                # exactly the admitted-and-unflushed entries that died
                # with the process
                self._buffer.journal = self._journal_buffer
            if self._restart_epoch:
                log.warning("server restart epoch %d (WAL at %s): "
                            "recovering", self._restart_epoch, wal_dir)
        if ckpt_dir is not None or self._wal_replay is not None:
            self._maybe_resume()
        self._round_lock = threading.Lock()
        self._validate_world_size(size)
        ts = kw.pop("timeout_s", None)
        if round_timeout_s is not None and round_timeout_s <= 0:
            # 0 would arm the elastic error-swallowing but DISARM the
            # watchdog ('or' treats 0.0 as unset) — a silent permanent hang
            raise ValueError(f"round_timeout_s={round_timeout_s} must be > 0")
        if round_timeout_s is not None:
            # elastic mode: a send to a dead/unreachable client must not
            # absorb more than one round deadline (the gRPC default is a
            # 600 s boot-tolerance window) — and its failure is handled
            # (the client becomes a straggler), not fatal
            kw.setdefault("send_timeout_s", round_timeout_s)
        super().__init__(rank, size, backend, timeout_s=round_timeout_s or ts, **kw)
        _obs.set_ranks_alive(size - 1)  # all peers presumed reachable at boot

    def _validate_world_size(self, size: int) -> None:
        """One worker process per sampled client (FedAvgAPI.py:20-28
        launches client_num_per_round+1 ranks); a deficit would silently
        aggregate fewer clients than configured. The hierarchical server
        (distributed/fedavg/hierarchy.py) overrides: its world also
        carries the edge-aggregator ranks."""
        if size - 1 != self.aggregator.cfg.client_num_per_round:
            raise ValueError(
                f"worker count {size - 1} != client_num_per_round="
                f"{self.aggregator.cfg.client_num_per_round}"
            )

    # a rank whose delivery failed is probed again only every k-th round:
    # one dead peer must not cost every round a full send deadline, but a
    # REBOOTED peer must still be able to rejoin
    _DEAD_RANK_REPROBE_ROUNDS = 4

    def _update_alive_gauge(self) -> None:
        """fed_ranks_alive from the undeliverable/reprobe bookkeeping —
        world size may be unknown on a partially-built instance (tests
        drive the elastic send path without the comm stack). Scheduled-
        offline ranks count as NOT alive alongside the undeliverable set,
        so alive and the quorum rule's churn-shrunken expected
        denominator move together through diurnal troughs (a trough never
        looks like an outage; a genuine crash inside the available set
        still dips alive below the shrunken expectation)."""
        size = getattr(self, "size", None)
        if size is not None:
            dead = set(self._undeliverable) | self._offline_now
            _obs.set_ranks_alive(size - 1 - len(dead))

    def _scheduled_offline(self) -> set[int]:
        """The churn trace's scheduled-offline rank set for the CURRENT
        round's window (empty with no trace). Publishes the
        fed_ranks_scheduled_offline gauge and refreshes fed_ranks_alive —
        every skip/admission/watchdog path reads availability through
        here so the health view can never drift from the decisions."""
        if self.churn_trace is None:
            return set()
        off = self.churn_trace.scheduled_offline_ranks(
            self.round_idx, self.size)
        if off != self._offline_now:
            self._offline_now = off
            _obs.set_ranks_scheduled_offline(len(off))
            self._update_alive_gauge()
            if self._fleet is not None:
                # fedtop's avail column: rank 0 owns the trace, so it
                # stamps the fleet rows directly (an away rank sends no
                # digests to say so itself)
                self._fleet.note_avail(off, self.size)
        return off

    @staticmethod
    def _is_transport_error(e: BaseException) -> bool:
        """Only delivery failures are elastic-tolerable; config/programming
        errors (KeyError on a bad ip table, serialization bugs) stay
        fatal. grpc.RpcError is detected by name so the server module
        needs no grpc import for the loopback/mqtt backends."""
        if isinstance(e, (ConnectionError, TimeoutError, OSError)):
            return True
        return any(c.__name__ == "RpcError" for c in type(e).__mro__)

    def send_message(self, msg) -> None:
        """Elastic mode tolerates an unreachable downlink: the failed rank
        simply has nothing to report this round and the watchdog drops it
        (the reference aborts the whole job instead — raise_MPI_error ->
        MPI.COMM_WORLD.Abort(), fedml_api/utils/context.py:9-18).
        Without a round deadline, delivery failures stay fatal."""
        rank = int(msg.get_receiver_id())
        failed_at = self._undeliverable.get(rank)
        # reprobe only on a POSITIVE multiple of the interval: at
        # round_idx == failed_at the failure was just recorded, and a
        # second send in the same round (e.g. the FINISH broadcast after a
        # failed final sync) must not re-block a full send deadline
        if (failed_at is not None and
                (self.round_idx == failed_at or
                 (self.round_idx - failed_at) % self._DEAD_RANK_REPROBE_ROUNDS)):
            log.debug("elastic: skipping send to dead rank %d "
                      "(failed at round %d; reprobed every %d rounds)",
                      rank, failed_at, self._DEAD_RANK_REPROBE_ROUNDS)
            return
        try:
            super().send_message(msg)
            if failed_at is not None:
                log.info("elastic: rank %d reachable again", rank)
                self._undeliverable.pop(rank, None)
                self._update_alive_gauge()
        except Exception as e:
            if self.round_timeout_s is None or not self._is_transport_error(e):
                raise
            self._undeliverable[rank] = self.round_idx
            self._update_alive_gauge()
            log.warning("elastic: dropping undeliverable send to rank %d",
                        rank, exc_info=True)

    def _ckpt_state_template(self):
        import jax

        st = {
            "net": self.aggregator.net,
            "server_opt_state": getattr(self.aggregator, "_server_opt_state", ()),
            # dp runs store the server noise RNG here so a resumed job
            # continues the key stream instead of REPLAYING the same noise
            "rng": getattr(self.aggregator, "_noise_rng",
                           jax.random.PRNGKey(0)),
        }
        if getattr(self.aggregator, "accountant", None) is not None:
            import numpy as np

            # cumulative RDP totals: epsilon() must cover pre-restart rounds
            st["dp_rdp"] = np.asarray(self.aggregator.accountant._rdp)
        return st

    def _maybe_resume(self):
        import time as _time

        t0 = _time.monotonic()
        import numpy as np

        from fedml_tpu.core.checkpoint import restore_latest

        committed = -1
        if self.ckpt_dir is not None:
            template = dict(self._ckpt_state_template(),
                            round=np.asarray(0, np.int64))
            # the newest RESTORABLE checkpoint is the commit authority: a
            # torn newest file (crash mid-save) is skipped + counted and
            # recovery falls back to the previous round
            hit = restore_latest(self.ckpt_dir, template)
            if hit is not None:
                committed, state = hit
                # sharded server plane: checkpoints gather on save (shard-
                # agnostic layout; the npz fallback restores plain host
                # arrays) — re-partition per the rule table so the device-
                # resident-sharded invariant survives resume, mirroring
                # the standalone engine's load_state, and refresh the
                # per-device sizing gauge
                part = getattr(self.aggregator, "_partitioner", None)
                self.aggregator.net = (part.shard(state["net"])
                                       if part is not None else state["net"])
                if hasattr(self.aggregator, "_server_opt_state"):
                    opt = state["server_opt_state"]
                    self.aggregator._server_opt_state = (
                        part.shard(opt) if part is not None else opt)
                if part is not None:
                    self.aggregator._record_server_state_bytes(
                        getattr(self.aggregator, "_server_opt_state", ()))
                if hasattr(self.aggregator, "_noise_rng"):
                    self.aggregator._noise_rng = state["rng"]
                if "dp_rdp" in state and getattr(self.aggregator,
                                                 "accountant",
                                                 None) is not None:
                    self.aggregator.accountant._rdp = np.asarray(
                        state["dp_rdp"])
            # reload persisted eval history + quarantine ledger so a
            # restarted process reports the SAME artifacts an
            # uninterrupted run would (post-resume saves must not rewrite
            # them with only the post-restart records)
            import json

            hist_path = os.path.join(self.ckpt_dir, "history.json")
            if os.path.exists(hist_path):
                with open(hist_path) as f:
                    self.aggregator.history = json.load(f)
            quar_path = os.path.join(self.ckpt_dir, "quarantine.json")
            if os.path.exists(quar_path) and \
                    hasattr(self.aggregator, "quarantine"):
                with open(quar_path) as f:
                    self.aggregator.quarantine.restore(json.load(f))
        replay = self._wal_replay
        if committed < 0 and (replay is None or not replay.records):
            return  # genuinely fresh start
        self.round_idx = committed + 1
        self._recover_in_flight(committed, replay)
        if self.wal is not None:
            from fedml_tpu.obs import perf_instrument as _perf

            _perf.record_recovery_seconds(_time.monotonic() - t0)
        log.info("resumed from checkpoint+WAL: committed round %d, next "
                 "round %d%s (restart epoch %d)", committed, self.round_idx,
                 " [open round re-runs]" if self._resume_round is not None
                 else "", self._restart_epoch)

    def _recover_in_flight(self, committed: int, replay) -> None:
        """WAL half of recovery: reconstruct what the crash interrupted.

        - an OPEN round (anything journaled past the last commit) re-runs
          as ``self.round_idx`` behind a resume probe, and every upload
          the dead server had ACCEPTED (sync ``upload`` / async buffer
          ``admit`` records — the payloads died with the process) is
          ledgered ``server_restart``, slot-exact;
        - DP pre-charges past the committed round re-charge the
          accountant (the noise MAY have been released pre-crash; ε must
          never read lower than the charges incurred — the conservative
          direction);
        - async dispatch-wave counters resume past their journaled
          maxima, keeping the per-rank sampling chain monotonic.

        Subclasses extend (the masked secure tier sheds a half-revealed
        round as ``secagg_shed`` — docs/ROBUSTNESS.md §Secure
        aggregation)."""
        if replay is None:
            return
        acct = getattr(self.aggregator, "accountant", None)
        if acct is not None:
            for rec in replay.of_kind("precharge"):
                if int(rec.get("round", -1)) > committed:
                    acct.step(float(rec["q"]), float(rec["z"]))
                    log.warning("recovery: re-charged DP accountant for "
                                "the pre-crash charge of round %d "
                                "(q=%.6f, z=%.3f)", rec["round"],
                                rec["q"], rec["z"])
        # per-client ledgers rebuild from EVERY precharge record (the WAL
        # is append-only for the run): unlike the accountant's cumulative
        # RDP, the variable-key {client: rdp} map rides no checkpoint —
        # the journaled client ids ARE its durable form. The in-flight
        # round's record re-charges too (its noise may have been released
        # pre-crash), so per-client ε can over-count by one round per
        # crash but never under-report — the precharge contract at
        # client granularity.
        ledger = getattr(self.aggregator, "client_ledger", None)
        if ledger is not None:
            recharged = 0
            for rec in replay.of_kind("precharge"):
                clients = rec.get("clients")
                if clients:
                    ledger.charge([int(c) for c in clients],
                                  float(rec["z"]))
                    recharged += 1
            if recharged:
                from fedml_tpu.obs import perf_instrument as _perf

                s = ledger.summary()
                _perf.set_client_epsilon(s["eps_client_max"],
                                         s["eps_client_mean"],
                                         s["clients_charged"])
                log.warning("recovery: rebuilt per-client privacy "
                            "ledgers from %d precharge record(s) — "
                            "eps_client_max=%.6f over %d client(s)",
                            recharged, s["eps_client_max"],
                            s["clients_charged"])
        if self._async:
            for rank, w in replay.dispatch_waves().items():
                self._dispatch_wave[rank] = w + 1
        in_flight = replay.since_last_commit(
            ("broadcast", "dispatch", "upload", "admit"))
        if not in_flight or self.round_idx >= self.round_num:
            return
        self._resume_round = self.round_idx
        lost = replay.since_last_commit(("upload", "admit"))
        # an admit whose entry was overflow-SHED pre-crash held no
        # foldable work at death (and was already counted overflow by the
        # live server) — it must not be re-ledgered server_restart
        shed_keys = {(int(r.get("rank", -1)), int(r.get("wave", -1)))
                     for r in replay.since_last_commit("shed")}
        lost = [rec for rec in lost
                if rec.get("kind") != "admit"
                or (int(rec["rank"]),
                    int(rec.get("wave", -1))) not in shed_keys]
        for rec in lost:
            self.aggregator.quarantine.record(
                int(rec.get("round", self.round_idx)), int(rec["rank"]),
                "server_restart", client=rec.get("client"))
            _obs.record_update_rejected("server_restart")
            if self._async:
                self._record_shed("server_restart")
        log.warning("recovery: round %d was in flight at the crash — "
                    "%d accepted upload(s) lost with the process "
                    "(ledgered server_restart); re-dispatching behind a "
                    "resume probe", self.round_idx, len(lost))

    def _maybe_save(self):
        if self.ckpt_dir is None:
            return
        from fedml_tpu.core.checkpoint import save_round

        st = self._ckpt_state_template()
        extra = {k: v for k, v in st.items()
                 if k not in ("net", "server_opt_state", "rng")}
        save_round(self.ckpt_dir, self.round_idx, st["net"],
                   st["server_opt_state"], st["rng"],
                   history=self.aggregator.history,
                   extra_state=extra or None)
        # the quarantine ledger rides the commit (atomic + fsync'd): a
        # restarted process must report the same ledger an uninterrupted
        # run would — the WAL's quarantine records are forensic only
        if hasattr(self.aggregator, "quarantine"):
            import json

            from fedml_tpu.core.wal import durable_write

            durable_write(os.path.join(self.ckpt_dir, "quarantine.json"),
                          json.dumps(
                              self.aggregator.quarantine.entries()).encode())
        if self.wal is not None:
            # commit AFTER the checkpoint rename: the checkpoint is the
            # state authority; the record witnesses it and resets the
            # WAL's in-flight (since_last_commit) window
            self.wal.commit(self.round_idx)

    def _broadcast_finish(self):
        # final best-effort delivery to EVERY rank, including ones the
        # elastic sender had marked undeliverable (the async path's
        # _finish_async rule, now on the sync path too): a rank that
        # RECOVERED after its crash window but whose reprobe round never
        # came would otherwise miss FINISH and block in its receive loop
        # until the simulated-launch join timeout abandons the thread. A
        # still-dead rank just re-fails the send (re-marked, skipped).
        self._undeliverable.clear()
        self._update_alive_gauge()
        for rank in range(1, self.size):
            msg = Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, rank)
            # round-tag the FINISH like every other s2c frame: the chaos
            # layer's windowed rules key on the frame's round (falling
            # back to the link's LAST-KNOWN round for untagged frames),
            # so an untagged FINISH to a rank whose link last saw a
            # crash-window round would read as still-crashed forever —
            # even though the window is over (stock peers ignore the
            # extra param; the wire is otherwise unchanged)
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            self.send_message(msg)
        self.finish()

    def run(self):
        if self.round_idx >= self.round_num:  # resumed past the last round
            self._broadcast_finish()
            return
        if self._resume_round is not None:
            # recovery found an open round: probe before re-dispatching so
            # the fleet's in-flight pre-crash work is accounted, then the
            # ack quorum (or the backstop) re-broadcasts under this epoch
            with self._round_lock:
                self._send_resume_probes()
        else:
            self.send_init_msg()
        super().run()
        if self._sim_crash is not None:
            # a crash point fired on a non-dispatch thread (watchdog /
            # timer) and stopped the loop: surface it to the supervision
            # driver from the thread that owns run()
            raise self._sim_crash

    def _broadcast_model(self, msg_type: str, global_params) -> None:
        """Sample this round's clients and broadcast ``global_params`` to
        every rank under ``msg_type`` — the shared body of send_init_msg
        and the round-advance sync (they must not diverge). Starts the
        round's trace and rides its context on each frame when tracing."""
        self._maybe_crash("broadcast")
        if self.telemetry is not None:
            # round-economics stamps (obs/goodput.py): the round's wall
            # starts here; wire_wait is bcast-done -> last counted arrival
            import time as _time

            self._gp_bcast_start_t = _time.monotonic()
            self._gp_last_arrival_t = None
        self._gp_fused_ingest_s = 0.0
        if self.wal is not None:
            # journal the round opening BEFORE any frame leaves: recovery
            # must know round r was in flight even if the crash lands
            # mid-broadcast
            self.wal.append("broadcast", sync=True, round=self.round_idx)
        self._uploads_this_round = 0
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        self._round_ids = [int(c) for c in client_indexes]
        # stamp the aggregator's accepted round BEFORE any client can
        # answer the broadcast — uploads tagged with any other round are
        # rejected at the slotting layer (add_local_trained_result)
        self.aggregator.begin_round(self.round_idx)
        # heartbeat-driven cohort admission (docs/ROBUSTNESS.md
        # §Asynchronous buffered rounds): ranks silent past the age
        # threshold are excluded from this round — no send, and the round
        # barrier does not wait for them (the aggregator's excluded set) —
        # except on reprobe rounds, which re-invite them so a resumed rank
        # rejoins; its first frame resets the age and readmits it for good
        suspects = _obs.suspect_ranks(
            range(1, self.size), self.heartbeat_max_age_s, self.round_idx,
            self._DEAD_RANK_REPROBE_ROUNDS)
        # scheduled-offline vs suspected-dead: a rank the churn trace says
        # is away is EXPECTED silent — it must never ride the suspect
        # path (no reprobe/backoff churn, no alert pressure). It is still
        # excluded from the cohort (no send, barrier does not wait).
        offline = self._scheduled_offline()
        suspects -= offline
        self.aggregator.excluded = {r - 1 for r in suspects | offline}
        if offline:
            log.debug("round %d: %d rank(s) scheduled-offline by the churn "
                      "trace — skipped silently", self.round_idx,
                      len(offline))
        if (self.heartbeat_max_age_s is not None
                and self.round_idx % self._DEAD_RANK_REPROBE_ROUNDS == 0):
            # reprobe round: force a REAL send attempt to every silent rank
            # — the elastic undeliverable skip runs on its own (failed_at
            # anchored) cadence, and the two schedules can otherwise never
            # align, leaving a resumed rank permanently uninvited
            silent = _obs.suspect_ranks(
                range(1, self.size), self.heartbeat_max_age_s,
                self.round_idx, 0)  # reprobe_every=0: the raw verdict
            for rank in list(self._undeliverable):
                if rank in silent:
                    self._undeliverable.pop(rank, None)
            self._update_alive_gauge()
        if suspects:
            log.warning("round %d: heartbeat-suspect ranks %s excluded "
                        "from the cohort (age > %.2fs; reprobed every %d "
                        "rounds)", self.round_idx, sorted(suspects),
                        self.heartbeat_max_age_s,
                        self._DEAD_RANK_REPROBE_ROUNDS)
        # stash the pack AS CLIENTS WILL SEE IT: under a lossy wire
        # codec their deltas are relative to the decoded broadcast; under
        # delta_broadcast the stash IS the base chain every rank holds
        delta, base_v = None, self.round_idx - 1
        if self.delta_broadcast:
            import numpy as np

            from fedml_tpu.comm.delta import apply_delta, round_delta

            pack = [np.asarray(v) for v in global_params]
            prev = self._version_pack.get(base_v)
            if prev is not None:
                delta = round_delta(pack, prev)
                # the canonical held value is the CHAIN value prev + delta
                # (f32 adds), not the pack: warm clients compute exactly
                # this, and the dense fallback ships it verbatim (marked
                # lossless) so every rank holds the same base bitwise
                stash = apply_delta(prev, delta)
            else:
                stash = pack
        else:
            stash = codec_roundtrip(global_params)
        self._bcast_leaves = stash
        self._stash_version(self.round_idx, stash)
        tr = self._dtracer
        if tr is not None:
            tr.begin_round(self.round_idx)
        for rank in range(1, self.size):
            if rank in suspects or rank in offline:
                continue
            msg = Message(msg_type, self.rank, rank)
            if delta is not None and self._rank_version.get(rank) == base_v:
                # warm rank: its last upload proved it holds base_v
                msg.add_params(MyMessage.MSG_ARG_KEY_DELTA_PARAMS, delta)
                msg.add_params(MyMessage.MSG_ARG_KEY_BASE_VERSION, base_v)
            else:
                msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, stash
                               if self.delta_broadcast else global_params)
                if self.delta_broadcast:
                    # the dense fallback must land bit-exact: the next
                    # delta is computed against this chain value
                    msg.mark_lossless(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(client_indexes[rank - 1]))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            if self._restart_epoch:
                # post-restart session tag, echoed on every upload so the
                # epoch gate sheds pre-crash in-flight work exactly once;
                # absent at epoch 0 — the wire is unchanged until a crash
                # actually happened
                msg.add_params(MyMessage.MSG_ARG_KEY_RESTART_EPOCH,
                               self._restart_epoch)
            if tr is not None:  # trace context rides the header scalars
                msg.add_params(TRACE_KEY, tr.broadcast_ctx(rank))
            if self._fleet is not None:
                # fleet enablement marker (obs/fleet.py): tells the rank
                # to piggyback digests on its uploads; absent with the
                # plane off, so the wire stays byte-identical. A churn-
                # armed server stamps avail so the rank's digests echo it
                # (fedtop's avail column) — a frame only reaches
                # scheduled-ONLINE ranks, hence the constant
                marker = self._fleet.marker()
                if self.churn_trace is not None:
                    marker = {**marker, "avail": 1.0}
                msg.add_params(MyMessage.MSG_ARG_KEY_TELEMETRY, marker)
            self.send_message(msg)
        if tr is not None:
            tr.end_broadcast()
        if self.telemetry is not None:
            import time as _time

            self._gp_bcast_end_t = _time.monotonic()
        # after_uploads=0: mid-round with the broadcast OUT but zero
        # uploads accepted — distinct from None (between commits, before
        # any frame of the round leaves)
        self._maybe_crash("post_broadcast")

    # ------------------------------------------- versioned broadcast stash
    # Retain enough versions to cover any admissible async staleness, with
    # a floor for the unbounded-staleness mode; sync rounds only ever look
    # up the current one.
    _VERSION_RETAIN = 16

    def _stash_version(self, version: int, decoded_leaves) -> None:
        self._version_pack[int(version)] = decoded_leaves
        if self._fused:
            # one H2D per broadcast version (async — overlaps the round)
            # instead of a host densify per upload against the numpy stash
            import jax

            self._version_dev[int(version)] = [
                jax.device_put(v) for v in decoded_leaves]
        if self._async:
            retain = max(self._VERSION_RETAIN,
                         (self._staleness_bound or 0) + 2)
        else:
            # sync rounds: the round-tag gate drops anything but the
            # current round before densify, and the delta chain needs only
            # r-1 — two stashed versions, not 16 model copies
            retain = 2
        for v in [v for v in self._version_pack if v <= version - retain]:
            del self._version_pack[v]
            self._version_dev.pop(v, None)

    def _decode_upload(self, msg_params, sender: int, version: int):
        """Densify one upload's wire payload into full model leaves:
        top-k (comm/sparse.py) and delta/quantized tiers (comm/delta.py)
        decode against the stashed broadcast of ``version``; dense uploads
        pass through. Returns None when the payload is structurally
        undecodable (quarantined + counted — a chaos bit-flip that
        survived CRC must cost one upload, not the server); raises on a
        genuinely unversioned base (a protocol bug, not wire damage)."""
        has_sparse = MyMessage.MSG_ARG_KEY_SPARSE_IDX in msg_params
        has_upd = MyMessage.MSG_ARG_KEY_UPDATE_CODEC in msg_params
        if not (has_sparse or has_upd):
            return msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS]
        base = self._version_pack.get(int(version))
        if base is None:
            raise RuntimeError(
                f"upload from rank {sender} is encoded against version "
                f"{version}, which was never broadcast (or predates this "
                f"server) — encoded uplinks require a versioned base "
                f"(stashed: {sorted(self._version_pack)})")
        try:
            if has_sparse:
                from fedml_tpu.comm.delta import CorruptPayload
                from fedml_tpu.comm.sparse import topk_decode

                idx = msg_params[MyMessage.MSG_ARG_KEY_SPARSE_IDX]
                val = msg_params[MyMessage.MSG_ARG_KEY_SPARSE_VAL]
                if len(idx) != len(base) or len(val) != len(base):
                    # zip would silently truncate a leaf-count mismatch —
                    # validate like the delta branch does
                    raise CorruptPayload(
                        f"sparse payload has {len(idx)}/{len(val)} leaves, "
                        f"model has {len(base)}")
                return topk_decode(base, idx, val)
            from fedml_tpu.comm.delta import apply_delta, decode_update

            codec = str(msg_params[MyMessage.MSG_ARG_KEY_UPDATE_CODEC])
            delta = decode_update(
                msg_params[MyMessage.MSG_ARG_KEY_UPDATE_PAYLOAD],
                msg_params[MyMessage.MSG_ARG_KEY_UPDATE_SCALE],
                codec, base)
            return apply_delta(base, delta)
        except (ValueError, KeyError, TypeError, IndexError) as e:
            # structural garbage that survived the CRC: quarantine at the
            # gate's ledger (reason 'undecodable'), count, drop — VALUE
            # garbage (corrupt scales -> non-finite decode) flows through
            # and dies at the sanitation gate instead. IndexError: a
            # bit-flipped sparse index lands out of range in topk_decode's
            # scatter.
            self.aggregator.quarantine.record(
                self.round_idx, sender, "undecodable")
            _obs.record_update_rejected("undecodable")
            log.warning("quarantining undecodable upload from rank %d "
                        "(%s)", sender, e)
            return None

    def _stage_fused(self, msg_params, sender: int, version: int,
                     sample_num) -> bool:
        """Fused twin of ``_decode_upload`` + ``add_local_trained_result``
        (docs/PERFORMANCE.md §Fused aggregation): host work is structural
        validation ONLY (zlib inflate to int8, leaf-count/size checks —
        comm/delta.inflate_update); the densify → gate → fold runs inside
        the aggregator's per-arrival jit against the device-resident
        version stash. Returns False when the payload is structurally
        undecodable (quarantined + counted, exactly like the stacked
        path); raises on a genuinely unversioned base."""
        import numpy as np

        from fedml_tpu.comm.delta import CorruptPayload, inflate_update

        has_sparse = MyMessage.MSG_ARG_KEY_SPARSE_IDX in msg_params
        has_upd = MyMessage.MSG_ARG_KEY_UPDATE_CODEC in msg_params
        base_dev = None
        if has_sparse or has_upd:
            base_dev = self._version_dev.get(int(version))
            base = self._version_pack.get(int(version))
            if base is None or base_dev is None:
                raise RuntimeError(
                    f"upload from rank {sender} is encoded against version "
                    f"{version}, which was never broadcast (or predates "
                    f"this server) — encoded uplinks require a versioned "
                    f"base (stashed: {sorted(self._version_pack)})")
        # EVERY structural failure — validation here, inflate_update, or a
        # shape error surfacing at the ingest jit's trace — must cost one
        # upload, never the receive loop (add_fused_result sits inside the
        # try for exactly that reason: the stacked _decode_upload's
        # contract, kept on the fused route)
        try:
            if not (has_sparse or has_upd):
                self.aggregator.add_fused_result(
                    sender - 1, "dense",
                    msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS], None,
                    sample_num, version, None)
                return True
            if has_sparse:
                idx = msg_params[MyMessage.MSG_ARG_KEY_SPARSE_IDX]
                val = msg_params[MyMessage.MSG_ARG_KEY_SPARSE_VAL]
                if len(idx) != len(base) or len(val) != len(base):
                    raise CorruptPayload(
                        f"sparse payload has {len(idx)}/{len(val)} leaves, "
                        f"model has {len(base)}")
                for sel, t in zip(idx, base):
                    sel = np.asarray(sel)
                    # the device scatter silently drops out-of-bounds
                    # indices where the host path raised IndexError —
                    # validate here so a bit-flipped index still costs
                    # exactly one upload, on both routes
                    if sel.size and np.issubdtype(
                            np.asarray(t).dtype, np.floating) and (
                            int(sel.max()) >= np.asarray(t).size
                            or int(sel.min()) < 0):
                        raise CorruptPayload(
                            f"sparse index out of range for a "
                            f"{np.asarray(t).size}-entry leaf")
                self.aggregator.add_fused_result(
                    sender - 1, "topk", (list(idx), list(val)), None,
                    sample_num, version, base_dev)
                return True
            codec = str(msg_params[MyMessage.MSG_ARG_KEY_UPDATE_CODEC])
            raw, scales = inflate_update(
                msg_params[MyMessage.MSG_ARG_KEY_UPDATE_PAYLOAD],
                msg_params[MyMessage.MSG_ARG_KEY_UPDATE_SCALE],
                codec, base)
            self.aggregator.add_fused_result(
                sender - 1, codec, raw, scales, sample_num, version,
                base_dev)
            return True
        except (ValueError, KeyError, TypeError, IndexError) as e:
            self.aggregator.quarantine.record(
                self.round_idx, sender, "undecodable")
            _obs.record_update_rejected("undecodable")
            log.warning("quarantining undecodable upload from rank %d "
                        "(%s)", sender, e)
            return False

    def _decode_upload_fused(self, msg_params, sender: int, version: int):
        """Fused twin of ``_decode_upload`` for the ASYNC path: the same
        structural validation as ``_stage_fused``, but the arrival jit
        only densifies against the device-resident version stash and
        answers the door's finiteness question — the gate/evidence row
        waits for the drain, whose flush-time global is the replacement
        reference (matching when the stacked route gates its staged
        entries). Returns ``(dense_device_leaves, finite)`` — the drain
        folds the leaves as kind='dense' whatever rode the wire — or
        None when the payload is structurally undecodable (quarantined +
        counted); raises on a never-broadcast base version."""
        import numpy as np

        from fedml_tpu.comm.delta import CorruptPayload, inflate_update
        from fedml_tpu.core.fused_agg import make_fused_densify

        has_sparse = MyMessage.MSG_ARG_KEY_SPARSE_IDX in msg_params
        has_upd = MyMessage.MSG_ARG_KEY_UPDATE_CODEC in msg_params
        base_dev = None
        if has_sparse or has_upd:
            base_dev = self._version_dev.get(int(version))
            base = self._version_pack.get(int(version))
            if base is None or base_dev is None:
                raise RuntimeError(
                    f"upload from rank {sender} is encoded against version "
                    f"{version}, which was never broadcast (or predates "
                    f"this server) — encoded uplinks require a versioned "
                    f"base (stashed: {sorted(self._version_pack)})")

        def _jit(kind):
            fn = self._fused_densify.get(kind)
            if fn is None:
                fn = make_fused_densify(kind, self.aggregator._fused_meta)
                self._fused_densify[kind] = fn
            return fn

        empty = None
        try:
            if not (has_sparse or has_upd):
                leaves, finite = _jit("dense")(
                    msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS],
                    empty, [])
            elif has_sparse:
                idx = msg_params[MyMessage.MSG_ARG_KEY_SPARSE_IDX]
                val = msg_params[MyMessage.MSG_ARG_KEY_SPARSE_VAL]
                if len(idx) != len(base) or len(val) != len(base):
                    raise CorruptPayload(
                        f"sparse payload has {len(idx)}/{len(val)} leaves, "
                        f"model has {len(base)}")
                for sel, t in zip(idx, base):
                    sel = np.asarray(sel)
                    # the device scatter silently drops out-of-bounds
                    # indices where the host path raised IndexError —
                    # validate so a bit-flipped index costs one upload
                    if sel.size and np.issubdtype(
                            np.asarray(t).dtype, np.floating) and (
                            int(sel.max()) >= np.asarray(t).size
                            or int(sel.min()) < 0):
                        raise CorruptPayload(
                            f"sparse index out of range for a "
                            f"{np.asarray(t).size}-entry leaf")
                leaves, finite = _jit("topk")(
                    (list(idx), list(val)), empty, base_dev)
            else:
                codec = str(msg_params[MyMessage.MSG_ARG_KEY_UPDATE_CODEC])
                raw, scales = inflate_update(
                    msg_params[MyMessage.MSG_ARG_KEY_UPDATE_PAYLOAD],
                    msg_params[MyMessage.MSG_ARG_KEY_UPDATE_SCALE],
                    codec, base)
                leaves, finite = _jit(codec)(raw, scales, base_dev)
            # one scalar readback — the async door's admit/shed decision
            # is host control flow either way (the stacked route pays a
            # full host isfinite scan here)
            return leaves, bool(finite)
        except (ValueError, KeyError, TypeError, IndexError) as e:
            self.aggregator.quarantine.record(
                self.round_idx, sender, "undecodable")
            _obs.record_update_rejected("undecodable")
            log.warning("quarantining undecodable upload from rank %d "
                        "(%s)", sender, e)
            return None

    def send_init_msg(self):
        if self._async:
            # async boot: every rank gets wave-0 work individually (same
            # cohort assignment as the sync broadcast — rank r trains
            # client_sampling(0)[r-1]); from here on dispatch is
            # event-driven, one rank at a time as uploads land
            self.aggregator.begin_round(self.round_idx)
            for rank in range(1, self.size):
                self._dispatch_one(rank, MyMessage.MSG_TYPE_S2C_INIT_CONFIG)
            return
        self._broadcast_model(MyMessage.MSG_TYPE_S2C_INIT_CONFIG,
                              self.aggregator.get_global_model_params())

    # ------------------------------------------------- async buffered mode
    # The event-driven loop of docs/ROBUSTNESS.md §Asynchronous buffered
    # rounds. All state below is touched under _round_lock only.
    def _dispatch_one(self, rank: int,
                      msg_type: str | None = None) -> None:
        """Hand ``rank`` its next unit of work: the current global model
        (packed once per version) + the client its dispatch-wave counter
        samples. Heartbeat-suspect ranks are skipped (admission control) —
        the flush-time reprobe re-dispatches them once they may have
        resumed. Scheduled-offline ranks (churn trace) are skipped
        SILENTLY before the suspect check: the trace expects them away,
        so they get no suspect bookkeeping and no reprobe churn — the
        flush-time reprobe hands them fresh work the moment the trace
        brings them back."""
        if rank in self._scheduled_offline():
            self._offline_skipped.add(rank)
            self._record_shed("offline")
            log.debug("async: rank %d scheduled-offline — dispatch skipped "
                      "until the trace's next arrival", rank)
            return
        suspects = _obs.suspect_ranks(
            range(1, self.size), self.heartbeat_max_age_s, self.round_idx,
            self._DEAD_RANK_REPROBE_ROUNDS)
        if rank in suspects:
            self._record_shed("suspect")
            log.warning("async: not dispatching to heartbeat-suspect rank "
                        "%d (reprobed every %d updates)", rank,
                        self._DEAD_RANK_REPROBE_ROUNDS)
            return
        import time as _time

        wave = self._dispatch_wave.get(rank, 0)
        self._dispatch_wave[rank] = wave + 1
        self._last_dispatch_version[rank] = self.round_idx
        self._last_dispatch_t[rank] = _time.monotonic()
        if self._bcast_version != self.round_idx or self._bcast_pack is None:
            self._bcast_pack = self.aggregator.get_global_model_params()
            self._bcast_version = self.round_idx
            # versioned base stash: encoded uplinks from THIS dispatch wave
            # densify against the broadcast as the client decodes it
            self._stash_version(self.round_idx,
                                codec_roundtrip(self._bcast_pack))
        cid = int(self.aggregator.client_sampling(wave)[rank - 1])
        if self.wal is not None:
            # journaled (fsync'd) so a restarted server resumes every
            # rank's wave counter PAST this dispatch — the sampling chain
            # stays monotonic across restarts and recovery knows work was
            # in flight
            self.wal.append("dispatch", sync=True, round=self.round_idx,
                            rank=rank, wave=wave, client=cid)
        msg = Message(msg_type or MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                      self.rank, rank)
        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, self._bcast_pack)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, cid)
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
        if self._restart_epoch:
            msg.add_params(MyMessage.MSG_ARG_KEY_RESTART_EPOCH,
                           self._restart_epoch)
        # the wave rides the dispatch and comes back on the upload: it is
        # the work-unit key (sampling + the client's rng/batch fold), and
        # reconstructing it server-side from the counter would misattribute
        # a delayed upload once a reprobe puts two dispatches in flight
        msg.add_params(MyMessage.MSG_ARG_KEY_DISPATCH_WAVE, wave)
        if self._fleet is not None:
            # same enablement marker the sync broadcast carries — without
            # it an async fleet would never fold a digest; avail constant
            # for the same reason as the sync path (a dispatch only
            # reaches scheduled-online ranks)
            marker = self._fleet.marker()
            if self.churn_trace is not None:
                marker = {**marker, "avail": 1.0}
            msg.add_params(MyMessage.MSG_ARG_KEY_TELEMETRY, marker)
        self._awaiting[rank] = wave
        self.send_message(msg)
        if rank in self._undeliverable:
            # elastic send failure: nothing is outstanding for this rank —
            # the flush-time reprobe owns bringing it back
            self._awaiting.pop(rank, None)

    def _handle_async_upload(self, msg_params) -> None:
        """Admission -> staging -> maybe flush -> re-dispatch. Caller holds
        _round_lock."""
        import time as _time

        import numpy as np

        from fedml_tpu.core.async_buffer import BufferedUpdate

        sender = int(msg_params[Message.MSG_ARG_KEY_SENDER])
        if self._fleet is not None:
            # fleet digest ingest happens before every gate: a shed or
            # stale upload still proves what its rank was doing (the
            # fleet view is liveness telemetry, not fold accounting)
            self._fleet.ingest(
                msg_params.get(MyMessage.MSG_ARG_KEY_TELEMETRY))
        if self._draining or self.round_idx >= self.round_num:
            # post-FINISH drain: absorb (and discard) the uploads that
            # were in flight when the job completed, then stop the loop —
            # clients never see a torn-down transport mid-upload
            self._awaiting.pop(sender, None)
            if self._draining and not self._awaiting:
                log.info("async: drain complete — stopping")
                self.finish()
            return
        expected_wave = self._awaiting.get(sender)
        # the echoed dispatch wave is authoritative (see _dispatch_one);
        # the fallback covers interop peers that drop unknown keys
        wave = msg_params.get(MyMessage.MSG_ARG_KEY_DISPATCH_WAVE)
        wave = expected_wave if wave is None else int(wave)
        if expected_wave is None or wave != expected_wave:
            # chaos-duplicated or superseded upload: either the rank has no
            # outstanding dispatch, or this is the abandoned twin of a
            # reprobe (the reprobe DECLARED that wave lost and reissued) —
            # exactly-once folding, like the sync round-tag gate
            _obs.record_stale_upload("stale")
            log.warning("async: drop upload from rank %d for wave %s "
                        "(awaiting %s)", sender, wave, expected_wave)
            return
        self._awaiting.pop(sender, None)
        trained_version = int(msg_params.get(MyMessage.MSG_ARG_KEY_ROUND,
                                             self.round_idx))
        staleness = self.round_idx - trained_version
        if not self._staleness.admits(staleness):
            # admission control: reject-and-requeue with the fresh global
            self._record_shed("stale")
            log.warning("async: rejecting upload from rank %d at staleness "
                        "%d > bound %d — requeued", sender, staleness,
                        self._staleness.bound)
            self._dispatch_one(sender)
            return
        # encoded uplinks (top-k / delta / quantized) compose with the
        # async waves because they densify against the stashed broadcast
        # of the version the dispatch carried (the PR-8 dense-only refusal
        # is lifted): an admissible-staleness upload whose base was
        # EVICTED from the bounded stash is shed as stale and requeued —
        # only a version never broadcast stays a loud protocol error
        # (_decode_upload raises)
        encoded = (MyMessage.MSG_ARG_KEY_SPARSE_IDX in msg_params
                   or MyMessage.MSG_ARG_KEY_UPDATE_CODEC in msg_params)
        if encoded and trained_version not in self._version_pack \
                and 0 <= trained_version <= self.round_idx:
            self._record_shed("stale")
            log.warning("async: rank %d's upload encoded against evicted "
                        "base version %d (stash floor %s) — requeued",
                        sender, trained_version,
                        min(self._version_pack, default=None))
            self._dispatch_one(sender)
            return
        staged_payload = None
        if self._fused:
            # fused arrival: densify on device against the version stash
            # (kind-specific jit, cached) — host work is structural
            # validation plus one scalar finiteness readback. The dense
            # device leaves ride the buffer; the drain folds them at the
            # door with the discounted weight (aggregator load_buffered).
            t0 = _time.monotonic()
            decoded = self._decode_upload_fused(msg_params, sender,
                                                trained_version)
            self._gp_fused_ingest_s += _time.monotonic() - t0
            if decoded is None:
                self._record_shed("undecodable")
                self._dispatch_one(sender)
                return
            staged_payload, finite = decoded
        else:
            wire_leaves = self._decode_upload(msg_params, sender,
                                              trained_version)
            if wire_leaves is None:
                # undecodable payload: quarantined + counted by
                # _decode_upload; the rank gets fresh work like any other
                # consumed upload
                self._record_shed("undecodable")
                self._dispatch_one(sender)
                return
        # the work unit's client id: echoed from the dispatch frame (like
        # the wave) so the hot path never rebuilds the O(client_num_in_
        # total) seeded sampling permutation under _round_lock; the
        # fallback recomputes it for interop peers that drop unknown keys
        client = msg_params.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX)
        client = (int(self.aggregator.client_sampling(wave)[sender - 1])
                  if client is None else int(client))
        if not self._fused:
            finite = all(np.isfinite(v).all() for v in wire_leaves
                         if isinstance(v, np.ndarray)
                         and np.issubdtype(v.dtype, np.floating))
        if not finite:
            # PR-4 quarantine at the door: a non-finite arrival never
            # enters the buffer (norm outliers still gate at flush, where
            # the cohort median exists)
            self.aggregator.quarantine.record(
                self.round_idx, sender, "nonfinite", client=client)
            _obs.record_update_rejected("nonfinite")
            self._record_shed("nonfinite")
            self._dispatch_one(sender)
            return
        now = _time.monotonic()
        if len(self._buffer) == 0:
            self._buffer_first_t = now
            self._arm_deadline()
        entry = BufferedUpdate(
            rank=sender, client=client,
            version=trained_version, wave=wave,
            payload=(staged_payload if self._fused
                     else self.aggregator._stage_upload(wire_leaves)),
            nsamp=float(msg_params[MyMessage.MSG_ARG_KEY_NUM_SAMPLES]),
            seq=wave * self.size + sender, t_arrival=now)
        for victim in self._buffer.add(entry):
            # backpressure: shed the stalest pending update, never block.
            # Counting is ALL a victim needs: an old victim's rank already
            # has outstanding work (it was re-dispatched when its entry was
            # staged — or parked, in bound-0 mode), and a shed-on-arrival
            # sender gets its one park-or-redispatch below like any other
            # consumed upload
            self._record_shed("overflow")
            log.warning("async: buffer overflow shed rank %d's update "
                        "(trained at version %d)", victim.rank,
                        victim.version)
        if self._staleness.synchronous:
            # bound 0 = the barrier expressed async: work dispatched now
            # would be born stale post-flush — park until the flush lands
            self._parked.append(sender)
        else:
            self._dispatch_one(sender)
        if self._buffer.ready:
            self._flush_buffer()

    def _flush_buffer(self) -> None:
        """One buffered aggregate = one global update: staleness-discounted
        weights through the aggregator's gated composition (the SUBCLASS
        ``aggregate()``, so FedOpt server momentum / robust clip+noise
        apply on top), then eval/checkpoint/telemetry at the sync round
        cadence, then re-dispatch of every parked rank with the fresh
        global. Caller holds _round_lock."""
        import time as _time

        import numpy as np

        from fedml_tpu.obs import perf_instrument as _perf

        entries = self._buffer.drain()
        self._buffer_epoch += 1
        if not entries or self.round_idx >= self.round_num:
            return
        version = self.round_idx
        self.aggregator.begin_round(version)
        stale = np.asarray([version - e.version for e in entries],
                           np.float32)
        discounts = [float(d) for d in self._discount_np(stale)]
        weights = [e.nsamp * d for e, d in zip(entries, discounts)]
        self.aggregator.load_buffered(entries, weights,
                                      discounts=discounts)
        for s in stale:
            _perf.record_update_staleness(float(s))
        now = _time.monotonic()
        fill_s = now - (self._buffer_first_t
                        if self._buffer_first_t is not None else now)
        _perf.record_buffer_fill(fill_s)
        self._buffer_first_t = None
        tel = self.telemetry
        try:
            if tel is not None:
                old_leaves = [np.asarray(v) for v in
                              self.aggregator.get_global_model_params()]
                with self._tracer.span("aggregate"):
                    global_params = self.aggregator.aggregate()
                with self._tracer.span("eval"):
                    self.aggregator.test_on_server_for_all_clients(version)
                upd_sq = sum(float(np.sum((np.asarray(n) - o) ** 2))
                             for n, o in zip(global_params, old_leaves))
                hist = self.aggregator.history
                q = self.aggregator.quarantine.for_round(version)
                spans = dict(self._tracer.rounds[-1])
                # async round economics: per-flush wall = time since the
                # previous flush (event-driven — there is no broadcast
                # barrier); the buffer-fill window IS the wire wait
                prev_flush = getattr(self, "_gp_prev_flush_t", None)
                self._gp_prev_flush_t = _time.monotonic()
                tel.emit_round(
                    version, clients=[e.client for e in entries],
                    spans=spans,
                    metrics={"update_norm": float(np.sqrt(upd_sq)),
                             "num_samples": float(sum(e.nsamp
                                                      for e in entries))},
                    **({} if prev_flush is None else self._goodput_extra(
                        spans, wire_wait_s=fill_s,
                        wall_s=self._gp_prev_flush_t - prev_flush)),
                    evals=(hist[-1] if hist
                           and hist[-1].get("round") == version else None),
                    **{"async": {
                        "k": len(entries),
                        "staleness": [int(s) for s in stale],
                        "buffer_fill_s": round(fill_s, 6),
                        "shed": self._shed_snapshot()}},
                    **({"quarantine": q} if q else {}),
                    **self._round_record_extra())
                self._tracer.next_round()
            else:
                self.aggregator.aggregate()
                self.aggregator.test_on_server_for_all_clients(version)
        finally:
            self.aggregator._async_meta = None
            # per-flush goodput window: the NEXT flush's fused arrival
            # jits start accumulating from zero
            self._gp_fused_ingest_s = 0.0
        self._maybe_save()
        self.round_idx += 1
        self._bcast_pack = None  # repack lazily at the next dispatch
        # crash points in async terms: a flush IS the commit boundary —
        # 'between commits' fires here (the new round exists, nothing of
        # it dispatched), and the per-round upload counter resets so
        # 'after_uploads' counts THIS round's admissions
        self._uploads_this_round = 0
        self._maybe_crash("broadcast")
        if self.round_idx >= self.round_num:
            self._finish_async()
            return
        parked, self._parked = self._parked, []
        for rank in parked:
            self._dispatch_one(rank)
        self._async_reprobe()
        # after_uploads=0 in async terms: the new round's dispatches are
        # out, nothing admitted yet
        self._maybe_crash("post_broadcast")

    def _finish_async(self) -> None:
        """Broadcast FINISH, then DRAIN instead of tearing down: the
        receive loop stays up until every outstanding dispatch's upload
        has landed (each is discarded by the drain gate above), bounded by
        a grace timer for ranks that died mid-dispatch. Caller holds
        _round_lock."""
        # final best-effort delivery to EVERY rank, including ones the
        # elastic sender had marked undeliverable — a skipped FINISH
        # leaves that client blocked in its receive loop until the
        # simulated-launch join timeout abandons the thread
        self._undeliverable.clear()
        self._update_alive_gauge()
        for rank in range(1, self.size):
            msg = Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, rank)
            # round-tagged like the sync FINISH (see _broadcast_finish)
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            self.send_message(msg)
        if not self._awaiting:
            self.finish()
            return
        self._draining = True
        log.info("async: job complete — draining %d in-flight upload(s) "
                 "(grace %.1fs)", len(self._awaiting), self._drain_grace_s)
        t = threading.Timer(self._drain_grace_s, self.finish)
        t.daemon = True
        t.start()

    def _record_shed(self, reason: str) -> None:
        """One shed verdict: the process-wide metric family AND this job's
        own tally (round records must scope to this job)."""
        from fedml_tpu.obs import perf_instrument as _perf

        _perf.record_async_shed(reason)
        self._shed_counts[reason] = self._shed_counts.get(reason, 0) + 1

    def _journal_buffer(self, event: str, e) -> None:
        """AsyncBuffer journal hook: buffer membership rides the WAL so
        recovery ledgers exactly the admitted-and-unflushed entries that
        died with the process. Admits are fsync'd (the lost-slot ledger
        is a correctness artifact); overflow sheds are forensic."""
        if self.wal is None:
            return
        extra = {} if event == "admit" else {"reason": "overflow"}
        self.wal.append("admit" if event == "admit" else "shed",
                        sync=event == "admit", round=int(e.version),
                        rank=int(e.rank), client=int(e.client),
                        wave=int(e.wave), nsamp=float(e.nsamp), **extra)
        if event == "admit":
            self._uploads_this_round += 1
            self._maybe_crash("upload")

    # ------------------------------------------------ crash points (chaos)
    def _maybe_crash(self, point: str) -> None:
        """Deterministic simulated-crash hook (loopback supervision,
        docs/ROBUSTNESS.md §Server crash recovery): ``_crash_plan`` holds
        ``(round, after_uploads)`` points derived from chaos ``crash``
        rules naming rank 0 — ``after_uploads=None`` dies BETWEEN COMMITS
        (entering the round, before any frame of it leaves), an integer
        dies MID-ROUND once that many uploads of the round were accepted
        (``0`` = broadcast out, nothing accepted yet)
        (their WAL records already fsync'd, their payloads about to die
        with the process). Only the head of the plan is consulted; the
        supervision driver pops it per boot, so a recovered server does
        not re-crash on the same point."""
        if not self._crash_plan:
            return
        rnd, after = self._crash_plan[0]
        why = None
        if point == "broadcast" and after is None \
                and self.round_idx == int(rnd):
            why = "between commits"
        elif point == "post_broadcast" and after is not None \
                and int(after) == 0 and self.round_idx == int(rnd):
            # m=0 must fire with the broadcast out and ZERO uploads
            # journaled — the upload hook can't express it (it only runs
            # after an accept)
            why = "mid-round after 0 uploads"
        elif point == "reveal" and after is not None and int(after) == -1 \
                and self.round_idx == int(rnd):
            # after_uploads = -1: die at the secagg reveal fan-out — the
            # recovery state machine's most dangerous window (the fold
            # must shed, never half-recover)
            why = "mid-reveal"
        elif point == "upload" and after is not None and int(after) >= 1 \
                and self.round_idx == int(rnd) \
                and self._uploads_this_round >= int(after):
            why = f"mid-round after {self._uploads_this_round} uploads"
        if why is None:
            return
        exc = SimulatedServerCrash(self.round_idx, why)
        # crash points can fire on the WATCHDOG thread (elastic timeouts,
        # the secagg reveal path) where a bare raise would kill only that
        # thread: flag the crash and stop the dispatch loop WITHOUT any
        # farewell frame (the loopback deregistration IS process death),
        # then raise — run() re-raises the flag to the supervision driver
        # whichever thread died first
        self._sim_crash = exc
        # black box (obs/flightrec.py): the crash is the one moment the
        # in-memory ring MUST become durable — record the crash marker,
        # then dump before the transport goes down
        from fedml_tpu.obs import flightrec as _flightrec

        _flightrec.flight_record("sim_crash", rank=self.rank,
                                 round=self.round_idx, point=point, why=why)
        _flightrec.dump_active("sim_crash")
        try:
            inner = getattr(self.com_manager, "inner", self.com_manager)
            inner.stop_receive_message()
        except Exception:  # noqa: BLE001 — dying is the whole point
            log.debug("simulated crash: transport teardown failed",
                      exc_info=True)
        raise exc

    # --------------------------------------------------- session resumption
    def _send_resume_probes(self) -> None:
        """Post-restart probe fan-out (docs/ROBUSTNESS.md §Server crash
        recovery): recovery found an OPEN round, so clients may hold
        in-flight pre-crash work. Each rank gets one s2c_resume frame
        carrying the new restart epoch; its c2s_resume answer (last-seen
        round + async wave) tells the server who is alive and what they
        hold before the open round is re-dispatched. A backstop timer
        proceeds without the silent ranks (they re-enter through the
        elastic undeliverable/reprobe machinery)."""
        self._resume_pending = set(range(1, self.size))
        log.info("resume probe: round %d re-runs under restart epoch %d — "
                 "probing %d rank(s)", self._resume_round,
                 self._restart_epoch, len(self._resume_pending))
        for rank in range(1, self.size):
            msg = Message(MyMessage.MSG_TYPE_S2C_RESUME_PROBE, self.rank,
                          rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self._resume_round)
            msg.add_params(MyMessage.MSG_ARG_KEY_RESTART_EPOCH,
                           self._restart_epoch)
            self.send_message(msg)
        grace = self.round_timeout_s or 5.0
        t = threading.Timer(grace, self._resume_backstop)
        t.daemon = True
        t.start()

    def _resume_backstop(self) -> None:
        with self._round_lock:
            if self._resume_round is None or self._finished.is_set():
                return
            log.warning("resume probe: %d rank(s) silent past the grace — "
                        "re-dispatching without them (elastic machinery "
                        "owns their rejoin)", len(self._resume_pending))
            self._complete_resume()

    def handle_message_resume_ack(self, msg_params):
        with self._round_lock:
            if self._resume_round is None:
                return  # late/duplicate ack after the backstop proceeded
            sender = int(msg_params[Message.MSG_ARG_KEY_SENDER])
            last = int(msg_params.get(MyMessage.MSG_ARG_KEY_LAST_SEEN_ROUND,
                                      -1))
            wave = int(msg_params.get(MyMessage.MSG_ARG_KEY_LAST_SEEN_WAVE,
                                      -1))
            self._resume_pending.discard(sender)
            self._resume_acks[sender] = (last, wave)
            log.info("resume probe: rank %d last saw round %d (wave %d); "
                     "%d pending", sender, last, wave,
                     len(self._resume_pending))
            if not self._resume_pending:
                self._complete_resume()

    def _complete_resume(self) -> None:
        """Re-dispatch the open round under the new epoch. Caller holds
        _round_lock. Ranks whose ack shows pre-crash work for this round
        get it superseded (the epoch gate sheds the stale upload when it
        lands); ranks that never answered ride the elastic path."""
        rnd, self._resume_round = self._resume_round, None
        if rnd is None:
            return
        stale = sorted(r for r, (last, _w) in self._resume_acks.items()
                       if last >= rnd)
        if stale:
            log.info("resume: ranks %s hold pre-crash round-%d work — "
                     "superseded by the re-dispatch (epoch gate sheds it "
                     "on arrival)", stale, rnd)
        if self._async:
            # async re-dispatch: every rank gets fresh work at the
            # recovered round; wave counters already resume past the
            # journaled maxima
            self.aggregator.begin_round(self.round_idx)
            for rank in range(1, self.size):
                self._dispatch_one(rank)
            return
        self._broadcast_model(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                              self.aggregator.get_global_model_params())

    def _shed_snapshot(self) -> dict:
        return dict(self._shed_counts)

    def _async_reprobe(self, force: bool = False) -> None:
        """Bring silent ranks back: a rank whose dispatch went nowhere
        (send failed elastically, heartbeat-skipped) OR whose upload was
        lost on the wire (still marked awaiting, but silent for
        ``_DEAD_RANK_REPROBE_ROUNDS`` global updates) is re-dispatched —
        the reissue DECLARES the old wave lost, so if its upload turns up
        late after all, the wave-matched awaiting gate drops it (no second
        dispatch stream). ``force`` skips the recently-dispatched check:
        the idle watchdog calls it after ``round_timeout_s`` of total
        silence, which is staleness evidence in itself — round_idx only
        advances on flushes, so a fully stalled fleet would otherwise
        never look old enough to reprobe. Both paths still respect the
        WALL-CLOCK grace: version age alone would starve any honest rank
        slower than _DEAD_RANK_REPROBE_ROUNDS flush intervals (small-K
        fleets flush fast), declaring its in-flight wave lost over and
        over while its uploads die at the gate. Caller holds
        _round_lock."""
        import time as _time

        now = _time.monotonic()
        offline = self._scheduled_offline()
        for rank in range(1, self.size):
            if rank in self._parked:
                continue
            if rank in offline:
                # scheduled-offline: the trace says it's away, not dead —
                # zero reprobe churn; the arrival fast-path below picks it
                # up the moment the trace brings it back
                continue
            if rank in self._offline_skipped:
                # back from scheduled-offline: re-dispatch immediately,
                # bypassing the age/grace checks — its silence was the
                # trace's doing, not evidence of death
                self._offline_skipped.discard(rank)
                self._idle_logged_round = None  # an arrival ends the stretch
                log.info("async: rank %d returned from scheduled-offline — "
                         "re-dispatching", rank)
                self._undeliverable.pop(rank, None)
                self._update_alive_gauge()
                self._awaiting.pop(rank, None)
                self._dispatch_one(rank)
                continue
            last = self._last_dispatch_version.get(rank)
            if not force and last is not None and \
                    (self.round_idx - last) < \
                    self._DEAD_RANK_REPROBE_ROUNDS:
                continue  # recently dispatched: give it time
            t_disp = self._last_dispatch_t.get(rank)
            if t_disp is not None and \
                    (now - t_disp) < self._reprobe_grace_s:
                continue  # dispatched recently in WALL-CLOCK: still alive
            log.info("async: reprobing silent rank %d", rank)
            # the reprobe IS the re-invitation: drop the elastic
            # undeliverable mark so the send is actually attempted
            self._undeliverable.pop(rank, None)
            self._update_alive_gauge()
            self._awaiting.pop(rank, None)
            self._dispatch_one(rank)

    def _arm_deadline(self) -> None:
        """Deadline flush: a buffer that has waited ``buffer_deadline_s``
        since its first arrival aggregates PARTIAL instead of waiting out a
        straggler cohort — the async analogue of the elastic round
        timeout."""
        if self.buffer_deadline_s is None:
            return
        epoch = self._buffer_epoch
        t = threading.Timer(self.buffer_deadline_s, self._deadline_fire,
                            args=(epoch,))
        t.daemon = True
        t.start()

    def _deadline_fire(self, epoch: int) -> None:
        with self._round_lock:
            if (self._finished.is_set() or epoch != self._buffer_epoch
                    or len(self._buffer) == 0):
                return
            log.warning("async: buffer deadline fired with %d/%d staged — "
                        "flushing partial", len(self._buffer),
                        self._buffer.flush_threshold)
            self._flush_buffer()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client,
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_RESUME_ACK,
            self.handle_message_resume_ack,
        )

    def _epoch_admits(self, msg_params) -> bool:
        """Restart-epoch gate (docs/ROBUSTNESS.md §Server crash recovery):
        an upload whose echoed epoch predates this boot is PRE-CRASH
        in-flight work — its slot was already ledgered ``server_restart``
        at recovery (if the dead server had accepted it) and the open
        round was re-dispatched, so folding it now would double-count.
        Counted, never ledgered (arrival timing is wall-clock; the ledger
        stays deterministic). Epoch-0 uploads against an epoch-0 server
        pass untouched — the pre-crash wire is unchanged."""
        up_epoch = int(msg_params.get(MyMessage.MSG_ARG_KEY_RESTART_EPOCH,
                                      0))
        if up_epoch == self._restart_epoch:
            return True
        _obs.record_stale_upload("server_restart")
        log.warning("dropping upload from rank %s at restart epoch %d "
                    "(server now at %d) — superseded by the post-crash "
                    "re-dispatch",
                    msg_params.get(Message.MSG_ARG_KEY_SENDER), up_epoch,
                    self._restart_epoch)
        return False

    def handle_message_receive_model_from_client(self, msg_params):
        with self._round_lock:
            if not self._epoch_admits(msg_params):
                if self._async:
                    # the pre-crash dispatch is dead; hand the rank fresh
                    # work under the new epoch so it rejoins the fleet
                    sender = int(msg_params[Message.MSG_ARG_KEY_SENDER])
                    self._record_shed("server_restart")
                    self._awaiting.pop(sender, None)
                    if not self._draining:
                        self._dispatch_one(sender)
                return
            if self._async:
                self._handle_async_upload(msg_params)
                return
            sender = msg_params[Message.MSG_ARG_KEY_SENDER]
            msg_round = msg_params.get(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            if int(msg_round) != self.round_idx:
                _obs.record_stale_upload("stale")
                log.warning("drop stale upload from rank %d (round %s, now %d)",
                            sender, msg_round, self.round_idx)
                return
            if self.telemetry is not None:
                # last counted arrival for this round's wire_wait bucket
                import time as _time

                self._gp_last_arrival_t = _time.monotonic()
            if self._dtracer is not None:
                # arrival time + clock sample + the piggybacked client
                # span buffer (None from a stock/untraced peer is fine —
                # the arrival alone keeps slack computable)
                self._dtracer.on_upload(int(sender),
                                        msg_params.get(TRACE_KEY))
            if self._fleet is not None:
                self._fleet.ingest(
                    msg_params.get(MyMessage.MSG_ARG_KEY_TELEMETRY))
            # proof of possession: an upload tagged round v means the
            # sender decoded broadcast v — the delta-downlink warm set
            self._rank_version[int(sender)] = int(msg_round)
            if self._fused:
                # fused ingest: structural validation on host, densify →
                # gate → pairwise fold on device (no per-client f32 tree
                # ever exists here). An undecodable payload still
                # satisfies the barrier, exactly like the stacked path.
                # The ingest seconds are aggregation work happening in
                # the wire-wait window — accumulated here so goodput
                # attributes them to agg_flush (_goodput_extra).
                import time as _time

                t0 = _time.monotonic()
                ok = self._stage_fused(
                    msg_params, int(sender), int(msg_round),
                    msg_params[MyMessage.MSG_ARG_KEY_NUM_SAMPLES])
                self._gp_fused_ingest_s += _time.monotonic() - t0
                if not ok and (int(sender) - 1) in \
                        self.aggregator.flag_client_model_uploaded:
                    self.aggregator.flag_client_model_uploaded[
                        int(sender) - 1] = True
                if self.aggregator.check_whether_all_receive():
                    self._advance_round()
                return
            # densify encoded uplinks (top-k / delta / quantized) against
            # the STASHED broadcast of the upload's version — the already-
            # decoded leaves kept at send time (re-packing the full model
            # per upload would cost N device→host materializations per
            # round under this lock); the round gate above means sync
            # lookups always hit the current round's stash
            wire_leaves = self._decode_upload(msg_params, int(sender),
                                              int(msg_round))
            if wire_leaves is None:
                # undecodable: quarantined + counted, but the ARRIVAL still
                # satisfies the barrier — with no elastic timeout armed, a
                # skipped slot would otherwise hang the round forever. The
                # round degrades to the exact partial aggregate over the
                # decodable uploads (the elastic-partial shape; an
                # all-undecodable round keeps the global model).
                if (sender - 1) in self.aggregator.flag_client_model_uploaded:
                    self.aggregator.flag_client_model_uploaded[sender - 1] = True
                if self.aggregator.check_whether_all_receive():
                    self._advance_round()
                return
            self.aggregator.add_local_trained_result(
                sender - 1,
                wire_leaves,
                msg_params[MyMessage.MSG_ARG_KEY_NUM_SAMPLES],
                round_idx=int(msg_round),
            )
            if self.wal is not None and \
                    self.aggregator.flag_client_model_uploaded.get(
                        int(sender) - 1):
                # journal the ACCEPT (fsync'd): the payload lives only in
                # this process — if we die before the round commits,
                # recovery ledgers this slot ``server_restart``
                self._uploads_this_round += 1
                self.wal.append(
                    "upload", sync=True, round=int(msg_round),
                    rank=int(sender),
                    client=(self._round_ids[int(sender) - 1]
                            if int(sender) - 1 < len(self._round_ids)
                            else None),
                    nsamp=float(
                        msg_params[MyMessage.MSG_ARG_KEY_NUM_SAMPLES]))
                self._maybe_crash("upload")
            if not self.aggregator.check_whether_all_receive():
                return
            self._advance_round()

    def _goodput_extra(self, spans: dict, wire_wait_s=None,
                       wall_s=None) -> dict:
        """The server round's ``goodput`` block (obs/goodput.py): wall from
        the broadcast stamp (sync) or the caller (async flush), wire_wait
        from bcast-done -> last counted arrival unless given, agg_flush
        from the aggregate span + the aggregator's fused flush latency.
        The server dispatches no jitted round variant, so the block is
        duty-cycle-only (relative goodput) — the device-side figures live
        on the engine ranks. {} when the stamps are missing (restart
        mid-round, init round)."""
        import time as _time

        if wall_s is None:
            t0 = getattr(self, "_gp_bcast_start_t", None)
            if t0 is None:
                return {}
            wall_s = _time.monotonic() - t0
        if wire_wait_s is None:
            bce = getattr(self, "_gp_bcast_end_t", None)
            arr = getattr(self, "_gp_last_arrival_t", None)
            wire_wait_s = (max(0.0, arr - bce)
                           if bce is not None and arr is not None else 0.0)
        # fused attribution: the per-arrival ingest jits run while the
        # server sits in the wire-wait window, but they are aggregation
        # work — move their seconds from wire_wait into agg_flush so a
        # fused A/B shifts the bucket that actually changed. The fused
        # FLUSH latency already rides inside the aggregate span, so only
        # the arrival-side seconds move (no double count).
        ingest_s = getattr(self, "_gp_fused_ingest_s", 0.0)
        wire_wait_s = max(0.0, wire_wait_s - ingest_s)
        buckets = _goodput.buckets_from_spans(
            wall_s, spans, wire_wait_s=wire_wait_s, flush_s=ingest_s)
        return {"goodput": _goodput.round_goodput(wall_s, buckets)}

    def _round_record_extra(self) -> dict:
        """Extra blocks a subclass rides on the telemetry round record
        (the hierarchical server adds its ``hier`` fan-in block). The
        ``privacy`` block is universal: any aggregator that exposes
        ``privacy_record()`` (the DP defenses, the masked secure tier —
        docs/ROBUSTNESS.md §Privacy ledger) gets its cumulative ε@δ +
        mechanism parameters on every emitted round."""
        extra: dict = {}
        pr = getattr(self.aggregator, "privacy_record", None)
        if pr is not None:
            block = pr()
            if block:
                extra["privacy"] = block
        if self._restart_epoch:
            # crash-recovery provenance (docs/ROBUSTNESS.md §Server crash
            # recovery): rounds emitted after a restart carry the epoch —
            # report.py renders a ``restarts`` column, hidden on runs (and
            # logs) that never crashed
            extra["server"] = {"restarts": self._restart_epoch,
                               "restart_epoch": self._restart_epoch}
        if self.churn_trace is not None:
            # churn provenance: how many ranks the trace held out this
            # round and how many idle (no-fold) rounds the run has taken —
            # hidden on trace-less runs, so their records stay byte-stable
            extra["churn"] = {"scheduled_offline": len(self._offline_now),
                              "idle_rounds": self._idle_rounds}
        return extra

    def _advance_round(self):
        """Aggregate what's collected, eval, and start the next round (or
        finish). Caller holds _round_lock."""
        self._idle_logged_round = None  # real progress ends an idle stretch
        tel = self.telemetry
        if tel is not None:
            import numpy as np

            n_samples = float(sum(self.aggregator.sample_num_dict.values()))
            old_leaves = [np.asarray(v)
                          for v in self.aggregator.get_global_model_params()]
            with self._tracer.span("aggregate"):
                global_params = self.aggregator.aggregate()
            with self._tracer.span("eval"):
                self.aggregator.test_on_server_for_all_clients(self.round_idx)
            upd_sq = sum(
                float(np.sum((np.asarray(n) - o) ** 2))
                for n, o in zip(global_params, old_leaves))
            hist = self.aggregator.history
            # stitch: close the round's trace and fold the critical-path
            # attribution (straggler rank, phase breakdown, slack, chaos
            # cross-reference) into the round record
            cp = (self._dtracer.finish_round()
                  if self._dtracer is not None else None)
            q = self.aggregator.quarantine.for_round(self.round_idx) \
                if hasattr(self.aggregator, "quarantine") else []
            spans = dict(self._tracer.rounds[-1])
            tel.emit_round(
                self.round_idx, clients=self._round_ids,
                spans=spans,
                metrics={"update_norm": float(np.sqrt(upd_sq)),
                         "num_samples": n_samples},
                **self._goodput_extra(spans),
                evals=(hist[-1] if hist
                       and hist[-1].get("round") == self.round_idx else None),
                **({"critical_path": cp} if cp else {}),
                **({"quarantine": q} if q else {}),
                # flush latency + staging mode (docs/PERFORMANCE.md §Fused
                # aggregation); report.py renders flush_s, hidden on logs
                # that predate the block
                **({"agg": self.aggregator.agg_record()}
                   if hasattr(self.aggregator, "agg_record") else {}),
                **self._round_record_extra())
            self._tracer.next_round()
        else:
            global_params = self.aggregator.aggregate()
            self.aggregator.test_on_server_for_all_clients(self.round_idx)
        self._maybe_save()

        self.round_idx += 1
        if self.round_idx == self.round_num:
            self._broadcast_finish()
            return
        self._broadcast_model(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                              global_params)

    def finish(self):
        try:
            super().finish()
        finally:
            if self.wal is not None:
                # flush + close the journal; a zombie timer appending
                # after this is a no-op (closed-handle check), which is
                # exactly the post-mortem silence a dead process has
                self.wal.close()

    def on_timeout(self, idle_s: float):
        """Watchdog (own thread): no traffic for round_timeout_s."""
        with self._round_lock:
            if self._async:
                # async analogue of elastic partial aggregation: a stalled
                # fleet flushes whatever is staged; a fully empty buffer
                # means every rank is dark — reprobe them instead of
                # waiting forever. A DRAINING server is quiet by design
                # (FINISH is out; reprobing would hand new work to clients
                # that already exited) — let the grace timer finish it.
                if self._finished.is_set() or self._draining:
                    return
                if len(self._buffer):
                    log.warning("async: fleet idle %.1fs — flushing %d "
                                "staged update(s)", idle_s,
                                len(self._buffer))
                    self._flush_buffer()
                else:
                    offline = self._scheduled_offline()
                    if offline and all(r in offline
                                       for r in range(1, self.size)):
                        # the WHOLE fleet is scheduled-offline: an idle
                        # trough, not a stall — log once per stretch,
                        # count it, and advance round_idx without folding
                        # (availability windows are round-indexed; a
                        # static round would keep the trough's offline
                        # set frozen and deadlock). The reprobe after the
                        # advance hands fresh work to whoever the trace
                        # brought back.
                        if self._idle_logged_round is None:
                            log.info(
                                "async: fleet idle — every rank is "
                                "scheduled-offline by the churn trace; "
                                "advancing idle rounds until the next "
                                "arrival")
                            self._idle_logged_round = self.round_idx
                        _obs.record_round_idle()
                        self._idle_rounds += 1
                        self.round_idx += 1
                        if self.round_idx >= self.round_num:
                            self._finish_async()
                            return
                        self._async_reprobe(force=True)
                        return
                    log.error("async: fleet idle %.1fs with an empty "
                              "buffer — reprobing silent ranks", idle_s)
                    self._async_reprobe(force=True)
                return
            received = [i + 1 for i, v in
                        self.aggregator.flag_client_model_uploaded.items() if v]
            missing = [i + 1 for i, v in
                       self.aggregator.flag_client_model_uploaded.items() if not v]
            if self.round_timeout_s is None or self._finished.is_set():
                log.error("round %d stalled %.1fs: waiting on client ranks %s",
                          self.round_idx, idle_s, missing)
                return
            if not received:
                offline = self._scheduled_offline()
                online_missing = [r for r in missing if r not in offline]
                if offline and not online_missing:
                    # every missing rank is scheduled-offline: an idle
                    # round, not a stall — log once per idle stretch,
                    # count fed_rounds_idle_total, and advance WITHOUT
                    # folding (availability windows are round-indexed, so
                    # a stalled round's offline set is static — standing
                    # still would deadlock an all-offline trough). The
                    # re-broadcast at the new round reaches whoever the
                    # trace brought back; if the trough persists, the
                    # next watchdog fire idles again, silently.
                    if self._idle_logged_round is None:
                        log.info(
                            "round %d: fleet idle — every missing rank "
                            "is scheduled-offline by the churn trace; "
                            "advancing idle rounds until the next "
                            "arrival", self.round_idx)
                        self._idle_logged_round = self.round_idx
                    _obs.record_round_idle()
                    self._idle_rounds += 1
                    self.round_idx += 1
                    if self.round_idx == self.round_num:
                        self._broadcast_finish()
                        return
                    self._broadcast_model(
                        MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                        self.aggregator.get_global_model_params())
                    return
                # elastic round with NOTHING to aggregate: advancing would
                # fold an empty cohort, but returning silently wedged the
                # job forever (every upload lost to corrupt-drop/crash in
                # one round = no progress, and the watchdog used to just
                # log). Re-broadcast the current global instead — each
                # resend draws fresh wire-fault outcomes and a recovered
                # rank gets a fresh shot at the round; the health layer's
                # stall rule (obs/health.py) reports the condition while
                # this nudge works on clearing it.
                log.error("round %d stalled %.1fs with NO uploads — "
                          "re-broadcasting round state to ranks %s",
                          self.round_idx, idle_s, missing)
                # forced reprobe first (the async branch's analogue): a
                # rank marked undeliverable THIS round is skipped by
                # send_message until round_idx moves — which it cannot
                # while stalled — so without clearing the marks an
                # all-downlink-failure stall would re-broadcast to nobody.
                # A re-failed send re-marks the rank immediately.
                self._undeliverable.clear()
                self._update_alive_gauge()
                self._broadcast_model(
                    MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                    self.aggregator.get_global_model_params())
                return
            log.warning(
                "round %d: elastic partial aggregation over ranks %s "
                "(stragglers %s dropped after %.1fs)",
                self.round_idx, received, missing, idle_s,
            )
            for i in list(self.aggregator.flag_client_model_uploaded):
                self.aggregator.flag_client_model_uploaded[i] = False
            self._advance_round()
