"""FedAvg server manager — round coordination over the comm layer.

Mirror of fedml_api/distributed/fedavg/FedAvgServerManager.py: send_init_msg
(:31-39), handle_message_receive_model_from_client (:45-82, aggregate when
all received, eval, resample, sync), send_message_sync_model_to_client
(:90-95). Adds a straggler watchdog (on_timeout) the reference lacks.
"""

from __future__ import annotations

import logging

from fedml_tpu.comm.managers import ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.message_define import MyMessage

log = logging.getLogger("fedml_tpu.distributed.fedavg")


class FedAvgServerManager(ServerManager):
    def __init__(self, aggregator: FedAvgAggregator, rank=0, size=0, backend="LOOPBACK", **kw):
        self.aggregator = aggregator
        self.round_num = aggregator.cfg.comm_round
        self.round_idx = 0
        if size - 1 != aggregator.cfg.client_num_per_round:
            # one worker process per sampled client (FedAvgAPI.py:20-28
            # launches client_num_per_round+1 ranks); a deficit would
            # silently aggregate fewer clients than configured.
            raise ValueError(
                f"worker count {size - 1} != client_num_per_round="
                f"{aggregator.cfg.client_num_per_round}"
            )
        super().__init__(rank, size, backend, **kw)

    def run(self):
        self.send_init_msg()
        super().run()

    def send_init_msg(self):
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        global_params = self.aggregator.get_global_model_params()
        for rank in range(1, self.size):
            msg = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_params)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(client_indexes[rank - 1]))
            self.send_message(msg)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client,
        )

    def handle_message_receive_model_from_client(self, msg_params):
        sender = msg_params[Message.MSG_ARG_KEY_SENDER]
        self.aggregator.add_local_trained_result(
            sender - 1,
            msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS],
            msg_params[MyMessage.MSG_ARG_KEY_NUM_SAMPLES],
        )
        if not self.aggregator.check_whether_all_receive():
            return
        global_params = self.aggregator.aggregate()
        self.aggregator.test_on_server_for_all_clients(self.round_idx)

        self.round_idx += 1
        if self.round_idx == self.round_num:
            for rank in range(1, self.size):
                self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, rank))
            self.finish()
            return
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        for rank in range(1, self.size):
            msg = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_params)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(client_indexes[rank - 1]))
            self.send_message(msg)

    def on_timeout(self, idle_s: float):
        missing = [i + 1 for i, v in self.aggregator.flag_client_model_uploaded.items() if not v]
        log.error(
            "round %d stalled %.1fs: waiting on client ranks %s",
            self.round_idx, idle_s, missing,
        )
