"""FedAvg server manager — round coordination over the comm layer.

Mirror of fedml_api/distributed/fedavg/FedAvgServerManager.py: send_init_msg
(:31-39), handle_message_receive_model_from_client (:45-82, aggregate when
all received, eval, resample, sync), send_message_sync_model_to_client
(:90-95).

Elastic extension (absent in the reference — SURVEY.md §5 'failure
detection: none'): with ``round_timeout_s`` set, a round that stalls past
the deadline aggregates over the subset of clients that DID report
(sample-weighted, so the average stays exact over the participants) and
moves on; late uploads from superseded rounds are round-tagged and dropped.
A crashed client therefore degrades throughput instead of hanging the job.

Checkpoint/resume (also absent in the reference): with ``ckpt_dir`` set the
server saves (net, opt state, round) after every aggregate and, on
construction, resumes from the latest checkpoint — a server restart
continues the job exactly where it stopped (clients are stateless between
rounds: they receive the global model each sync), so crash-resume ≡ an
uninterrupted run (tested).
"""

from __future__ import annotations

import logging
import os
import threading

from fedml_tpu.comm.managers import ServerManager
from fedml_tpu.comm.message import Message, codec_roundtrip
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.message_define import MyMessage
from fedml_tpu.obs import comm_instrument as _obs
from fedml_tpu.obs.tracing import TRACE_KEY

log = logging.getLogger("fedml_tpu.distributed.fedavg")


class FedAvgServerManager(ServerManager):
    def __init__(self, aggregator: FedAvgAggregator, rank=0, size=0,
                 backend="LOOPBACK", round_timeout_s: float | None = None,
                 ckpt_dir: str | None = None, telemetry=None, **kw):
        self.aggregator = aggregator
        self.round_num = aggregator.cfg.comm_round
        self.round_idx = 0
        self._bcast_leaves = None  # this round's packed broadcast (sparse)
        self.round_timeout_s = round_timeout_s
        self.ckpt_dir = ckpt_dir
        # rank -> round its delivery last failed. Initialized HERE, not
        # lazily at first failure: two sender paths (round loop + watchdog
        # thread) can fail concurrently, and a hasattr-then-create race
        # would lose one rank's failure record.
        self._undeliverable: dict[int, int] = {}
        # obs.Telemetry: per-round event records (sampled ids, aggregate/eval
        # span timings, update norm, comm byte/message deltas). None = the
        # seed behavior, zero extra work.
        self.telemetry = telemetry
        self._round_ids: list[int] = []
        # cross-rank tracer (obs/tracing.py): present only when the
        # Telemetry bundle opted in (trace_dir / trace=True). None = no
        # __trace params on any frame — the wire is byte-identical.
        self._dtracer = telemetry.tracer if telemetry is not None else None
        if telemetry is not None:
            import dataclasses

            from fedml_tpu.obs.tracing import RoundTracer

            self._tracer = RoundTracer(sink=self._dtracer)
            telemetry.run_header(dataclasses.asdict(aggregator.cfg),
                                 engine="distributed", backend=backend,
                                 world_size=size,
                                 tracing=self._dtracer is not None)
        if ckpt_dir is not None:
            self._maybe_resume()
        self._round_lock = threading.Lock()
        if size - 1 != aggregator.cfg.client_num_per_round:
            # one worker process per sampled client (FedAvgAPI.py:20-28
            # launches client_num_per_round+1 ranks); a deficit would
            # silently aggregate fewer clients than configured.
            raise ValueError(
                f"worker count {size - 1} != client_num_per_round="
                f"{aggregator.cfg.client_num_per_round}"
            )
        ts = kw.pop("timeout_s", None)
        if round_timeout_s is not None and round_timeout_s <= 0:
            # 0 would arm the elastic error-swallowing but DISARM the
            # watchdog ('or' treats 0.0 as unset) — a silent permanent hang
            raise ValueError(f"round_timeout_s={round_timeout_s} must be > 0")
        if round_timeout_s is not None:
            # elastic mode: a send to a dead/unreachable client must not
            # absorb more than one round deadline (the gRPC default is a
            # 600 s boot-tolerance window) — and its failure is handled
            # (the client becomes a straggler), not fatal
            kw.setdefault("send_timeout_s", round_timeout_s)
        super().__init__(rank, size, backend, timeout_s=round_timeout_s or ts, **kw)
        _obs.set_ranks_alive(size - 1)  # all peers presumed reachable at boot

    # a rank whose delivery failed is probed again only every k-th round:
    # one dead peer must not cost every round a full send deadline, but a
    # REBOOTED peer must still be able to rejoin
    _DEAD_RANK_REPROBE_ROUNDS = 4

    def _update_alive_gauge(self) -> None:
        """fed_ranks_alive from the undeliverable/reprobe bookkeeping —
        world size may be unknown on a partially-built instance (tests
        drive the elastic send path without the comm stack)."""
        size = getattr(self, "size", None)
        if size is not None:
            _obs.set_ranks_alive(size - 1 - len(self._undeliverable))

    @staticmethod
    def _is_transport_error(e: BaseException) -> bool:
        """Only delivery failures are elastic-tolerable; config/programming
        errors (KeyError on a bad ip table, serialization bugs) stay
        fatal. grpc.RpcError is detected by name so the server module
        needs no grpc import for the loopback/mqtt backends."""
        if isinstance(e, (ConnectionError, TimeoutError, OSError)):
            return True
        return any(c.__name__ == "RpcError" for c in type(e).__mro__)

    def send_message(self, msg) -> None:
        """Elastic mode tolerates an unreachable downlink: the failed rank
        simply has nothing to report this round and the watchdog drops it
        (the reference aborts the whole job instead — raise_MPI_error ->
        MPI.COMM_WORLD.Abort(), fedml_api/utils/context.py:9-18).
        Without a round deadline, delivery failures stay fatal."""
        rank = int(msg.get_receiver_id())
        failed_at = self._undeliverable.get(rank)
        # reprobe only on a POSITIVE multiple of the interval: at
        # round_idx == failed_at the failure was just recorded, and a
        # second send in the same round (e.g. the FINISH broadcast after a
        # failed final sync) must not re-block a full send deadline
        if (failed_at is not None and
                (self.round_idx == failed_at or
                 (self.round_idx - failed_at) % self._DEAD_RANK_REPROBE_ROUNDS)):
            log.debug("elastic: skipping send to dead rank %d "
                      "(failed at round %d; reprobed every %d rounds)",
                      rank, failed_at, self._DEAD_RANK_REPROBE_ROUNDS)
            return
        try:
            super().send_message(msg)
            if failed_at is not None:
                log.info("elastic: rank %d reachable again", rank)
                self._undeliverable.pop(rank, None)
                self._update_alive_gauge()
        except Exception as e:
            if self.round_timeout_s is None or not self._is_transport_error(e):
                raise
            self._undeliverable[rank] = self.round_idx
            self._update_alive_gauge()
            log.warning("elastic: dropping undeliverable send to rank %d",
                        rank, exc_info=True)

    def _ckpt_state_template(self):
        import jax

        st = {
            "net": self.aggregator.net,
            "server_opt_state": getattr(self.aggregator, "_server_opt_state", ()),
            # dp runs store the server noise RNG here so a resumed job
            # continues the key stream instead of REPLAYING the same noise
            "rng": getattr(self.aggregator, "_noise_rng",
                           jax.random.PRNGKey(0)),
        }
        if getattr(self.aggregator, "accountant", None) is not None:
            import numpy as np

            # cumulative RDP totals: epsilon() must cover pre-restart rounds
            st["dp_rdp"] = np.asarray(self.aggregator.accountant._rdp)
        return st

    def _maybe_resume(self):
        from fedml_tpu.core.checkpoint import latest_round, restore_round

        r = latest_round(self.ckpt_dir)
        if r is None:
            return
        import numpy as np

        template = dict(self._ckpt_state_template(), round=np.asarray(0, np.int64))
        state = restore_round(self.ckpt_dir, r, template)
        # sharded server plane: checkpoints gather on save (shard-agnostic
        # layout; the npz fallback restores plain host arrays) — re-partition
        # per the rule table so the device-resident-sharded invariant
        # survives resume, mirroring the standalone engine's load_state,
        # and refresh the per-device sizing gauge
        part = getattr(self.aggregator, "_partitioner", None)
        self.aggregator.net = (part.shard(state["net"]) if part is not None
                               else state["net"])
        if hasattr(self.aggregator, "_server_opt_state"):
            opt = state["server_opt_state"]
            self.aggregator._server_opt_state = (
                part.shard(opt) if part is not None else opt)
        if part is not None:
            self.aggregator._record_server_state_bytes(
                getattr(self.aggregator, "_server_opt_state", ()))
        if hasattr(self.aggregator, "_noise_rng"):
            self.aggregator._noise_rng = state["rng"]
        if "dp_rdp" in state and getattr(self.aggregator, "accountant",
                                         None) is not None:
            import numpy as np

            self.aggregator.accountant._rdp = np.asarray(state["dp_rdp"])
        self.round_idx = int(state["round"]) + 1
        # reload persisted eval history so post-resume saves don't rewrite
        # history.json with only the post-restart records
        hist_path = os.path.join(self.ckpt_dir, "history.json")
        if os.path.exists(hist_path):
            import json

            with open(hist_path) as f:
                self.aggregator.history = json.load(f)
        log.info("resumed from checkpoint: next round %d", self.round_idx)

    def _maybe_save(self):
        if self.ckpt_dir is None:
            return
        from fedml_tpu.core.checkpoint import save_round

        st = self._ckpt_state_template()
        extra = {k: v for k, v in st.items()
                 if k not in ("net", "server_opt_state", "rng")}
        save_round(self.ckpt_dir, self.round_idx, st["net"],
                   st["server_opt_state"], st["rng"],
                   history=self.aggregator.history,
                   extra_state=extra or None)

    def _broadcast_finish(self):
        for rank in range(1, self.size):
            self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, rank))
        self.finish()

    def run(self):
        if self.round_idx >= self.round_num:  # resumed past the last round
            self._broadcast_finish()
            return
        self.send_init_msg()
        super().run()

    def _broadcast_model(self, msg_type: str, global_params) -> None:
        """Sample this round's clients and broadcast ``global_params`` to
        every rank under ``msg_type`` — the shared body of send_init_msg
        and the round-advance sync (they must not diverge). Starts the
        round's trace and rides its context on each frame when tracing."""
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        self._round_ids = [int(c) for c in client_indexes]
        # stamp the aggregator's accepted round BEFORE any client can
        # answer the broadcast — uploads tagged with any other round are
        # rejected at the slotting layer (add_local_trained_result)
        self.aggregator.begin_round(self.round_idx)
        # stash the pack AS CLIENTS WILL SEE IT: under a lossy wire
        # codec their deltas are relative to the decoded broadcast
        self._bcast_leaves = codec_roundtrip(global_params)
        tr = self._dtracer
        if tr is not None:
            tr.begin_round(self.round_idx)
        for rank in range(1, self.size):
            msg = Message(msg_type, self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_params)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(client_indexes[rank - 1]))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            if tr is not None:  # trace context rides the header scalars
                msg.add_params(TRACE_KEY, tr.broadcast_ctx(rank))
            self.send_message(msg)
        if tr is not None:
            tr.end_broadcast()

    def send_init_msg(self):
        self._broadcast_model(MyMessage.MSG_TYPE_S2C_INIT_CONFIG,
                              self.aggregator.get_global_model_params())

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client,
        )

    def handle_message_receive_model_from_client(self, msg_params):
        with self._round_lock:
            sender = msg_params[Message.MSG_ARG_KEY_SENDER]
            msg_round = msg_params.get(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            if int(msg_round) != self.round_idx:
                _obs.record_stale_upload("stale")
                log.warning("drop stale upload from rank %d (round %s, now %d)",
                            sender, msg_round, self.round_idx)
                return
            if self._dtracer is not None:
                # arrival time + clock sample + the piggybacked client
                # span buffer (None from a stock/untraced peer is fine —
                # the arrival alone keeps slack computable)
                self._dtracer.on_upload(int(sender),
                                        msg_params.get(TRACE_KEY))
            if MyMessage.MSG_ARG_KEY_SPARSE_IDX in msg_params:
                # sparse uplink: densify against the global this round
                # broadcast — the ALREADY-PACKED leaves stashed at send
                # time (re-packing the full model per upload would cost N
                # device→host materializations per round under this lock)
                from fedml_tpu.comm.sparse import topk_decode

                wire_leaves = topk_decode(
                    self._bcast_leaves,
                    msg_params[MyMessage.MSG_ARG_KEY_SPARSE_IDX],
                    msg_params[MyMessage.MSG_ARG_KEY_SPARSE_VAL])
            else:
                wire_leaves = msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS]
            self.aggregator.add_local_trained_result(
                sender - 1,
                wire_leaves,
                msg_params[MyMessage.MSG_ARG_KEY_NUM_SAMPLES],
                round_idx=int(msg_round),
            )
            if not self.aggregator.check_whether_all_receive():
                return
            self._advance_round()

    def _advance_round(self):
        """Aggregate what's collected, eval, and start the next round (or
        finish). Caller holds _round_lock."""
        tel = self.telemetry
        if tel is not None:
            import numpy as np

            n_samples = float(sum(self.aggregator.sample_num_dict.values()))
            old_leaves = [np.asarray(v)
                          for v in self.aggregator.get_global_model_params()]
            with self._tracer.span("aggregate"):
                global_params = self.aggregator.aggregate()
            with self._tracer.span("eval"):
                self.aggregator.test_on_server_for_all_clients(self.round_idx)
            upd_sq = sum(
                float(np.sum((np.asarray(n) - o) ** 2))
                for n, o in zip(global_params, old_leaves))
            hist = self.aggregator.history
            # stitch: close the round's trace and fold the critical-path
            # attribution (straggler rank, phase breakdown, slack, chaos
            # cross-reference) into the round record
            cp = (self._dtracer.finish_round()
                  if self._dtracer is not None else None)
            q = self.aggregator.quarantine.for_round(self.round_idx) \
                if hasattr(self.aggregator, "quarantine") else []
            tel.emit_round(
                self.round_idx, clients=self._round_ids,
                spans=dict(self._tracer.rounds[-1]),
                metrics={"update_norm": float(np.sqrt(upd_sq)),
                         "num_samples": n_samples},
                evals=(hist[-1] if hist
                       and hist[-1].get("round") == self.round_idx else None),
                **({"critical_path": cp} if cp else {}),
                **({"quarantine": q} if q else {}))
            self._tracer.next_round()
        else:
            global_params = self.aggregator.aggregate()
            self.aggregator.test_on_server_for_all_clients(self.round_idx)
        self._maybe_save()

        self.round_idx += 1
        if self.round_idx == self.round_num:
            self._broadcast_finish()
            return
        self._broadcast_model(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                              global_params)

    def on_timeout(self, idle_s: float):
        """Watchdog (own thread): no traffic for round_timeout_s."""
        with self._round_lock:
            received = [i + 1 for i, v in
                        self.aggregator.flag_client_model_uploaded.items() if v]
            missing = [i + 1 for i, v in
                       self.aggregator.flag_client_model_uploaded.items() if not v]
            if self.round_timeout_s is None or not received or self._finished.is_set():
                log.error("round %d stalled %.1fs: waiting on client ranks %s",
                          self.round_idx, idle_s, missing)
                return
            log.warning(
                "round %d: elastic partial aggregation over ranks %s "
                "(stragglers %s dropped after %.1fs)",
                self.round_idx, received, missing, idle_s,
            )
            for i in list(self.aggregator.flag_client_model_uploaded):
                self.aggregator.flag_client_model_uploaded[i] = False
            self._advance_round()
