from fedml_tpu.distributed.fedavg.api import (
    FedML_FedAvg_distributed,
    run_simulated,
)
from fedml_tpu.distributed.fedavg.message_define import MyMessage

__all__ = ["FedML_FedAvg_distributed", "run_simulated", "MyMessage"]
