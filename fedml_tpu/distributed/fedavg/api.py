"""Distributed FedAvg entry — rank dispatch + in-process simulation helper.

Mirror of fedml_api/distributed/fedavg/FedAvgAPI.py:13-75: rank 0 becomes
the server (aggregator + server manager), rank k the client (trainer +
client manager). ``run_simulated`` stands in for mpirun: it launches all
ranks as threads over the loopback (or localhost-gRPC) backend — the
reference's "fake cluster = many processes on one box" pattern (SURVEY.md
§4.5) without processes.
"""

from __future__ import annotations

from fedml_tpu.algorithms.fedavg import FedAvgConfig
from fedml_tpu.core.client_data import FederatedData
from fedml_tpu.core.local import Task
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.client_manager import FedAvgClientManager
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.distributed.fedavg.trainer import DistributedTrainer
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated


def init_server(dataset, task, cfg, size, backend, **kw):
    aggregator = FedAvgAggregator(dataset, task, cfg, worker_num=size - 1)
    return FedAvgServerManager(aggregator, rank=0, size=size, backend=backend, **kw)


def init_client(dataset, task, cfg, rank, size, backend, local_spec=None, **kw):
    trainer = DistributedTrainer(rank, dataset, task, cfg, local_spec=local_spec)
    return FedAvgClientManager(trainer, rank=rank, size=size, backend=backend, **kw)


def FedML_FedAvg_distributed(
    process_id: int,
    worker_number: int,
    dataset: FederatedData,
    task: Task,
    cfg: FedAvgConfig,
    backend: str = "GRPC",
    **backend_kw,
):
    """Launch this process's role and block until the job finishes.

    Returns the manager (server manager exposes .aggregator.history/.net).
    """
    if process_id == 0:
        mgr = init_server(dataset, task, cfg, worker_number, backend, **backend_kw)
    else:
        mgr = init_client(dataset, task, cfg, process_id, worker_number, backend, **backend_kw)
    mgr.run()
    return mgr


def run_simulated(
    dataset: FederatedData,
    task: Task,
    cfg: FedAvgConfig,
    backend: str = "LOOPBACK",
    job_id: str = "fedavg-sim",
    base_port: int = 50000,
    ckpt_dir: str | None = None,
    broker_host: str = "127.0.0.1",
    broker_port: int = 1883,
    sparsify_ratio: float | None = None,
    update_codec: str | None = None,
    error_feedback: bool = True,
    delta_broadcast: bool = False,
    telemetry=None,
    chaos_plan=None,
    round_timeout_s: float | None = None,
    aggregator: str | None = None,
    aggregator_params: dict | None = None,
    sanitize: bool | float | None = None,
    adversary_plan=None,
    warmup: bool = False,
    shard_server_state: bool = False,
    partition_rules=None,
    async_buffer_k: int | None = None,
    staleness="constant",
    staleness_bound: int | None = None,
    buffer_deadline_s: float | None = None,
    buffer_capacity: int | None = None,
    heartbeat_max_age_s: float | None = None,
    sum_assoc: str = "auto",
    edges: int | None = None,
    fused_agg: bool = False,
    churn_trace=None,
) -> FedAvgAggregator:
    """All ranks as threads on one host — the mpirun-on-localhost analogue.

    ``chaos_plan``: a ``fedml_tpu.chaos.FaultPlan`` installed for the
    duration of the run — every rank's comm manager is wrapped in the
    deterministic fault injector (drops/dups/corruption/partitions per the
    plan's seeded schedule). Pair with ``round_timeout_s`` so dropped
    uplinks degrade to elastic partial aggregation instead of a hang.

    ``adversary_plan``: a ``fedml_tpu.chaos.AdversaryPlan`` — the listed
    worker ranks upload model-space attacks (sign_flip/scale/gaussian/
    nan/shift) on their scheduled rounds; pair with ``aggregator=``
    ('median', 'krum', ...) and the ``sanitize`` gate to run a replayable
    attack-vs-defense experiment (docs/ROBUSTNESS.md).

    ``warmup``: AOT-compile the client local-fit program through the
    persistent compile cache (enable_compile_cache) before launching the
    ranks — one rank's warm-up seeds the disk cache the sibling ranks (and
    repeat runs) then deserialize from (docs/PERFORMANCE.md §Warm-up). Off
    by default: on tiny test workloads the extra AOT pass costs more than
    the compiles it saves.

    ``shard_server_state``: partition the server's global model over this
    process's local devices (core/partition_rules.py); uploads stage to
    their shard's placement on arrival and the gather happens only at
    broadcast-pack time (docs/PERFORMANCE.md §Partitioned server state).
    Bit-exact vs the replicated server; no-op with one local device.
    ``partition_rules`` overrides the default rule table (same format as
    the standalone engine's — ``rules_from_json`` output is accepted).

    ``async_buffer_k``: arm buffered-async rounds (docs/ROBUSTNESS.md
    §Asynchronous buffered rounds) — the server aggregates as soon as K
    sanitized arrivals are staged (or ``buffer_deadline_s`` fires),
    weighting each by the ``staleness`` discount ('constant' | 'poly:A' |
    'exp:A'); ``staleness_bound`` rejects-and-requeues staler updates
    (bound 0 = the synchronous barrier expressed async — bitwise-identical
    to the sync path at K = cohort, test-enforced); ``buffer_capacity``
    bounds the staging queue (overflow sheds the stalest, never blocks);
    ``heartbeat_max_age_s`` arms heartbeat-driven cohort admission (sync
    AND async: silent ranks are excluded until a reprobe brings them
    back).

    ``update_codec``: delta/quantized uplink tier ('delta' | 'delta-int8'
    | 'delta-sign1', comm/delta.py) with client-side error feedback
    (``error_feedback=False`` is the convergence-ablation knob only).
    ``delta_broadcast``: round-delta downlinks to warm clients with a
    dense fallback for joiners/reprobes (docs/PERFORMANCE.md §Wire
    efficiency). Encoded uplinks — top-k AND the delta tiers — compose
    with ``async_buffer_k``: they densify against the version-stamped
    broadcast the dispatch wave carried (the former dense-only refusal is
    lifted; only a genuinely unversioned base is an error).

    ``churn_trace``: a ``fedml_tpu.chaos.ChurnTrace`` armed at the RANK
    level (docs/ROBUSTNESS.md §Fleet campaigns & client churn) — worker
    ranks the trace schedules offline for a round are skipped SILENTLY
    (no suspect bookkeeping, no reprobe churn, quorum denominators
    shrink) and re-invited the round the trace brings them back; a rank
    that goes dark while the trace expects it present rides the existing
    suspected-dead machinery. Orthogonal to ``cfg.churn_trace``, which
    churns the CLIENT population the cohort is sampled from.

    ``fused_agg``: fused on-device server aggregation (docs/PERFORMANCE.md
    §Fused aggregation) — uploads stage as their raw quantized leaves and
    one jit per arrival runs decode → densify against the device-resident
    broadcast stash, so the server never materializes per-client f32
    trees on host. Plain runs fold-at-arrival (peak staging O(log
    fan-in) partials); robust estimators and armed ``sanitize`` ride the
    STAGED fused mode (per-arrival evidence rows, one verdict jit at
    flush) and are BITWISE the stacked route, model bits and quarantine
    ledger. Composes with ``shard_server_state`` (the flush jit's output
    layout is the rule-table placement), ``async_buffer_k`` (arrivals
    densify at the door, the drain folds with discounted weights) and
    ``edges`` (the edge tier ingests per arrival; its uplink frames are
    bit-identical to the stacked edge's). Bitwise
    ``sum_assoc='pairwise'`` (which it implies). The one refusal left:
    host-representation aggregates (TurboAggregate keeps its own mod-p
    fused path)."""
    if edges:
        # hierarchical 2-tier topology (distributed/fedavg/hierarchy.py,
        # docs/ROBUSTNESS.md §Hierarchical tiers): 1 root + E edge
        # aggregator ranks + W workers; root fan-in is O(edges).
        # ``aggregator=``/``sanitize=`` arm the two-phase cross-tier
        # robust protocol (§Cross-tier robust gating) — every PR-4
        # defense composes with the tree. The modes below are not wired
        # through the edge tier — the dense synchronous protocol is the
        # tree contract.
        unsupported = {
            "sparsify_ratio": sparsify_ratio, "update_codec": update_codec,
            "delta_broadcast": delta_broadcast or None,
            "async_buffer_k": async_buffer_k,
            "shard_server_state": shard_server_state or None,
            "heartbeat_max_age_s": heartbeat_max_age_s,
            "sum_assoc": None if sum_assoc == "auto" else sum_assoc,
        }
        bad = [k for k, v in unsupported.items() if v is not None]
        if bad:
            raise ValueError(
                f"edges={edges} (hierarchical topology) does not compose "
                f"with {bad} — run the flat topology for those modes "
                "(tree aggregation is pairwise by construction)")
        if churn_trace is not None:
            raise ValueError(
                "churn_trace= here is RANK-level scheduled availability, "
                "and the tree's edge/worker ranks are infrastructure "
                "slots, not devices — drive client-level churn through "
                "cfg.churn_trace (cohort sampling), which composes with "
                "edges")
        from fedml_tpu.distributed.fedavg.hierarchy import (
            run_simulated_hierarchical,
        )

        # chaos crash rules naming rank 0 ARE wired for the tree now: the
        # hierarchical driver runs the same supervision loop as the flat
        # path (kill at the scheduled point, recover through checkpoint +
        # WAL, edges re-sync on the next downlink)
        return run_simulated_hierarchical(
            dataset, task, cfg, edges=edges, backend=backend,
            job_id=job_id, base_port=base_port, broker_host=broker_host,
            broker_port=broker_port, ckpt_dir=ckpt_dir,
            telemetry=telemetry, chaos_plan=chaos_plan,
            round_timeout_s=round_timeout_s, adversary_plan=adversary_plan,
            warmup=warmup, aggregator=aggregator,
            aggregator_params=aggregator_params, sanitize=sanitize,
            fused_agg=fused_agg)
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port, broker_host, broker_port)
    from fedml_tpu import chaos as _chaos

    if chaos_plan is not None:  # None must not clobber an installed plan
        _chaos.install_plan(chaos_plan)
    try:
        # chaos crash rules naming RANK 0 are server restarts (docs/
        # ROBUSTNESS.md §Server crash recovery): this driver executes
        # them deterministically — kill the manager at the scheduled
        # point (SimulatedServerCrash, a SIGKILL analogue: no farewell
        # frames, no graceful saves) and boot a FRESH manager through
        # the real checkpoint + WAL recovery path.
        active = _chaos.active_plan()
        crash_points = (active.server_crash_points()
                        if active is not None else [])
        if crash_points and ckpt_dir is None:
            raise ValueError(
                "a chaos crash rule naming rank 0 (server restart) needs "
                "ckpt_dir= — recovery replays checkpoint + WAL")

        def build_server():
            agg = FedAvgAggregator(dataset, task, cfg, worker_num=size - 1,
                                   aggregator=aggregator,
                                   aggregator_params=aggregator_params,
                                   sanitize=sanitize,
                                   shard_server_state=shard_server_state,
                                   partition_rules=partition_rules,
                                   sum_assoc=sum_assoc,
                                   fused_agg=fused_agg)
            return FedAvgServerManager(agg, rank=0, size=size,
                                       backend=backend, ckpt_dir=ckpt_dir,
                                       round_timeout_s=round_timeout_s,
                                       telemetry=telemetry,
                                       async_buffer_k=async_buffer_k,
                                       staleness=staleness,
                                       staleness_bound=staleness_bound,
                                       buffer_deadline_s=buffer_deadline_s,
                                       buffer_capacity=buffer_capacity,
                                       heartbeat_max_age_s=heartbeat_max_age_s,
                                       delta_broadcast=delta_broadcast,
                                       churn_trace=churn_trace,
                                       **kw)

        server = build_server()
        clients = [
            init_client(dataset, task, cfg, rank, size, backend,
                        sparsify_ratio=sparsify_ratio,
                        update_codec=update_codec,
                        error_feedback=error_feedback,
                        adversary_plan=adversary_plan, **kw)
            for rank in range(1, size)
        ]
        if warmup and clients:
            from fedml_tpu.utils.metrics import enable_compile_cache

            enable_compile_cache()
            # one rank compiles, every sibling deserializes from disk
            clients[0].warmup()
        if not crash_points:
            launch_simulated(server, clients)
            aggregator_ = server.aggregator
        else:
            server = run_supervised_simulated(server, clients,
                                              crash_points, build_server)
            aggregator_ = server.aggregator
    finally:
        if chaos_plan is not None:
            _chaos.install_plan(None)
    return aggregator_


def run_supervised_simulated(server, clients, crash_points, build_server,
                             join_timeout: float = 60.0):
    """Loopback supervision loop (docs/ROBUSTNESS.md §Server crash
    recovery): run the server until a scheduled SimulatedServerCrash
    fires, abandon the dead manager's transport WITHOUT any farewell
    frame (clients observe exactly the silence a dead process leaves),
    and boot a fresh manager — fresh aggregator, fresh memory — that
    recovers through checkpoint + WAL. Each crash point is consumed by
    one kill; the recovered server does not re-crash on it. Clients run
    once, spanning every server generation (they survive the outage and
    answer the resume probe — session resumption)."""
    import logging
    import threading

    from fedml_tpu.distributed.fedavg.server_manager import (
        SimulatedServerCrash,
    )

    log = logging.getLogger("fedml_tpu.distributed.fedavg")
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    remaining = list(crash_points)
    while True:
        server._crash_plan = list(remaining)
        try:
            server.run()
        except SimulatedServerCrash as e:
            remaining = remaining[1:]
            log.warning("supervisor: %s — abandoning the dead manager and "
                        "restarting through recovery (%d scheduled "
                        "crash(es) left)", e, len(remaining))
            abandon_simulated_server(server)
            server = build_server()
            continue
        if remaining:
            # the campaign finished with scheduled kills never fired
            # (e.g. an elastic round accepted fewer uploads than the
            # after_uploads threshold) — say so loudly, or a soak trial
            # 'passes' a recovery path that was never exercised
            log.warning("supervisor: run completed with %d scheduled "
                        "crash point(s) never fired: %s — the recovery "
                        "path was NOT exercised", len(remaining),
                        remaining)
        break
    for t in threads:
        t.join(timeout=join_timeout)
    return server


def abandon_simulated_server(server) -> None:
    """SIGKILL analogue for an in-process server manager: free its
    transport registration so the next generation can bind rank 0, close
    its journal handle (post-mortem appends become no-ops), and flag it
    finished so its timers/watchdog exit. Crucially sends NOTHING — a
    dead process says no goodbyes."""
    import logging

    server._finished.set()
    try:
        cm = server.com_manager
        inner = getattr(cm, "inner", cm)  # unwrap a chaos proxy
        inner.stop_receive_message()
    except Exception:  # noqa: BLE001 — teardown of a "dead" manager must
        # not kill the supervisor; the next boot re-binds rank 0 anyway
        logging.getLogger("fedml_tpu.distributed.fedavg").warning(
            "supervisor: abandoning dead server transport failed",
            exc_info=True)
    if getattr(server, "wal", None) is not None:
        server.wal.close()
