"""FedAvg client manager — local fit on command, upload to server.

Mirror of fedml_api/distributed/fedavg/FedAvgClientManager.py: on INIT/SYNC,
update model + assigned client index, run __train (:72-75), send model to
rank 0 (:66-70).

Tracing: when an inbound broadcast carries ``__trace`` context (the server
has tracing on), the handler times its unpack / local_fit / pack phases as
spans parented to the server's broadcast span and piggybacks the finished
buffer (plus the NTP clock stamps) on the upload frame — so clients trace
exactly when the server does, with zero client-side configuration. With no
context present this path is untouched and the upload is byte-identical.
"""

from __future__ import annotations

import contextlib

from fedml_tpu.comm.managers import ClientManager
from fedml_tpu.comm.message import Message
from fedml_tpu.distributed.fedavg.message_define import MyMessage
from fedml_tpu.distributed.fedavg.trainer import DistributedTrainer
from fedml_tpu.obs.fleet import TELEMETRY_KEY, DigestEmitter, attach_digest
from fedml_tpu.obs.tracing import TRACE_KEY, ClientSpanBuffer


class FedAvgClientManager(ClientManager):
    def __init__(self, trainer: DistributedTrainer, rank, size,
                 backend="LOOPBACK", sparsify_ratio: float | None = None,
                 adversary_plan=None, async_uplink: bool = True,
                 update_codec: str | None = None,
                 error_feedback: bool = True, server_rank: int = 0,
                 adversary_rank: int | None = None, **kw):
        self.trainer = trainer
        self.round_idx = 0
        # where uploads go: rank 0 (the flat root) by default; in a 2-tier
        # topology (distributed/fedavg/hierarchy.py) each worker's uplink
        # targets its EDGE aggregator rank instead — everything else about
        # the client protocol is unchanged (the downlink frames an edge
        # relays are byte-compatible with the root's)
        self.server_rank = int(server_rank)
        # async_uplink: uplink frame encoding (tree flatten + buffer copies
        # + CRC32 + optional deflate) and transmission run on a FIFO sender
        # worker (core/pipeline.AsyncSender) instead of the dispatch-loop
        # thread — the thread that must be free to receive the next
        # broadcast the moment an elastic server moves on without us. Wire
        # bytes and ordering are identical; a send failure still kills the
        # manager visibly (re-raised from the next submit / finish).
        self.async_uplink = async_uplink
        self._sender = None
        # model-space adversary (chaos/adversary.py): when this rank is in
        # the plan's schedule, its upload is perturbed AFTER the honest
        # local fit and BEFORE packing/sparsification — the Byzantine
        # client lies on the wire, so every server-side defense (clip,
        # sanitation gate, robust aggregator) sees exactly what a real
        # attacker would send. ``adversary_rank`` is the 1-based COHORT
        # rank the plan's schedule matches (default: this transport rank
        # — the flat topology's identity); in a 2-tier topology workers
        # sit at transport ranks E+1..E+W but play cohort slots 0..W-1,
        # so the hierarchy launcher passes slot + 1 and ONE plan drives a
        # flat and a tree run identically (ledger parity included)
        self.adversary_plan = adversary_plan
        self.adversary_rank = int(adversary_rank) if adversary_rank \
            is not None else int(rank)
        # top-k sparsified uplinks with per-rank error feedback
        # (comm/sparse.py); None = dense protocol. Validate HERE so a bad
        # ratio fails at launch, not inside the receive-loop handler after
        # a full local fit (where it would hang the server instead)
        if sparsify_ratio is not None and not 0.0 < sparsify_ratio <= 1.0:
            raise ValueError(
                f"sparsify_ratio must be in (0, 1], got {sparsify_ratio}")
        self.sparsify_ratio = sparsify_ratio
        # delta/quantized uplink tier (comm/delta.py, docs/PERFORMANCE.md
        # §Wire efficiency): 'delta' | 'delta-int8' | 'delta-sign1';
        # None/'dense' = the full-model protocol. Validated at launch for
        # the same reason as sparsify_ratio. The tiers are mutually
        # exclusive with top-k: both replace MODEL_PARAMS on the wire.
        if update_codec in ("dense", ""):
            update_codec = None
        if update_codec is not None:
            from fedml_tpu.comm.delta import UPDATE_CODECS

            if update_codec not in UPDATE_CODECS:
                raise ValueError(f"unknown update_codec {update_codec!r} "
                                 f"(one of {UPDATE_CODECS} or 'dense')")
            if sparsify_ratio:
                raise ValueError(
                    "update_codec and sparsify_ratio are mutually "
                    "exclusive uplink tiers — pick one")
        self.update_codec = update_codec
        # one shared error-feedback residual (comm/ef.py) owned by ALL
        # lossy tiers (top-k AND the quantized delta tiers); error_feedback
        # =False is the ablation knob the convergence tests use — never
        # the production setting (untracked compression error accumulates)
        self._ef = None
        if error_feedback and (sparsify_ratio or
                               update_codec in ("delta-int8", "delta-sign1")):
            from fedml_tpu.comm.ef import ErrorFeedback

            self._ef = ErrorFeedback()
        # the decoded broadcast currently held + its version tag — the
        # base every delta tier encodes against, and what a round-delta
        # broadcast (MSG_ARG_KEY_DELTA_PARAMS) reconstructs from
        self._held = None
        self._held_version: int | None = None
        # server session state (docs/ROBUSTNESS.md §Server crash recovery):
        # the restart epoch of the newest s2c frame, echoed on every
        # upload so a restarted server can shed this client's pre-crash
        # in-flight work exactly once; the last async dispatch wave seen,
        # answered on the post-restart s2c_resume probe. Epoch 0 = no
        # crash yet — nothing is echoed and the wire is unchanged.
        self._restart_epoch = 0
        self._last_wave: int | None = None
        self._trace_buf: ClientSpanBuffer | None = None  # lazy: see module doc
        # fleet digest emitter (obs/fleet.py): lazily created the first
        # time a broadcast carries the __telemetry marker — same
        # zero-client-config contract as tracing. None = plane off = the
        # uplink is byte-identical.
        self._digest: DigestEmitter | None = None
        super().__init__(rank, size, backend, **kw)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.handle_message_receive_model
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, lambda _m: self.finish()
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_RESUME_PROBE,
            self.handle_message_resume_probe,
        )

    def handle_message_init(self, msg_params):
        self.round_idx = 0
        self._sync_and_train(msg_params)

    def handle_message_resume_probe(self, msg_params):
        """Post-restart server probe (docs/ROBUSTNESS.md §Server crash
        recovery): adopt the new restart epoch — every later upload echoes
        it, which is what lets the server shed this client's pre-crash
        in-flight work — and answer with the last round (and async
        dispatch wave) this client saw, so the server re-dispatches or
        sheds deterministically. Handlers run serially: if this client
        was mid-fit when the server died, the probe is answered right
        after that fit's (now epoch-stale) upload is queued."""
        self._restart_epoch = int(msg_params.get(
            MyMessage.MSG_ARG_KEY_RESTART_EPOCH, self._restart_epoch))
        # answer the PROBE'S sender: probes always come straight from the
        # root, and in the hierarchical topology self.server_rank is this
        # worker's edge — which has no ack handler and must not be in the
        # resume path (flat runs are unchanged: sender == server_rank == 0)
        probe_src = int(msg_params.get(Message.MSG_ARG_KEY_SENDER,
                                       self.server_rank))
        msg = Message(MyMessage.MSG_TYPE_C2S_RESUME_ACK, self.rank,
                      probe_src)
        msg.add_params(MyMessage.MSG_ARG_KEY_LAST_SEEN_ROUND,
                       int(self.round_idx))
        msg.add_params(MyMessage.MSG_ARG_KEY_LAST_SEEN_WAVE,
                       -1 if self._last_wave is None
                       else int(self._last_wave))
        msg.add_params(MyMessage.MSG_ARG_KEY_RESTART_EPOCH,
                       self._restart_epoch)
        self.send_message(msg)

    def handle_message_receive_model(self, msg_params):
        self.round_idx += 1  # fallback when the server omits the round tag
        self._sync_and_train(msg_params)

    def _sync_and_train(self, msg_params):
        # trust the server's round counter (keeps stragglers aligned after an
        # elastic partial aggregation skipped them)
        self.round_idx = int(msg_params.get(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx))
        # adopt the server's restart epoch from any s2c frame carrying one
        # (a post-crash broadcast can arrive before the resume probe)
        ep = msg_params.get(MyMessage.MSG_ARG_KEY_RESTART_EPOCH)
        if ep is not None:
            self._restart_epoch = int(ep)
        buf = None
        blob = msg_params.get(TRACE_KEY)
        if isinstance(blob, dict) and blob.get("tid"):  # server is tracing
            if self._trace_buf is None:
                self._trace_buf = ClientSpanBuffer(self.rank)
            buf = self._trace_buf
            buf.on_broadcast(blob)
        # fleet plane marker (obs/fleet.py): the server's collector is
        # armed — start digesting (lazy, like the trace buffer)
        dig = None
        tmark = msg_params.get(TELEMETRY_KEY)
        if isinstance(tmark, dict):
            if self._digest is None:
                self._digest = DigestEmitter(self.rank)
            dig = self._digest
            dig.on_downlink(tmark)

        @contextlib.contextmanager
        def span(name):
            # compose the (independent) trace span and digest phase
            # timers — either plane can be on without the other
            with (buf.span(name) if buf is not None
                  else contextlib.nullcontext()):
                with (dig.phase(name) if dig is not None
                      else contextlib.nullcontext()):
                    yield
        # buffered-async dispatch (docs/ROBUSTNESS.md §Asynchronous buffered
        # rounds): the server's dispatch-wave counter is the work-unit key —
        # the local fit folds its rng/batch order by the WAVE (so a
        # requeued dispatch within one global version draws fresh batches,
        # matching the virtual-clock simulator's key chain), and the wave
        # is echoed on the upload so the server attributes it exactly even
        # with two dispatches in flight after a reprobe. Absent on
        # synchronous rounds: round_idx keys the fit, nothing is echoed,
        # and the wire is unchanged.
        wave = msg_params.get(MyMessage.MSG_ARG_KEY_DISPATCH_WAVE)
        if wave is not None:
            self._last_wave = int(wave)  # answered on a resume probe
        if MyMessage.MSG_ARG_KEY_DELTA_PARAMS in msg_params:
            # round-delta broadcast (docs/ROBUSTNESS.md §Delta broadcast):
            # reconstruct global@r = held@base + delta. The server only
            # sends deltas to ranks whose last UPLOAD proved they hold the
            # base version, so a mismatch here is a protocol violation
            # (e.g. a restarted client the server still believes warm) —
            # fail loudly rather than train against a wrong base.
            from fedml_tpu.comm.delta import apply_delta

            base_v = int(msg_params[MyMessage.MSG_ARG_KEY_BASE_VERSION])
            if self._held is None or self._held_version != base_v:
                raise RuntimeError(
                    f"rank {self.rank}: delta broadcast against version "
                    f"{base_v} but this client holds "
                    f"{self._held_version} — the server's warm-rank "
                    "tracking and this client disagree (restarted client?)")
            global_leaves = apply_delta(
                self._held, msg_params[MyMessage.MSG_ARG_KEY_DELTA_PARAMS])
        else:
            global_leaves = msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS]
        # the held base: what every delta tier encodes against, and the
        # next round-delta broadcast reconstructs from
        self._held = global_leaves
        self._held_version = self.round_idx
        with span("unpack"):
            self.trainer.update_model(global_leaves)
            self.trainer.update_dataset(int(msg_params[MyMessage.MSG_ARG_KEY_CLIENT_INDEX]))
        with span("local_fit"):
            wire_leaves, local_sample_num = self.trainer.train(
                self.round_idx if wave is None else int(wave))
        if self.adversary_plan is not None:
            from fedml_tpu.chaos.adversary import perturb_leaves

            wire_leaves = perturb_leaves(
                self.adversary_plan, wire_leaves, global_leaves,
                self.adversary_rank, self.round_idx)
        msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank,
                      self.server_rank)
        with span("pack"):
            if self.sparsify_ratio:
                from fedml_tpu.comm.sparse import (topk_delta, topk_encode,
                                                   topk_residual)

                delta = topk_delta(wire_leaves, global_leaves)
                comp = self._ef.compensate(delta) if self._ef else delta
                idx, vals = topk_encode(comp, self.sparsify_ratio)
                if self._ef:
                    # topk_residual IS comp - shipped: install it directly
                    self._ef.update_residual(topk_residual(comp, idx))
                msg.add_params(MyMessage.MSG_ARG_KEY_SPARSE_IDX, idx)
                msg.add_params(MyMessage.MSG_ARG_KEY_SPARSE_VAL, vals)
            elif self.update_codec:
                from fedml_tpu.comm.delta import (decode_update,
                                                  encode_update, round_delta)

                delta = round_delta(wire_leaves, global_leaves)
                comp = self._ef.compensate(delta) if self._ef else delta
                payload, scales = encode_update(comp, self.update_codec)
                if self._ef:
                    # residual tracks the SERVER's view: comp minus the
                    # decoded form of what actually went on the wire
                    self._ef.update(comp, decode_update(
                        payload, scales, self.update_codec, wire_leaves))
                msg.add_params(MyMessage.MSG_ARG_KEY_UPDATE_CODEC,
                               self.update_codec)
                msg.add_params(MyMessage.MSG_ARG_KEY_UPDATE_PAYLOAD, payload)
                msg.add_params(MyMessage.MSG_ARG_KEY_UPDATE_SCALE, scales)
            else:
                msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, wire_leaves)
            msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, local_sample_num)
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            if self._restart_epoch:
                # echo the session tag: a restarted server's epoch gate
                # sheds pre-crash uploads by exactly this mismatch
                msg.add_params(MyMessage.MSG_ARG_KEY_RESTART_EPOCH,
                               self._restart_epoch)
            if wave is not None:  # echo the async work-unit key verbatim
                msg.add_params(MyMessage.MSG_ARG_KEY_DISPATCH_WAVE, int(wave))
                # ... and the client id, so the server's ingest path never
                # rebuilds the seeded sampling permutation per upload
                msg.add_params(
                    MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                    int(msg_params[MyMessage.MSG_ARG_KEY_CLIENT_INDEX]))
        if buf is not None:  # span buffer + clock stamps ride the uplink
            msg.add_params(TRACE_KEY, buf.upload_blob())
        if dig is not None:  # the fleet digest rides the same frame
            attach_digest(msg, dig.digest(self.round_idx, wave=wave))
        self._send_upload(msg)

    def _send_upload(self, msg):
        if not self.async_uplink:
            self.send_message(msg)
            return
        if self._sender is None:  # lazy: only a manager that uploads pays
            from fedml_tpu.core.pipeline import AsyncSender

            self._sender = AsyncSender(self.send_message,
                                       name=f"fedml-uplink-r{self.rank}",
                                       on_error=self._on_uplink_error)
        self._sender.submit(msg)

    def _on_uplink_error(self, exc):
        """Sender-worker failure hook (runs on the worker thread). Without
        it a failed upload would HANG this rank: the next wake-up would be
        a broadcast the server will never send (it is still waiting for the
        upload that just died), so no submit/close remains to re-raise
        from. Shut the manager down instead — the same visible-death
        semantics the synchronous send path had."""
        import logging

        logging.getLogger("fedml_tpu.distributed.fedavg").error(
            "rank %d: uplink send failed (%s) — shutting down instead of "
            "waiting for a broadcast the server cannot send", self.rank, exc)
        self._sender = None  # worker already dead; nothing left to flush
        self.finish()

    def warmup(self) -> dict | None:
        """AOT-compile the local fit before run() blocks on the first
        broadcast (engine.warmup() analogue; see DistributedTrainer.warmup)."""
        if hasattr(self.trainer, "warmup"):
            return self.trainer.warmup()
        return None

    def finish(self):
        sender, self._sender = self._sender, None
        try:
            if sender is not None:
                # flush the queued uplink (normally empty: FINISH only
                # arrives after the server collected the last round) and
                # surface any send failure before reporting a clean exit
                sender.close()
        finally:
            # the transport must stop even when close() raises — a wedged
            # sender should fail THIS rank loudly, not leak its receive
            # loop as well
            super().finish()
