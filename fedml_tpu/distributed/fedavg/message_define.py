"""FedAvg message vocabulary.

Mirror of fedml_api/distributed/fedavg/message_define.py:6-11.
"""


class MyMessage:
    # server -> client
    MSG_TYPE_S2C_INIT_CONFIG = "s2c_init"
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = "s2c_sync"
    MSG_TYPE_S2C_FINISH = "s2c_finish"
    # client -> server
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = "c2s_send_model"

    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_ROUND = "round_idx"
    # buffered-async dispatch (docs/ROBUSTNESS.md §Asynchronous buffered
    # rounds): the rank's dispatch-wave counter rides the downlink and is
    # echoed verbatim on the upload — the server must not reconstruct it
    # from its own counter (a reprobe can put two dispatches in flight),
    # and the client folds its local-fit rng/batch order by the WAVE, so
    # a requeued dispatch draws fresh batches instead of replaying the
    # version-keyed ones. Absent on synchronous rounds (wire unchanged).
    MSG_ARG_KEY_DISPATCH_WAVE = "dispatch_wave"
    # sparse uplink (comm/sparse.py): flat top-k indices + values per leaf,
    # replacing MODEL_PARAMS; the server densifies against the stashed
    # broadcast of the version the upload's ROUND tag names
    MSG_ARG_KEY_SPARSE_IDX = "sparse_idx"
    MSG_ARG_KEY_SPARSE_VAL = "sparse_val"
    # quantized/delta uplink (comm/delta.py, docs/PERFORMANCE.md §Wire
    # efficiency): UPDATE_CODEC names the tier ('delta' | 'delta-int8' |
    # 'delta-sign1'), UPDATE_PAYLOAD carries one encoded array per model
    # leaf, UPDATE_SCALE the per-leaf f32 scales. All replace MODEL_PARAMS;
    # the base version is the echoed ROUND tag (same stash lookup as the
    # sparse tier). Payload/scale keys are in Message.LOSSY_EXEMPT — the
    # lossy frame tiers must never re-encode them.
    MSG_ARG_KEY_UPDATE_CODEC = "upd_codec"
    MSG_ARG_KEY_UPDATE_PAYLOAD = "upd_q"
    MSG_ARG_KEY_UPDATE_SCALE = "upd_scale"
    # hierarchical 2-tier topology (docs/ROBUSTNESS.md §Hierarchical
    # tiers; distributed/fedavg/hierarchy.py): the root sends ONE s2c
    # frame per EDGE carrying CHILD_CLIENTS (the cohort slots' client
    # assignments for that edge's block); the edge fans it out to its
    # workers as ordinary s2c frames, tree-reduces their sanitized
    # uplinks, and answers with ONE e2s_agg frame — a pre-aggregated
    # update (EDGE_WSUM, canonical pairwise weighted SUM, never a mean:
    # the division happens once, at the root) + its weight total
    # (EDGE_WEIGHT) + per-child quarantine verdicts (EDGE_REASONS, slot
    # ids in EDGE_SLOTS, trained client ids in EDGE_CLIENTS). Root
    # fan-in is O(edges), and tree ≡ flat stays bitwise under
    # sum_assoc='pairwise' (test-enforced).
    MSG_TYPE_E2S_SEND_AGG_TO_SERVER = "e2s_agg"
    MSG_ARG_KEY_CHILD_CLIENTS = "child_clients"
    MSG_ARG_KEY_EDGE_WSUM = "edge_wsum"
    MSG_ARG_KEY_EDGE_WEIGHT = "edge_weight"
    MSG_ARG_KEY_EDGE_REASONS = "edge_reasons"
    MSG_ARG_KEY_EDGE_SLOTS = "edge_slots"
    MSG_ARG_KEY_EDGE_CLIENTS = "edge_clients"
    # raw client-reported sample mass of the uploads that ARRIVED at the
    # edge (pre-gate, pre-verdict) — telemetry only, never the division:
    # under two-phase robust gating EDGE_WEIGHT is the fold total of the
    # VERDICT weights (krum's winner folds at weight exactly 1.0), so the
    # round record's num_samples would otherwise read verdict mass, not
    # sample mass, and diverge from the flat twin's
    MSG_ARG_KEY_EDGE_SAMPLES = "edge_samples"
    # two-phase cross-tier robust gating (docs/ROBUSTNESS.md §Cross-tier
    # robust gating): with a robust aggregator / sanitation gate armed in
    # tree mode, the edge HOLDS its block's staged uploads and first
    # forwards ONE e2s_evidence frame — per-slot sanitation evidence
    # (EVIDENCE_NORM update norms, EVIDENCE_FINITE flags, the [C, S]
    # EVIDENCE_SKETCH count-sketch of the flattened updates, and the raw
    # EVIDENCE_WEIGHT sample counts), sketch_dim + 3 scalars per client.
    # The root runs the cohort-global gate + estimator selection over the
    # gathered evidence and answers each edge with ONE s2e_verdict frame
    # (VERDICT_WEIGHTS: per-slot survivor weights, zero = rejected or
    # unselected; VERDICT_REASONS: the ledger's reason codes). The edge
    # then folds ONLY the survivors (zero-weight slots replaced by the
    # held global — exact zero terms) and forwards the ordinary e2s_agg
    # partial, so steady root ingress stays O(edges) update frames and
    # only O(cohort) scalar evidence ever reaches the root. Both frame
    # types are round-tagged and deduped like any FMT2 frame.
    MSG_TYPE_E2S_SEND_EVIDENCE_TO_SERVER = "e2s_evidence"
    MSG_TYPE_S2E_SEND_VERDICT_TO_EDGE = "s2e_verdict"
    MSG_ARG_KEY_EVIDENCE_NORM = "ev_norm"
    MSG_ARG_KEY_EVIDENCE_FINITE = "ev_finite"
    MSG_ARG_KEY_EVIDENCE_SKETCH = "ev_sketch"
    MSG_ARG_KEY_EVIDENCE_WEIGHT = "ev_weight"
    MSG_ARG_KEY_VERDICT_WEIGHTS = "verdict_w"
    MSG_ARG_KEY_VERDICT_REASONS = "verdict_reasons"
    # masked secure aggregation (docs/ROBUSTNESS.md §Secure aggregation;
    # distributed/turboaggregate.py): uploads carry the MASKED field
    # vector + the Shamir share vector of the client's self-mask seed
    # (share k addressed to cohort slot k) inside MODEL_PARAMS' leaf
    # list. When clients drop inside round_timeout_s the server sends
    # each SURVIVOR one s2c_reveal frame naming the dead slots
    # (SECAGG_DEAD, round-tagged); the survivor answers one c2s_reveal
    # frame with its pairwise seeds for exactly those slots
    # (SECAGG_PAIR_SEEDS, same order as the echoed SECAGG_DEAD) — the
    # shares/seeds that let the server strip the dead clients' orphaned
    # pairwise masks and the live clients' self-masks. Below t+1
    # survivors (or a reveal lost past the deadline) the round sheds and
    # re-broadcasts instead of wedging.
    MSG_TYPE_S2C_REVEAL_REQUEST = "s2c_reveal"
    MSG_TYPE_C2S_REVEAL_SHARES = "c2s_reveal"
    MSG_ARG_KEY_SECAGG_DEAD = "secagg_dead"
    MSG_ARG_KEY_SECAGG_PAIR_SEEDS = "secagg_pair_seeds"
    # hierarchical masked secure aggregation (docs/ROBUSTNESS.md
    # §Hierarchical secure aggregation): with --edges each worker's
    # pairwise masks are drawn WITHIN its edge block (seeds/keys stay
    # cohort-global, partners restricted), so the masks cancel at the
    # edge. The edge folds its block's masked uploads mod p, runs the
    # tiered reveal locally for in-block dead slots (s2c_reveal /
    # c2s_reveal between edge and its workers, same frames as the flat
    # tier), strips the masks, and forwards ONE e2s_masked_agg frame per
    # round: the UNMASKED int64 field partial (EDGE_FIELD_SUM — still
    # additive mod p; the root folds E partials and decodes ONCE), the
    # block's survivor/dead GLOBAL slot ids (EDGE_SURVIVORS / EDGE_DEAD),
    # per-surviving-slot sample counts keyed by global slot
    # (EDGE_SLOT_SAMPLES), the block's plaintext extra-state pytrees
    # (EDGE_EXTRAS, one per survivor, slot order), and how the block
    # decoded (SECAGG_OUTCOME full|recovered|shed + SECAGG_RECOVERY_S).
    # A whole edge lost inside round_timeout_s is the only case the root
    # handles: it sheds exactly that block's slots — no cross-block mask
    # ever needs repair. Root ingress stays O(edges) frames.
    MSG_TYPE_E2S_SEND_MASKED_AGG_TO_SERVER = "e2s_masked_agg"
    MSG_ARG_KEY_EDGE_FIELD_SUM = "edge_field_sum"
    MSG_ARG_KEY_EDGE_SURVIVORS = "edge_survivors"
    MSG_ARG_KEY_EDGE_DEAD = "edge_dead"
    MSG_ARG_KEY_EDGE_SLOT_SAMPLES = "edge_slot_samples"
    MSG_ARG_KEY_EDGE_EXTRAS = "edge_extras"
    MSG_ARG_KEY_SECAGG_OUTCOME = "secagg_outcome"
    MSG_ARG_KEY_SECAGG_RECOVERY_S = "secagg_recovery_s"
    # server crash recovery (docs/ROBUSTNESS.md §Server crash recovery):
    # after a restart every s2c frame carries the server's RESTART_EPOCH
    # (absent on epoch-0 runs — the wire is unchanged until a crash
    # actually happens; stock peers ignore it) and clients echo it on
    # every upload, so the epoch gate sheds pre-crash in-flight work
    # exactly once (counted ``server_restart``) instead of double-folding
    # it into the re-dispatched round. A server that recovers a WAL with
    # an OPEN (uncommitted) round first sends each rank one s2c_resume
    # probe; the client answers c2s_resume with the LAST round (and async
    # dispatch wave) it saw, letting the server deterministically decide
    # per rank between re-dispatch and shed before re-broadcasting the
    # open round under the new epoch.
    MSG_TYPE_S2C_RESUME_PROBE = "s2c_resume"
    MSG_TYPE_C2S_RESUME_ACK = "c2s_resume"
    MSG_ARG_KEY_RESTART_EPOCH = "restart_epoch"
    MSG_ARG_KEY_LAST_SEEN_ROUND = "last_seen_round"
    MSG_ARG_KEY_LAST_SEEN_WAVE = "last_seen_wave"
    # fleet observability plane (docs/OBSERVABILITY.md §Fleet rollup;
    # obs/fleet.py owns the semantics — this constant mirrors
    # fleet.TELEMETRY_KEY, test-pinned equal): with Telemetry(fleet=True)
    # on rank 0 every s2c frame carries a small enablement marker under
    # this key and every uplink piggybacks the rank's compact digest
    # (round/wave, counter deltas, phase-timing sketch, ε, memory); an
    # edge folds its block's digests into ONE blob on its e2s_agg frame
    # so root ingress stays O(edges). Stock peers ignore the key; with
    # the plane off (the default) no frame carries it — the wire is
    # byte-identical, test-enforced.
    MSG_ARG_KEY_TELEMETRY = "__telemetry"
    # round-delta broadcast (server -> warm client): DELTA_PARAMS replaces
    # MODEL_PARAMS and BASE_VERSION names the global version the delta was
    # computed against — the client must hold exactly that version (the
    # server only sends deltas to ranks whose last upload PROVED it); cold
    # ranks (joiners, reprobes, elastic re-sends) get the dense fallback
    MSG_ARG_KEY_DELTA_PARAMS = "delta_params"
    MSG_ARG_KEY_BASE_VERSION = "base_version"
