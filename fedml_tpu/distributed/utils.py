"""Worker-mapping config for the cross-process runtime.

Mirror of the reference's gpu_mapping.yaml + grpc_ipconfig.csv pair
(fedml_api/distributed/utils/gpu_mapping.py:8-37 maps MPI rank -> (host,
cuda device); ip_config_utils.py maps rank -> ip). On TPU there is no
per-process accelerator binding to manage — XLA owns the chips — so the
mapping collapses to rank -> host for message routing, plus optional
per-rank TPU visibility for multi-host jobs.

YAML schema:
    workers:
      - host: 10.0.0.1     # ranks are assigned in listed order
        ranks: [0, 1]
      - host: 10.0.0.2
        ranks: [2, 3, 4]
"""

from __future__ import annotations


def load_worker_mapping(path: str) -> dict[int, str]:
    """rank -> host, usable directly as GrpcCommManager's ip_table."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    table: dict[int, str] = {}
    for entry in doc["workers"]:
        for r in entry["ranks"]:
            if r in table:
                raise ValueError(f"rank {r} mapped twice")
            table[int(r)] = str(entry["host"])
    return table


def mapping_to_ip_config_csv(table: dict[int, str], path: str) -> None:
    """Write the reference-format csv (receiver_id,ip) for interop."""
    with open(path, "w") as f:
        f.write("receiver_id,ip\n")
        for r in sorted(table):
            f.write(f"{r},{table[r]}\n")


def backend_kwargs(backend: str, job_id: str, base_port: int = 50000,
                   broker_host: str = "127.0.0.1",
                   broker_port: int = 1883) -> dict:
    """Transport-specific kwargs for make_comm_manager: loopback routes by
    job_id; gRPC by port block (reference: grpc_comm_manager.py:29 port =
    50000+rank); MQTT by broker address (mqtt_comm_manager.py)."""
    b = backend.upper()
    if b == "LOOPBACK":
        return {"job_id": job_id}
    if b == "MQTT":
        return {"broker_host": broker_host, "broker_port": broker_port}
    return {"base_port": base_port}


def launch_simulated(server, clients, join_timeout: float = 60.0):
    """Run all ranks as threads on one host — the mpirun-on-localhost
    analogue every run_simulated shares (reference SURVEY.md §4.5: "fake
    cluster = many processes on one box"). Blocks in the server's receive
    loop; returns once every client thread drained FINISH."""
    import threading

    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=join_timeout)
    return server
