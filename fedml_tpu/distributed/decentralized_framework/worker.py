"""Gossip worker: train -> push to out-neighbors -> await in-neighbors.

Message flow parity with decentralized_worker_manager.py:25-46; the mixing
step is the topology-weighted average of in-neighbor vectors (DSGD-style,
standalone/decentralized/client_dsgd.py semantics), with ``train_fn``
supplied by the caller (a jitted local step in real use).
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np

from fedml_tpu.comm.managers import DistributedManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.topology import SymmetricTopologyManager

MSG_NEIGHBOR = "gossip_result"
KEY_VEC = "vec"
KEY_ROUND = "round_idx"


class DecentralizedWorkerManager(DistributedManager):
    def __init__(self, rank: int, size: int, topology: SymmetricTopologyManager,
                 x0: np.ndarray, train_fn: Callable, num_rounds: int,
                 backend="LOOPBACK", **kw):
        self.topology = topology
        self.x = np.asarray(x0, np.float64)
        self.train_fn = train_fn
        self.num_rounds = num_rounds
        self.round_idx = 0
        self.inbox: dict[int, dict[int, np.ndarray]] = {}
        self.done = threading.Event()
        self.history: list[np.ndarray] = []
        super().__init__(rank, size, backend, **kw)

    # all ranks are workers: in/out neighbors come from the mixing topology
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_NEIGHBOR, self._on_neighbor)

    def run(self):
        self._train_and_push()
        super().run()

    def _train_and_push(self):
        self.x = np.asarray(self.train_fn(self.x, self.rank, self.round_idx))
        for nb in self.topology.get_out_neighbor_idx_list(self.rank):
            msg = Message(MSG_NEIGHBOR, self.rank, int(nb))
            msg.add_params(KEY_VEC, self.x)
            msg.add_params(KEY_ROUND, self.round_idx)
            self.send_message(msg)
        self._maybe_advance()

    def _on_neighbor(self, params):
        r = int(params[KEY_ROUND])
        self.inbox.setdefault(r, {})[params[Message.MSG_ARG_KEY_SENDER]] = params[KEY_VEC]
        self._maybe_advance()

    def _maybe_advance(self):
        in_nbs = self.topology.get_in_neighbor_idx_list(self.rank)
        got = self.inbox.get(self.round_idx, {})
        if any(nb not in got for nb in in_nbs):
            return
        # topology-weighted mixing (row-stochastic W)
        w = self.topology.get_in_neighbor_weights(self.rank)
        mixed = w[self.rank] * self.x
        for nb in in_nbs:
            mixed = mixed + w[nb] * got[nb]
        self.x = mixed
        self.history.append(self.x.copy())
        self.inbox.pop(self.round_idx, None)
        self.round_idx += 1
        if self.round_idx >= self.num_rounds:
            self.done.set()
            self.finish()
            return
        self._train_and_push()


def run_decentralized(x0s, train_fn, num_rounds: int, neighbor_num: int = 2,
                      backend="LOOPBACK", job_id="gossip", seed=0):
    """All workers as threads; returns the list of final worker vectors."""
    n = len(x0s)
    topo = SymmetricTopologyManager(n, neighbor_num=neighbor_num, seed=seed)
    topo.generate_topology()
    workers = [
        DecentralizedWorkerManager(
            r, n, topo, x0s[r], train_fn, num_rounds, backend,
            **({"job_id": job_id} if backend.upper() == "LOOPBACK" else {}),
        )
        for r in range(n)
    ]
    threads = [threading.Thread(target=w.run, daemon=True) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    return [w.x for w in workers]
