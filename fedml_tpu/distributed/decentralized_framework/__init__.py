"""Decentralized (server-less) gossip framework over the comm layer.

Mirror of fedml_api/distributed/decentralized_framework/ (SURVEY.md §2.2,
§3.5): each worker trains locally, pushes its result to topology
out-neighbors, and advances to the next round once all in-neighbor results
arrive (decentralized_worker_manager.py:29-46). The on-TPU SPMD counterpart
(lax.ppermute mixing) lives in fedml_tpu/algorithms/decentralized.py; this
package is the cross-process form for real multi-party deployments.
"""

from fedml_tpu.distributed.decentralized_framework.worker import (
    DecentralizedWorkerManager,
    run_decentralized,
)

__all__ = ["DecentralizedWorkerManager", "run_decentralized"]
