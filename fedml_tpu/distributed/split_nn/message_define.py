"""SplitNN message vocabulary (split_nn/message_define.py analogue)."""


class SplitMessage:
    MSG_TYPE_S2C_START = "split_s2c_start"       # your turn: (round, client_id)
    MSG_TYPE_S2C_GRADS = "split_s2c_grads"       # grads for the last acts
    MSG_TYPE_S2C_FINISH = "split_s2c_finish"
    MSG_TYPE_C2S_ACTS = "split_c2s_acts"         # acts + labels + mask
    MSG_TYPE_C2S_TURN_DONE = "split_c2s_done"    # my shard is exhausted

    KEY_ACTS = "acts"
    KEY_LABELS = "labels"
    KEY_MASK = "mask"
    KEY_GRADS = "grads"
    KEY_ROUND = "round_idx"
    KEY_CLIENT_ID = "client_id"
