"""Distributed SplitNN entry — role dispatch + localhost simulation.

Mirror of fedml_api/distributed/split_nn/SplitNNAPI.py: rank 0 owns the
upper model cut (server), ranks 1..K the lower cuts (clients in a ring).
"""

from __future__ import annotations

from fedml_tpu.algorithms.split_nn import SplitNNConfig
from fedml_tpu.distributed.split_nn.client_manager import SplitNNClientManager
from fedml_tpu.distributed.split_nn.server_manager import SplitNNServerManager
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated


def SplitNN_distributed(process_id: int, worker_number: int, dataset,
                        client_module, server_module, cfg: SplitNNConfig,
                        backend: str = "GRPC", **backend_kw):
    """Launch this process's role and block until the job finishes."""
    if process_id == 0:
        mgr = SplitNNServerManager(dataset, client_module, server_module, cfg,
                                   rank=0, size=worker_number,
                                   backend=backend, **backend_kw)
    else:
        mgr = SplitNNClientManager(dataset, client_module, cfg,
                                   rank=process_id, size=worker_number,
                                   backend=backend, **backend_kw)
    mgr.run()
    return mgr


def run_simulated(dataset, client_module, server_module, cfg: SplitNNConfig,
                  backend: str = "LOOPBACK", job_id: str = "splitnn-sim",
                  base_port: int = 50000):
    """All ranks as threads (mpirun-on-localhost analogue). Returns
    (server_manager, client_managers) — server holds .history and the upper
    cut; each client keeps its slot's lower cut."""
    size = cfg.client_num + 1
    kw = backend_kwargs(backend, job_id, base_port)
    server = SplitNNServerManager(dataset, client_module, server_module, cfg,
                                  rank=0, size=size, backend=backend, **kw)
    clients = [
        SplitNNClientManager(dataset, client_module, cfg, rank=r, size=size,
                             backend=backend, **kw)
        for r in range(1, size)
    ]
    launch_simulated(server, clients)
    return server, clients
