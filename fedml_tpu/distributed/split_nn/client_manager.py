"""SplitNN client: lower-cut owner — activations up, gradients back.

Mirror of split_nn/client.py: forward_pass ships activations + labels
(:25-31); on the returned activation gradients the client backprops through
its cut and steps (:33-35). The lower cut persists per worker slot across
rounds; batch order/shuffles match the in-process SplitNNAPI exactly
(grouping-invariant pack_clients), so the distributed ring reproduces the
fused program's parameters bit-for-bit (tested).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.comm.managers import ClientManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.client_data import pack_clients
from fedml_tpu.distributed.split_nn.message_define import SplitMessage


class SplitNNClientManager(ClientManager):
    def __init__(self, dataset, client_module, cfg, rank, size,
                 backend="LOOPBACK", **kw):
        self.data, self.cm, self.cfg = dataset, client_module, cfg

        # identical init derivation to SplitNNAPI.__init__ (k1 of the split);
        # every slot starts from the same lower-cut weights, as in-process
        key = jax.random.PRNGKey(cfg.seed)
        k1, _ = jax.random.split(key)
        x0 = jnp.asarray(dataset.train_x[: cfg.batch_size])
        self.cp = client_module.init(k1, x0, train=False)["params"]
        self.ctx = optax.sgd(cfg.lr)
        self.copt = self.ctx.init(self.cp)

        counts = [len(v) for v in dataset.train_idx_map.values()]
        b = int(np.ceil(max(counts) / cfg.batch_size))
        self.num_batches = min(cfg.max_batches or b, b)

        cm, ctx = client_module, self.ctx

        @jax.jit
        def forward(cp, x):
            return cm.apply({"params": cp}, x, train=True)

        @jax.jit
        def backward(cp, copt, x, m, cot):
            def fwd(cp_):
                return cm.apply({"params": cp_}, x, train=True)

            _, vjp = jax.vjp(fwd, cp)
            (g,) = vjp(cot)
            upd, copt_n = ctx.update(g, copt, cp)
            has = jnp.sum(m) > 0
            keep = lambda new, old: jax.tree.map(
                lambda a, b: jax.lax.select(has, a, b), new, old)
            return keep(optax.apply_updates(cp, upd), cp), keep(copt_n, copt)

        self._forward, self._backward = forward, backward
        self._cb = None
        self._cb_round = None
        self._bidx = 0
        super().__init__(rank, size, backend, **kw)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(SplitMessage.MSG_TYPE_S2C_START,
                                              self._on_start)
        self.register_message_receive_handler(SplitMessage.MSG_TYPE_S2C_GRADS,
                                              self._on_grads)
        self.register_message_receive_handler(SplitMessage.MSG_TYPE_S2C_FINISH,
                                              lambda _m: self.finish())

    # ------------------------------------------------------------------ turn
    def _on_start(self, params):
        round_idx = int(params[SplitMessage.KEY_ROUND])
        client_id = int(params[SplitMessage.KEY_CLIENT_ID])
        if self._cb_round != (round_idx, client_id):
            # one pack per (round, assignment); epochs within the round reuse it
            self._cb = pack_clients(self.data, [client_id], self.cfg.batch_size,
                                    max_batches=self.num_batches,
                                    seed=self.cfg.seed, round_idx=round_idx)
            self._cb_round = (round_idx, client_id)
        # pack_clients sizes the block to THIS client's batch count (it
        # truncates, never pads up to num_batches) — iterate what it built
        self._n_batches = self._cb.x.shape[1]
        self._bidx = 0
        self._send_acts()

    def _send_acts(self):
        i = self._bidx
        self._x = jnp.asarray(self._cb.x[0][i])
        self._m = jnp.asarray(self._cb.mask[0][i])
        acts = self._forward(self.cp, self._x)
        msg = Message(SplitMessage.MSG_TYPE_C2S_ACTS, self.rank, 0)
        msg.add_params(SplitMessage.KEY_ACTS, np.asarray(acts))
        msg.add_params(SplitMessage.KEY_LABELS, np.asarray(self._cb.y[0][i]))
        msg.add_params(SplitMessage.KEY_MASK, np.asarray(self._cb.mask[0][i]))
        self.send_message(msg)

    def _on_grads(self, params):
        cot = jnp.asarray(params[SplitMessage.KEY_GRADS])
        self.cp, self.copt = self._backward(self.cp, self.copt, self._x,
                                            self._m, cot)
        self._bidx += 1
        if self._bidx < self._n_batches:
            self._send_acts()
            return
        self.send_message(Message(SplitMessage.MSG_TYPE_C2S_TURN_DONE,
                                  self.rank, 0))
