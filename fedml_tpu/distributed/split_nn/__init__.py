"""Cross-process SplitNN — per-batch activation/gradient exchange.

Mirror of fedml_api/distributed/split_nn/ (SURVEY.md §3.4): the client owns
the lower model cut, the server the upper; every batch crosses the process
boundary twice (activations up, gradients back), and clients take turns in
a ring. The math is the exact split of the in-process engine's batch_step
(algorithms/split_nn.py), so the two runtimes converge identically.
"""

from fedml_tpu.distributed.split_nn.api import run_simulated
from fedml_tpu.distributed.split_nn.client_manager import SplitNNClientManager
from fedml_tpu.distributed.split_nn.server_manager import SplitNNServerManager

__all__ = ["run_simulated", "SplitNNClientManager", "SplitNNServerManager"]
