"""SplitNN server: upper model owner + ring coordinator.

Mirror of split_nn/server.py forward_pass/backward_pass (:40-60) fused into
one jitted step: loss on incoming activations, server-parameter update, and
the activation gradient shipped back. Ring turn-taking parity with
SplitNNAPI.train (algorithms/split_nn.py:106-128): per round, epochs x
clients turns in rank order.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.comm.managers import ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.distributed.split_nn.message_define import SplitMessage

log = logging.getLogger("fedml_tpu.distributed.split_nn")


class SplitNNServerManager(ServerManager):
    def __init__(self, dataset, client_module, server_module, cfg, rank=0,
                 size=0, backend="LOOPBACK", **kw):
        self.data, self.sm, self.cfg = dataset, server_module, cfg
        self.num_clients = size - 1
        self.round_idx = 0
        self.epoch_idx = 0
        self.turn = 0  # which client rank-1 is active
        self.history: list[dict] = []
        self._aux = jnp.zeros(3)

        # identical init derivation to SplitNNAPI.__init__ (k1 inits the
        # lower cut to shape the example activations, k2 the upper)
        key = jax.random.PRNGKey(cfg.seed)
        k1, k2 = jax.random.split(key)
        x0 = jnp.asarray(dataset.train_x[: cfg.batch_size])
        cvars = client_module.init(k1, x0, train=False)
        acts0 = client_module.apply(cvars, x0, train=False)
        svars = server_module.init(k2, acts0, train=False)
        self.sp = svars["params"]
        self.stx = optax.sgd(cfg.lr)
        self.sopt = self.stx.init(self.sp)

        sm, stx = server_module, self.stx

        @jax.jit
        def server_step(sp, sopt, acts, y, m):
            def loss_fn(sp_, acts_):
                logits = sm.apply({"params": sp_}, acts_, train=True)
                per = optax.softmax_cross_entropy_with_integer_labels(logits, y)
                n = jnp.maximum(jnp.sum(m), 1.0)
                l = jnp.sum(per * m) / n
                correct = jnp.sum((jnp.argmax(logits, -1) == y) * m)
                return l, (jnp.sum(per * m), correct, jnp.sum(m))

            (l, aux), (gs, g_acts) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(sp, acts)
            has = jnp.sum(m) > 0
            upd, sopt_n = stx.update(gs, sopt, sp)
            keep = lambda new, old: jax.tree.map(
                lambda a, b: jax.lax.select(has, a, b), new, old)
            return (keep(optax.apply_updates(sp, upd), sp), keep(sopt_n, sopt),
                    g_acts, jnp.stack(aux))

        self._server_step = server_step
        super().__init__(rank, size, backend, **kw)

    # ------------------------------------------------------------------ flow
    def run(self):
        self._start_turn()
        super().run()

    def _active_rank(self) -> int:
        return 1 + self.turn

    def _start_turn(self):
        ids = sample_clients(self.round_idx, self.data.num_clients,
                             self.num_clients, self.cfg.seed)
        msg = Message(SplitMessage.MSG_TYPE_S2C_START, self.rank, self._active_rank())
        msg.add_params(SplitMessage.KEY_ROUND, self.round_idx)
        msg.add_params(SplitMessage.KEY_CLIENT_ID, int(ids[self.turn]))
        self.send_message(msg)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(SplitMessage.MSG_TYPE_C2S_ACTS,
                                              self._on_acts)
        self.register_message_receive_handler(SplitMessage.MSG_TYPE_C2S_TURN_DONE,
                                              self._on_turn_done)

    def _on_acts(self, params):
        acts = jnp.asarray(params[SplitMessage.KEY_ACTS])
        y = jnp.asarray(params[SplitMessage.KEY_LABELS])
        m = jnp.asarray(params[SplitMessage.KEY_MASK])
        self.sp, self.sopt, g_acts, aux = self._server_step(
            self.sp, self.sopt, acts, y, m)
        self._aux = self._aux + aux
        msg = Message(SplitMessage.MSG_TYPE_S2C_GRADS, self.rank,
                      params[Message.MSG_ARG_KEY_SENDER])
        msg.add_params(SplitMessage.KEY_GRADS, jax.device_get(g_acts))
        self.send_message(msg)

    def _on_turn_done(self, _params):
        self.turn += 1
        if self.turn < self.num_clients:
            self._start_turn()
            return
        self.turn = 0
        self.epoch_idx += 1
        if self.epoch_idx < self.cfg.epochs:
            self._start_turn()
            return
        self.epoch_idx = 0
        aux = jax.device_get(self._aux)
        n = max(float(aux[2]), 1.0)
        self.history.append({"round": self.round_idx,
                             "train_loss": float(aux[0]) / n,
                             "train_acc": float(aux[1]) / n})
        self._aux = jnp.zeros(3)
        self.round_idx += 1
        if self.round_idx >= self.cfg.comm_round:
            for r in range(1, self.size):
                self.send_message(Message(SplitMessage.MSG_TYPE_S2C_FINISH,
                                          self.rank, r))
            self.finish()
            return
        self._start_turn()
