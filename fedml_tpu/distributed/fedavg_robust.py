"""Distributed robust FedAvg — defenses applied at the server aggregator.

Mirror of fedml_api/distributed/fedavg_robust/ (6-file pattern): the message
flow, trainer, and managers are FedAvg's; FedAvgRobustAggregator.py applies
the fedml_core/robustness defenses before/after the weighted average
(--defense_type norm_diff_clipping|weak_dp, --norm_bound, --stddev,
robust_aggregation.py:33-36). Here each uploaded update is norm-diff-clipped
against the current global model inside one jitted pass, and weak-DP noise
is added to the aggregate — the same pure pytree ops the SPMD
FedAvgRobustAPI runs as engine hooks (algorithms/fedavg_robust.py).
"""

from __future__ import annotations

import jax

from fedml_tpu.algorithms.fedavg import FedAvgConfig
from fedml_tpu.comm.message import pack_pytree, unpack_pytree
from fedml_tpu.core.local import NetState
from fedml_tpu.core.robust import add_gaussian_noise, norm_diff_clipping
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.api import init_client
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated


class FedAvgRobustAggregator(FedAvgAggregator):
    def __init__(self, dataset, task, cfg: FedAvgConfig, worker_num: int,
                 defense_type: str = "norm_diff_clipping",  # | 'weak_dp' | 'none'
                 norm_bound: float = 30.0, stddev: float = 0.025):
        super().__init__(dataset, task, cfg, worker_num)
        if defense_type not in ("norm_diff_clipping", "weak_dp", "none"):
            # 'dp' (accounted DP-FedAvg) is the in-process engine's
            # (algorithms/fedavg_robust.py); an unknown value silently
            # running defenseless would be worse than refusing
            raise ValueError(f"unknown defense_type {defense_type!r} for the "
                             "cross-process robust runtime")
        self.defense_type = defense_type
        self._noise_rng = jax.random.PRNGKey(cfg.seed + 7)

        @jax.jit
        def clip(net: NetState, net_global: NetState) -> NetState:
            return NetState(
                norm_diff_clipping(net.params, net_global.params, norm_bound),
                net.extra,
            )

        @jax.jit
        def noise(net: NetState, rng) -> NetState:
            return NetState(add_gaussian_noise(rng, net.params, stddev), net.extra)

        self._clip, self._noise = clip, noise

    def aggregate(self):
        if self.defense_type in ("norm_diff_clipping", "weak_dp"):
            for r in list(self.model_dict):
                net_r = unpack_pytree(self.net, self.model_dict[r])
                self.model_dict[r] = pack_pytree(self._clip(net_r, self.net))
        out = super().aggregate()  # weighted average -> self.net
        if self.defense_type == "weak_dp":
            self._noise_rng, k = jax.random.split(self._noise_rng)
            self.net = self._noise(self.net, k)
            out = pack_pytree(self.net)
        return out


def run_simulated(dataset, task, cfg: FedAvgConfig, backend="LOOPBACK",
                  job_id="fedavg-robust-sim", base_port=50000, **defense_kw):
    """All ranks as threads (mpirun-on-localhost analogue); returns the
    aggregator with .net/.history."""
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port)
    aggregator = FedAvgRobustAggregator(dataset, task, cfg, worker_num=size - 1,
                                        **defense_kw)
    server = FedAvgServerManager(aggregator, rank=0, size=size, backend=backend, **kw)
    clients = [init_client(dataset, task, cfg, r, size, backend, **kw)
               for r in range(1, size)]
    launch_simulated(server, clients)
    return aggregator
