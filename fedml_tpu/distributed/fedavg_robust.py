"""Distributed robust FedAvg — defenses applied at the server aggregator.

Mirror of fedml_api/distributed/fedavg_robust/ (6-file pattern): the message
flow, trainer, and managers are FedAvg's; FedAvgRobustAggregator.py applies
the fedml_core/robustness defenses before/after the weighted average
(--defense_type norm_diff_clipping|weak_dp, --norm_bound, --stddev,
robust_aggregation.py:33-36). Here each uploaded update is norm-diff-clipped
against the current global model inside one jitted pass, and weak-DP noise
is added to the aggregate — the same pure pytree ops the SPMD
FedAvgRobustAPI runs as engine hooks (algorithms/fedavg_robust.py).

Beyond the reference, ``defense_type='dp'`` is ACCOUNTED DP-FedAvg
(core/privacy.py): clip to C, UNIFORM average over the m clients that
actually reported (elastic rounds shrink m — the noise z*C/m and the
accountant's sampling rate both use the realized m), Gaussian noise on
the aggregate, cumulative (ε, δ) via ``epsilon()``. DP state (RDP totals
+ noise RNG) rides in the server checkpoint so a resumed job neither
under-reports ε nor replays noise keys.
"""

from __future__ import annotations

import jax

from fedml_tpu.algorithms.fedavg import FedAvgConfig
from fedml_tpu.comm.message import pack_pytree, unpack_pytree
from fedml_tpu.core.local import NetState
from fedml_tpu.core.robust import add_gaussian_noise, norm_diff_clipping
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.api import init_client
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated


class FedAvgRobustAggregator(FedAvgAggregator):
    # the clipping defense unpacks/re-packs every upload host-side at the
    # barrier (pack_pytree = np.asarray) — arrival-time device staging
    # would just bounce each update device->host again under the lock
    _stage_uploads_on_arrival = False

    def __init__(self, dataset, task, cfg: FedAvgConfig, worker_num: int,
                 defense_type: str = "norm_diff_clipping",  # |'weak_dp'|'dp'|'none'
                 norm_bound: float = 30.0, stddev: float = 0.025,
                 noise_multiplier: float = 1.0, **agg_kw):
        # agg_kw: the base aggregator's robust-aggregation surface
        # (aggregator= / sanitize=) — clipping runs first, then the gate +
        # robust estimator see the clipped stack (defenses compose)
        super().__init__(dataset, task, cfg, worker_num, **agg_kw)
        if defense_type not in ("norm_diff_clipping", "weak_dp", "dp", "none"):
            # an unknown value silently running defenseless would be worse
            # than refusing
            raise ValueError(f"unknown defense_type {defense_type!r} for the "
                             "cross-process robust runtime")
        self.defense_type = defense_type
        self.accountant = None
        if defense_type == "dp":
            # accounted DP-FedAvg (see algorithms/fedavg_robust.py): clip
            # to C, UNIFORM average, noise z*C/m. m is the clients that
            # ACTUALLY reported (elastic partial aggregation may shrink a
            # round) — the noise is calibrated per aggregate, and the
            # accountant is charged with the realized sampling rate.
            from fedml_tpu.core.privacy import DPAccountant

            if noise_multiplier <= 0:
                raise ValueError("defense_type='dp' needs noise_multiplier "
                                 f"> 0, got {noise_multiplier}")
            self.accountant = DPAccountant()
            self._dp_z, self._dp_C = noise_multiplier, norm_bound
        self._privacy_cache = None
        self._noise_rng = jax.random.PRNGKey(cfg.seed + 7)
        self._stddev = stddev

        @jax.jit
        def clip(net: NetState, net_global: NetState) -> NetState:
            return NetState(
                norm_diff_clipping(net.params, net_global.params, norm_bound),
                net.extra,
            )

        # sd is a TRACED scalar: elastic rounds vary m (and hence the dp
        # stddev) round to round — a static arg would recompile each time
        def noise(net: NetState, rng, sd) -> NetState:
            return NetState(add_gaussian_noise(rng, net.params, sd), net.extra)

        noise_jit_kw = {}
        if self._partitioner is not None:
            # pin the noised state to the rule-table layout inside the
            # compiled pass — the server plane stays partitioned round
            # over round with no eager re-sharding afterwards
            noise_jit_kw["out_shardings"] = self._partitioner.shardings(
                self.net)
        self._clip, self._noise = clip, jax.jit(noise, **noise_jit_kw)

    def aggregate(self):
        if self.defense_type in ("norm_diff_clipping", "weak_dp", "dp"):
            for r in list(self.model_dict):
                net_r = unpack_pytree(self.net, self.model_dict[r])
                self.model_dict[r] = pack_pytree(self._clip(net_r, self.net))
        m_received = len(self.model_dict)
        if self.defense_type == "dp":
            # uniform average: the C/m sensitivity the noise assumes does
            # not survive sample-count weighting on unbalanced data. The
            # DP argument drops the SAMPLE-COUNT half of the weight only —
            # an async buffered flush's staleness discount (load_buffered's
            # side table) still applies, or --staleness would be silently
            # disabled exactly when the defense is on
            disc = getattr(self, "_async_discounts", None)
            self.sample_num_dict = {
                r: (1 if disc is None else disc.get(r, 1.0))
                for r in self.sample_num_dict}
        self._aggregate_core()  # weighted average -> self.net, unpacked
        if self.defense_type in ("weak_dp", "dp"):
            if self.defense_type == "dp":
                sd = self._dp_z * self._dp_C / max(m_received, 1)
                # privacy-budget ledger (docs/ROBUSTNESS.md §Privacy
                # ledger): the block the server manager rides on this
                # round's record, plus the live ε gauge the
                # privacy_budget health rule alerts on
                from fedml_tpu.core.privacy import charge_and_record

                q = m_received / self.cfg.client_num_in_total
                wal = getattr(self, "wal", None)
                if wal is not None:
                    # WAL pre-charge, fsync'd BEFORE the noise key is
                    # drawn (§Server crash recovery): a crash between
                    # charge and commit replays this record into the
                    # restarted accountant, so the reported cumulative ε
                    # can never be lower than the charges incurred (the
                    # conservative direction — a crash between pre-charge
                    # and the noise draw over-counts one round)
                    wal.append("precharge", sync=True,
                               round=int(self.current_round),
                               q=float(q), z=float(self._dp_z),
                               clip=float(self._dp_C), m=int(m_received))
                self._privacy_cache = charge_and_record(
                    self.accountant, q,
                    self._dp_z, self._dp_C, realized_m=m_received)
            else:
                sd = self._stddev
            self._noise_rng, k = jax.random.split(self._noise_rng)
            # out_shardings pin the noised state to the rule-table layout
            # when the server plane is sharded
            self.net = self._noise(self.net, k, sd)
        return pack_pytree(self.net)

    def epsilon(self, delta: float = 1e-5) -> float:
        """Cumulative (ε, δ)-DP spent so far (defense_type='dp')."""
        if self.accountant is None:
            raise ValueError("defense_type='dp' required for accounting")
        return self.accountant.epsilon(delta)

    def privacy_record(self) -> dict | None:
        """The round record's ``privacy`` block (None outside dp mode) —
        the server manager rides it on every emitted round."""
        return self._privacy_cache


def run_simulated(dataset, task, cfg: FedAvgConfig, backend="LOOPBACK",
                  job_id="fedavg-robust-sim", base_port=50000,
                  ckpt_dir: str | None = None, **defense_kw):
    """All ranks as threads (mpirun-on-localhost analogue); returns the
    aggregator with .net/.history."""
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port)
    aggregator = FedAvgRobustAggregator(dataset, task, cfg, worker_num=size - 1,
                                        **defense_kw)
    server = FedAvgServerManager(aggregator, rank=0, size=size, backend=backend,
                                 ckpt_dir=ckpt_dir, **kw)
    clients = [init_client(dataset, task, cfg, r, size, backend, **kw)
               for r in range(1, size)]
    launch_simulated(server, clients)
    return aggregator
