"""Distributed TurboAggregate — secure aggregation over the cross-process runtime.

Mirror of fedml_api/distributed/turboaggregate/ (TA_Aggregator.py:56+,
mpc_function.py:38-76): clients never upload cleartext updates. Each client
quantizes its weighted params (weight = its share of the round's public
sample counts, computable by every party from the deterministic sampler)
into GF(2^31-1), Shamir-encodes them, and uploads only the share matrix; the
server sums shares in the field and reconstructs the *sum* by Lagrange
interpolation at 0 — additive homomorphism means no single update is ever
visible server-side. BN/extra statistics (not secret) travel in cleartext
and take the plain weighted mean.

The field/Shamir primitives are the same collectives.finite_field ops the
SPMD TurboAggregateAPI uses, so the secure path matches plain FedAvg up to
quantization (<1e-3 relative, tested).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgConfig
from fedml_tpu.collectives import finite_field as ff
from fedml_tpu.comm.message import pack_pytree
from fedml_tpu.core.local import NetState
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.client_manager import FedAvgClientManager
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.distributed.fedavg.trainer import DistributedTrainer
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated
from fedml_tpu.utils.tree import (tree_unvectorize, tree_vectorize,
                                  tree_weighted_mean)


class SecureTrainer(DistributedTrainer):
    """DistributedTrainer whose wire format is [shares, *extra_leaves]."""

    def __init__(self, client_rank, dataset, task, cfg, n_shares=5,
                 threshold_t=2, quant_scale=2**16):
        super().__init__(client_rank, dataset, task, cfg)
        self.n_shares, self.threshold_t = n_shares, threshold_t
        self.quant_scale = quant_scale

    def _round_weight(self, round_idx: int, n: int) -> float:
        """This client's sample-weight n_k / sum_cohort(n_j). Sample counts
        are public and the sampler is deterministic, so every party computes
        the same cohort total — keeping encoded field values <= |w|*scale
        (pre-normalized like the in-process path; an n_k-scaled share would
        burn mod-p headroom and wrap silently at FEMNIST scale)."""
        from fedml_tpu.core.sampling import sample_clients

        ids = sample_clients(round_idx, self.cfg.client_num_in_total,
                             self.cfg.client_num_per_round, self.cfg.seed)
        cap = self.num_batches * self.cfg.batch_size
        total = sum(min(len(self.dataset.train_idx_map[int(i)]), cap) for i in ids)
        return n / max(total, 1)

    def train(self, round_idx: int):
        n = self.fit(round_idx)  # self.net now holds the local fit
        w = self._round_weight(round_idx, n)
        vec = tree_vectorize(self.net.params) * w
        z = ff.field_encode(vec, self.quant_scale)
        key = jax.random.fold_in(
            jax.random.PRNGKey(self.cfg.seed + 1013), round_idx)
        key = jax.random.fold_in(key, self.client_index)
        shares = np.asarray(
            ff.shamir_encode(z, key, self.n_shares, self.threshold_t), np.int64)
        extras = pack_pytree(self.net.extra)
        return [shares] + extras, n


class TAAggregator(FedAvgAggregator):
    """Sums share matrices in GF(p); reconstructs only the aggregate."""

    # Shamir shares are int64 host math (mod-p numpy) — device staging at
    # arrival would buy nothing and jnp would truncate the field elements
    _stage_uploads_on_arrival = False

    def __init__(self, dataset, task, cfg: FedAvgConfig, worker_num: int,
                 n_shares=5, threshold_t=2, quant_scale=2**16):
        super().__init__(dataset, task, cfg, worker_num)
        self.n_shares, self.threshold_t = n_shares, threshold_t
        self.quant_scale = quant_scale

    def aggregate(self):
        ranks = sorted(self.model_dict)

        summed = None
        for r in ranks:
            sh = np.asarray(self.model_dict[r][0], np.int64)
            summed = sh if summed is None else (summed + sh) % ff.P_DEFAULT
        alphas = np.arange(1, self.n_shares + 1, dtype=np.int64)
        z_sum = ff.shamir_decode(jnp.asarray(summed), jnp.asarray(alphas),
                                 self.threshold_t)
        # clients upload pre-normalized weights (weights sum to 1), so the
        # reconstructed field sum IS the weighted average
        vec = ff.field_decode(z_sum, self.quant_scale)
        new_params = tree_unvectorize(jnp.asarray(vec, jnp.float32),
                                      self.net.params)

        extra_leaves = jax.tree.leaves(self.net.extra)
        if extra_leaves:
            stacked = [
                jnp.stack([jnp.asarray(self.model_dict[r][1 + i]) for r in ranks])
                for i in range(len(extra_leaves))
            ]
            wts = jnp.asarray([self.sample_num_dict[r] for r in ranks], jnp.float32)
            avg = tree_weighted_mean(stacked, wts)
            new_extra = jax.tree.unflatten(jax.tree.structure(self.net.extra), avg)
        else:
            new_extra = self.net.extra

        self.net = NetState(new_params, new_extra)
        self.model_dict.clear()
        self.sample_num_dict.clear()
        return pack_pytree(self.net)


def run_simulated(dataset, task, cfg: FedAvgConfig, backend="LOOPBACK",
                  job_id="turboagg-sim", base_port=50000, n_shares=5,
                  threshold_t=2, quant_scale=2**16):
    """All ranks as threads (mpirun-on-localhost analogue); returns the
    aggregator with .net/.history."""
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port)
    aggregator = TAAggregator(dataset, task, cfg, worker_num=size - 1,
                              n_shares=n_shares, threshold_t=threshold_t,
                              quant_scale=quant_scale)
    server = FedAvgServerManager(aggregator, rank=0, size=size, backend=backend, **kw)
    clients = []
    for r in range(1, size):
        trainer = SecureTrainer(r, dataset, task, cfg, n_shares=n_shares,
                                threshold_t=threshold_t, quant_scale=quant_scale)
        clients.append(FedAvgClientManager(trainer, rank=r, size=size,
                                           backend=backend, **kw))
    launch_simulated(server, clients)
    return aggregator
