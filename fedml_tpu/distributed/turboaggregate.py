"""Distributed TurboAggregate — dropout-tolerant masked secure aggregation.

The original mirror of fedml_api/distributed/turboaggregate/ shipped whole
Shamir share MATRICES per client and died the moment any client dropped
mid-round. This tier is the SecAgg-mold replacement (core/secure_agg.py,
docs/ROBUSTNESS.md §Secure aggregation):

- clients upload ONE masked field vector (their weighted update quantized
  into GF(2^31-1) plus cancelling pairwise masks and a Shamir-shared
  self-mask) — the server never sees a cleartext update, and its
  per-upload cost is a single streaming add mod p (``fold_masked``);
- with ``round_timeout_s`` armed, clients that crash/partition inside the
  deadline degrade the round instead of wedging it: the server asks each
  survivor for the pairwise seeds of exactly the dead slots
  (``s2c_reveal``/``c2s_reveal`` frames), strips the orphaned masks and
  the survivors' self-masks, and lands the EXACT elastic partial
  aggregate (survivor reweighting, sample-weight exact vs a numpy
  oracle); below ``threshold_t + 1`` survivors — or with a reveal lost
  past the deadline — the round sheds loudly: every lost slot is
  ledgered, ``fed_secagg_rounds_total{outcome="shed"}`` counts it, and
  the round re-broadcasts (the all-uploads-lost wedge-fix path) so a
  recovered fleet re-converges;
- ``defense_type='dp'`` runs accounted DP-FedAvg ON the masked path:
  clients clip their round delta to C before masking, the server
  calibrates Gaussian noise ``z*C/m`` over the REALIZED survivor count m,
  and every round record carries the ``privacy`` block (ε@δ, q, z, C,
  cumulative RDP — core/privacy.privacy_block). DP state (RDP totals +
  noise RNG) rides the server checkpoint, so resume neither under-reports
  ε nor replays noise keys.

Replay is bit-for-bit: every mask seed derives from the session seed via
sha256 (core/secure_agg.derive_secret — the fedlint determinism
discipline), so a chaos run's masked aggregates, ledger, and recovery
frames replay exactly.

Hierarchical tier (docs/ROBUSTNESS.md §Hierarchical secure aggregation;
``run_simulated(edges=E)``): pairwise masks are drawn WITHIN each edge
block (seeds/keys stay cohort-global, partners restricted — masks cancel
at the edge), so every ``TASecureEdgeManager`` folds its block's masked
uploads mod p, runs the reveal recovery LOCALLY for in-block dead slots,
and forwards one unmasked int64 field partial; the root
(``HierTASecureServerManager``/``HierTAAggregator``) folds E partials
mod p and decodes ONCE — mod-p addition is exact and associative, so the
tree aggregate is BITWISE the flat masked aggregate over the same cohort.
Root ingress stays O(edges) frames; a whole edge lost inside
``round_timeout_s`` sheds exactly that block's slots (no cross-block mask
ever needs repair). ``fused_ingest=True`` keeps the fold accumulator
device-resident (one jitted add mod p per arrival — the fused_agg
treatment on the masked path, bitwise identical to the host fold).
``defense_type='dp'`` additionally charges a per-client privacy ledger
(core/privacy.ClientPrivacyLedger): the WAL precharge record carries the
surviving client ids, so per-user ε survives a server SIGKILL and is
never under-reported.
"""

from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgConfig
from fedml_tpu.collectives import finite_field as ff
from fedml_tpu.comm.message import Message, pack_pytree
from fedml_tpu.core import secure_agg as sa
from fedml_tpu.core.local import NetState
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.client_manager import FedAvgClientManager
from fedml_tpu.distributed.fedavg.hierarchy import (EdgeTopology,
                                                    FedAvgEdgeManager)
from fedml_tpu.distributed.fedavg.message_define import MyMessage
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.distributed.fedavg.trainer import DistributedTrainer
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated
from fedml_tpu.obs import comm_instrument as _obs
from fedml_tpu.obs import perf_instrument as _perf
from fedml_tpu.utils.tree import (tree_unvectorize, tree_vectorize,
                                  tree_weighted_mean)

log = logging.getLogger("fedml_tpu.distributed.fedavg")


def _batch_cap(dataset, cfg: FedAvgConfig) -> int:
    """The trainer's num_batches formula (trainer.num_batches_for — ONE
    definition) as a sample cap: the server must compute the SAME
    per-client cap to reproduce the deterministic cohort weight total
    (sample counts are public; the masked sum is not)."""
    from fedml_tpu.distributed.fedavg.trainer import num_batches_for

    max_count = max(len(v) for v in dataset.train_idx_map.values())
    return num_batches_for(max_count, cfg) * cfg.batch_size


def cohort_sample_counts(round_idx: int, cfg: FedAvgConfig, dataset,
                         cap: int) -> tuple[np.ndarray, list[int]]:
    """(sampled client ids, per-slot sample counts) — computable by every
    party from the deterministic sampler, which is what lets clients
    pre-normalize their weights without a weight-exchange phase."""
    from fedml_tpu.core.sampling import sample_clients

    ids = sample_clients(round_idx, cfg.client_num_in_total,
                         cfg.client_num_per_round, cfg.seed)
    counts = [min(len(dataset.train_idx_map[int(i)]), cap) for i in ids]
    return ids, counts


def _secagg_config(cfg: FedAvgConfig, threshold_t: int | None,
                   quant_scale: float, defense_type: str,
                   norm_bound: float,
                   secagg_max_abs: float) -> sa.SecAggConfig:
    """One construction rule for every party: DP mode's clip bound IS the
    capacity promise (||delta||_2 <= C bounds every coordinate); the
    weighted path promises ``secagg_max_abs`` and enforces it at mask
    time. ``threshold_t=None`` adapts to the cohort (min(2, K-1) — a
    2-client cohort cannot carry t=2); an EXPLICIT t out of range stays
    a loud error. Raises at construction when the cohort would wrap
    GF(p)."""
    if threshold_t is None:
        threshold_t = sa.default_threshold_t(cfg.client_num_per_round)
    max_abs = float(norm_bound) if defense_type == "dp" \
        else float(secagg_max_abs)
    return sa.SecAggConfig(cohort=cfg.client_num_per_round,
                           threshold_t=threshold_t,
                           quant_scale=quant_scale, max_abs=max_abs)


class SecureTrainer(DistributedTrainer):
    """DistributedTrainer whose wire format is [masked_vec, b_shares,
    *extra_leaves] — the update never leaves the client unmasked."""

    def __init__(self, client_rank, dataset, task, cfg, threshold_t=None,
                 quant_scale=2**16, defense_type: str = "none",
                 norm_bound: float = 30.0, secagg_max_abs: float = 4.0,
                 n_shares=None, slot: int | None = None, peers=None):
        from fedml_tpu.core.client_source import ClientDataSource

        if isinstance(dataset, ClientDataSource):
            raise ValueError(
                "the masked secure-aggregation tier is cross-silo: it "
                "needs every cohort member's public sample count "
                "(train_idx_map) for the pre-normalized weights — "
                "streamed ClientDataSources are refused")
        super().__init__(client_rank, dataset, task, cfg)
        if n_shares is not None:
            log.debug("SecureTrainer: n_shares is ignored — self-mask "
                      "seeds are Shamir-shared across the whole cohort")
        # cohort SLOT (stable per rank) — not the per-round dataset client
        # id the server re-assigns via CLIENT_INDEX. The hierarchical tier
        # passes it explicitly (worker rank = 1 + edges + slot, so rank-1
        # would be wrong there) plus the slot's edge-block ``peers``: pair
        # masks drawn only against block partners cancel AT THE EDGE.
        self.slot = (client_rank - 1) if slot is None else int(slot)
        self.peers = None if peers is None \
            else sorted(int(j) for j in peers)
        self.defense_type = defense_type
        self.norm_bound = float(norm_bound)
        self.secagg = _secagg_config(cfg, threshold_t, quant_scale,
                                     defense_type, norm_bound,
                                     secagg_max_abs)

    def _round_weight(self, round_idx: int, n: int) -> float:
        """This client's n_k / sum_cohort(n_j), from the public sampler —
        pre-normalized so encoded field values stay inside the capacity
        promise (an n_k-scaled upload would burn mod-p headroom and wrap
        silently at scale)."""
        _, counts = cohort_sample_counts(round_idx, self.cfg, self.dataset,
                                         _batch_cap(self.dataset, self.cfg))
        return n / max(sum(counts), 1)

    def reveal_pair_seeds(self, round_idx: int,
                          dead_slots: list[int]) -> list[int]:
        """The recovery reveal: this survivor's pairwise seeds for exactly
        the DEAD slots (each masks nothing once the dead contribution is
        gone) — never a seed for a live pair, never the self-mask seed."""
        sk = sa.secret_key(self.cfg.seed, round_idx, self.slot,
                           self.secagg.p)
        pks = sa.public_keys(self.cfg.seed, round_idx, self.secagg.cohort,
                             self.secagg.p)
        return [sa.pair_seed(sk, pks[int(j)], self.secagg.p)
                for j in dead_slots]

    def train(self, round_idx: int):
        if self.defense_type == "dp":
            # snapshot the broadcast BEFORE the fit overwrites self.net:
            # the clipped ROUND DELTA is what gets masked
            global_vec = np.asarray(tree_vectorize(self.net.params),
                                    np.float64)
        n = self.fit(round_idx)  # self.net now holds the local fit
        if self.defense_type == "dp":
            # clip the ROUND DELTA to the L2 ball C, mask unweighted: the
            # server divides by the realized survivor count and the noise
            # z*C/m assumes exactly this sensitivity
            vec = np.asarray(tree_vectorize(self.net.params),
                             np.float64) - global_vec
            nrm = float(np.linalg.norm(vec))
            if nrm > self.norm_bound:
                vec = vec * (self.norm_bound / nrm)
            weight = 1.0
        else:
            vec = np.asarray(tree_vectorize(self.net.params), np.float64)
            weight = self._round_weight(round_idx, n)
        # mask_update enforces the capacity promise (max_abs) for every
        # engine — a coordinate past it would wrap the cohort sum
        masked = sa.mask_update(vec, weight, self.slot, self.cfg.seed,
                                round_idx, self.secagg, peers=self.peers)
        b_shares = sa.self_mask_shares(self.cfg.seed, round_idx, self.slot,
                                       self.secagg)
        extras = pack_pytree(self.net.extra)
        return [masked, b_shares] + extras, n


class TAAggregator(FedAvgAggregator):
    """Folds masked uploads mod p (one add per arrival); decodes only the
    survivor SUM after mask recovery."""

    # masked vectors are int64 host math (mod-p numpy) — device staging at
    # arrival would buy nothing and jnp would truncate the field elements
    _stage_uploads_on_arrival = False

    def __init__(self, dataset, task, cfg: FedAvgConfig, worker_num: int,
                 threshold_t=None, quant_scale=2**16,
                 defense_type: str = "none",  # 'none' | 'dp'
                 norm_bound: float = 30.0, noise_multiplier: float = 1.0,
                 secagg_max_abs: float = 4.0, n_shares=None,
                 fused_ingest: bool = False):
        from fedml_tpu.core.client_source import ClientDataSource

        if isinstance(dataset, ClientDataSource):
            raise ValueError(
                "the masked secure-aggregation tier is cross-silo: "
                "streamed ClientDataSources are refused (public cohort "
                "sample counts need train_idx_map)")
        super().__init__(dataset, task, cfg, worker_num)
        if defense_type not in ("none", "dp"):
            raise ValueError(f"unknown defense_type {defense_type!r} for "
                             "the secure-aggregation tier ('none' | 'dp')")
        # capacity guard at CONSTRUCTION (collectives/finite_field.py):
        # K terms * 2 * quant_scale * max_abs must stay inside GF(p)
        self.secagg = _secagg_config(cfg, threshold_t, quant_scale,
                                     defense_type, norm_bound,
                                     secagg_max_abs)
        self.quant_scale = float(quant_scale)
        self.defense_type = defense_type
        # NOT named fused_agg: that attribute routes the base server
        # manager through _stage_fused/add_fused_result (the dense device
        # path), which would bypass the masked fold entirely. fused_ingest
        # keeps the mod-p accumulator device-resident inside OUR fold.
        self.fused_ingest = bool(fused_ingest)
        self._fold = sa.fold_masked_device if fused_ingest \
            else sa.fold_masked
        self.accountant = None
        self.client_ledger = None
        self._privacy_cache = None
        if defense_type == "dp":
            from fedml_tpu.core.privacy import (ClientPrivacyLedger,
                                                DPAccountant)

            if noise_multiplier <= 0:
                raise ValueError("defense_type='dp' needs noise_multiplier"
                                 f" > 0, got {noise_multiplier}")
            self.accountant = DPAccountant()
            self.client_ledger = ClientPrivacyLedger()
            self._dp_z, self._dp_C = float(noise_multiplier), float(norm_bound)
            self._noise_rng = jax.random.PRNGKey(cfg.seed + 7)
            _perf.ensure_client_privacy_family()
        _perf.ensure_secagg_families()
        # per-round masked-fold state (begin_round resets; _frozen parks
        # the fold while a recovery phase is in flight so a late upload
        # cannot corrupt the already-fixed survivor sum)
        self._acc = None
        self._round_slots: set[int] = set()
        self._b_shares: dict[int, np.ndarray] = {}
        self._extras: dict[int, list] = {}
        self._frozen = False
        self._recovery: tuple[list[int], list[int], dict] | None = None

    def begin_round(self, round_idx: int) -> None:
        super().begin_round(round_idx)
        self._acc = None
        self._round_slots = set()
        self._b_shares = {}
        self._extras = {}
        self._frozen = False
        self._recovery = None
        self.sample_num_dict.clear()

    def add_local_trained_result(self, index: int, wire_leaves,
                                 sample_num: int,
                                 round_idx: int | None = None) -> None:
        if not self._admit_upload(index, round_idx):
            return
        if self._frozen:
            # recovery in flight: the survivor set (and the reveal
            # requests out for it) is FIXED — folding a late slot now
            # would leave its masks unstrippable; the shed/re-broadcast
            # path gives the rank a fresh shot at the round
            _obs.record_stale_upload("stale")
            log.warning("secagg: dropping late upload from slot %d — "
                        "mask recovery already in flight", index)
            return
        if index in self._round_slots:
            # chaos-duplicated upload: the fold is additive, so exactly-
            # once matters here where the dense path's slot overwrite was
            # naturally idempotent
            _obs.record_stale_upload("stale")
            log.warning("secagg: dropping duplicate upload from slot %d",
                        index)
            return
        masked, b_shares = wire_leaves[0], wire_leaves[1]
        self._acc = self._fold(self._acc, masked, self.secagg.p)
        self._round_slots.add(index)
        self._b_shares[index] = np.asarray(b_shares, np.int64)
        self._extras[index] = list(wire_leaves[2:])
        self.sample_num_dict[index] = sample_num
        self.flag_client_model_uploaded[index] = True

    def set_recovery(self, survivors, dead,
                     pair_reveals: dict[int, dict[int, int]]) -> None:
        """Fix the survivor/dead split (and the survivor-revealed pairwise
        seeds) the next ``aggregate()`` decodes with. Dead slots are
        ledgered ``secagg_dropout`` with the clients they would have
        trained."""
        survivors = sorted(int(s) for s in survivors)
        dead = sorted(int(d) for d in dead)
        if len(survivors) < self.secagg.recovery_min:
            raise ValueError(
                f"secagg recovery needs >= {self.secagg.recovery_min} "
                f"survivors, got {len(survivors)}")
        self._recovery = (survivors, dead, dict(pair_reveals))
        if dead:
            ids = self.client_sampling(self.current_round)
            for j in dead:
                self.quarantine.record(self.current_round, j + 1,
                                       "secagg_dropout",
                                       client=int(ids[j]))
                _obs.record_update_rejected("secagg_dropout")
            _perf.record_secagg_dropped(len(dead))

    def aggregate(self):
        if self._recovery is None:
            # full barrier (no elastic manager in the stack): every slot
            self.set_recovery(sorted(self._round_slots), [], {})
        survivors, dead, reveals = self._recovery
        t0 = time.perf_counter()
        # strip survivors' self-masks from the shares the SURVIVOR slots
        # hold (>= t+1 by the recovery threshold) + the dead slots'
        # orphaned pairwise masks from the survivor reveals
        self_seeds = {
            i: sa.recover_self_seed(
                survivors, self._b_shares[i][survivors],
                self.secagg.threshold_t, self.secagg.p)
            for i in survivors}
        vec_sum = sa.unmask_sum(self._acc, survivors, dead, self_seeds,
                                reveals, self.secagg)
        return self._finish_aggregate(vec_sum, survivors, t0)

    def _finish_aggregate(self, vec_sum, survivors, t0):
        """The decode-side tail both tiers share once a round's unmasked
        float64 survivor SUM exists (flat: after unmask_sum; tree: after
        the root folds the edges' field partials and decodes once): the
        DP noise/charge path — including the per-client precharge journal
        — or the elastic survivor reweighting, the extras mean, and the
        fold-state reset."""
        nsamp = np.asarray([self.sample_num_dict[i] for i in survivors],
                           np.float64)
        if self.defense_type == "dp":
            # clients masked UNWEIGHTED clipped deltas: uniform average
            # over the realized m + noise z*C/m, accountant charged with
            # the realized sampling rate (elastic rounds shrink m)
            m = len(survivors)
            delta = vec_sum / m
            sd = self._dp_z * self._dp_C / m
            ids = self.client_sampling(self.current_round)
            client_ids = [int(ids[i]) for i in survivors]
            wal = getattr(self, "wal", None)
            if wal is not None:
                # WAL pre-charge, fsync'd BEFORE the noise key is drawn
                # (docs/ROBUSTNESS.md §Server crash recovery): a restarted
                # accountant replays this record, so the reported ε can
                # never be lower than the charges actually incurred. The
                # surviving CLIENT ids ride the record, so the per-client
                # ledgers replay under the same never-under-report
                # contract (clients= is what _recover_in_flight re-charges)
                wal.append("precharge", sync=True,
                           round=int(self.current_round),
                           q=float(m / self.cfg.client_num_in_total),
                           z=float(self._dp_z), clip=float(self._dp_C),
                           m=int(m), clients=client_ids)
            self._noise_rng, k = jax.random.split(self._noise_rng)
            noise = np.asarray(
                jax.random.normal(k, np.shape(delta), jnp.float32),
                np.float64) * sd
            global_vec = np.asarray(tree_vectorize(self.net.params),
                                    np.float64)
            new_vec = global_vec + delta + noise
            from fedml_tpu.core.privacy import charge_and_record

            self._privacy_cache = charge_and_record(
                self.accountant, m / self.cfg.client_num_in_total,
                self._dp_z, self._dp_C, realized_m=m,
                client_ledger=self.client_ledger, client_ids=client_ids)
        else:
            # clients pre-normalized by the FULL cohort total T; the
            # decoded sum is sum_S (n_i/T) x_i — rescale by T / sum_S n_i
            # for the exact survivor-weighted mean (the elastic rule)
            _, counts = cohort_sample_counts(
                self.current_round, self.cfg, self.dataset,
                _batch_cap(self.dataset, self.cfg))
            new_vec = vec_sum * (max(sum(counts), 1)
                                 / max(float(nsamp.sum()), 1e-12))
        new_params = tree_unvectorize(
            jnp.asarray(np.asarray(new_vec, np.float32)), self.net.params)

        # extras (BN stats) are not secret: plain weighted mean over the
        # survivors' cleartext extra leaves
        extra_leaves = jax.tree.leaves(self.net.extra)
        if extra_leaves and survivors:
            stacked = [
                jnp.stack([jnp.asarray(self._extras[i][k])
                           for i in survivors])
                for k in range(len(extra_leaves))
            ]
            avg = tree_weighted_mean(stacked,
                                     jnp.asarray(nsamp, jnp.float32))
            new_extra = jax.tree.unflatten(
                jax.tree.structure(self.net.extra), avg)
        else:
            new_extra = self.net.extra

        self.net = NetState(new_params, new_extra)
        self._acc, self._recovery = None, None
        self._round_slots, self._b_shares, self._extras = set(), {}, {}
        self.sample_num_dict.clear()
        _perf.record_flush_seconds(time.perf_counter() - t0)
        return pack_pytree(self.net)

    def privacy_record(self) -> dict | None:
        """The round record's ``privacy`` block (None outside dp mode) —
        the server manager rides it on every emitted round."""
        return self._privacy_cache


class TASecureClientManager(FedAvgClientManager):
    """FedAvgClientManager that answers mask-recovery reveal requests.

    Reveal requests are retried by the server watchdog (one deterministic
    re-send at the watchdog cadence), so the handler dedupes on
    (round, dead-set): a retry that finds the reveal already computed
    retransmits the SAME seeds verbatim — the server's exactly-once fold
    drops the duplicate, and a retry can never desync the seed values."""

    def register_message_receive_handlers(self):
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_REVEAL_REQUEST,
            self.handle_message_reveal_request)

    def handle_message_reveal_request(self, msg_params):
        round_idx = int(msg_params[MyMessage.MSG_ARG_KEY_ROUND])
        dead = [int(d) for d in
                np.asarray(msg_params[MyMessage.MSG_ARG_KEY_SECAGG_DEAD])]
        key = (round_idx, tuple(dead))
        cache = getattr(self, "_reveal_cache", None)
        if cache is None:
            cache = self._reveal_cache = {}
        seeds = cache.get(key)
        if seeds is None:
            seeds = self.trainer.reveal_pair_seeds(round_idx, dead)
            # one recovery in flight at a time: the previous round's (or
            # dead-set's) entry can never be legitimately re-requested
            cache.clear()
            cache[key] = seeds
        else:
            log.info("secagg: duplicate reveal request for round %d — "
                     "retransmitting the cached reply verbatim", round_idx)
        msg = Message(MyMessage.MSG_TYPE_C2S_REVEAL_SHARES, self.rank,
                      self.server_rank)
        msg.add_params(MyMessage.MSG_ARG_KEY_SECAGG_DEAD,
                       np.asarray(dead, np.int64))
        msg.add_params(MyMessage.MSG_ARG_KEY_SECAGG_PAIR_SEEDS,
                       np.asarray(seeds, np.int64))
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, round_idx)
        # reveals bypass the async uplink sender: tiny frames, and the
        # round cannot advance until they land — FIFO with nothing
        self.send_message(msg)


class TASecureServerManager(FedAvgServerManager):
    """FedAvgServerManager with the mask-recovery state machine.

    Phases per round: ``uploads`` (the ordinary barrier / elastic
    timeout) -> when slots are missing and survivors >= t+1, ``recovery``
    (reveal requests out, replies folding in) -> aggregate. Below
    threshold, or on a reveal lost past the watchdog deadline, the round
    SHEDS: every lost slot is ledgered, the outcome metric counts it, and
    the round re-broadcasts (the wedge-fix path) so a recovered fleet
    re-converges instead of wedging."""

    def __init__(self, aggregator: TAAggregator, **kw):
        if kw.get("async_buffer_k") is not None:
            raise ValueError("the masked secure-aggregation tier needs "
                             "the synchronous cohort — async_buffer_k is "
                             "refused")
        if kw.get("delta_broadcast"):
            raise ValueError("delta_broadcast is not wired for the "
                             "masked secure-aggregation tier (uploads "
                             "prove no base version — run dense)")
        if kw.get("heartbeat_max_age_s") is not None:
            raise ValueError("heartbeat cohort admission is not wired for "
                             "the masked secure-aggregation tier (an "
                             "excluded slot's masks would orphan every "
                             "round) — rely on round_timeout_s recovery")
        super().__init__(aggregator, **kw)
        self._phase = "uploads"
        self._reveal: dict | None = None
        if not hasattr(self, "_last_secagg"):
            # crash recovery (_recover_in_flight, called from the base
            # __init__) may already have recorded a shed outcome here —
            # don't clobber it
            self._last_secagg: dict | None = None

    def register_message_receive_handlers(self):
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_REVEAL_SHARES,
            self.handle_message_reveal_shares)

    # ------------------------------------------------------------ recovery
    def _advance_round(self):
        """Route through mask recovery before the base aggregate: a full
        cohort decodes immediately; missing slots start the reveal phase
        (or shed below threshold). Caller holds _round_lock."""
        agg: TAAggregator = self.aggregator
        survivors = sorted(agg._round_slots)
        dead = [s for s in range(agg.worker_num) if s not in agg._round_slots]
        if not dead:
            agg.set_recovery(survivors, [], {})
            _perf.record_secagg_round("full")
            self._last_secagg = {"outcome": "full", "dead": []}
            super()._advance_round()
            return
        if len(survivors) < agg.secagg.recovery_min:
            self._shed_round(
                survivors, dead,
                f"{len(survivors)} survivors < recovery threshold "
                f"{agg.secagg.recovery_min}")
            return
        self._begin_recovery(survivors, dead)

    def _recover_in_flight(self, committed: int, replay) -> None:
        """Crash recovery × the secagg state machine (docs/ROBUSTNESS.md
        §Server crash recovery): the base recovery ledgers the accepted
        masked uploads as ``server_restart`` and re-dispatches the open
        round — which for the masked tier IS the shed-and-rebroadcast
        path (fresh boot = fresh fold state: ``_acc``/``_recovery``/
        ``_phase`` reset, clients re-mask for the re-broadcast round, so
        a half-revealed fold can never survive a restart). If the WAL
        shows a reveal was in flight, the dead slots it was recovering
        are additionally ledgered ``secagg_shed`` — the same verdict the
        live shed path records — and the outcome metric counts a shed."""
        super()._recover_in_flight(committed, replay)
        if replay is None or self._resume_round is None:
            return
        reveals = replay.since_last_commit("secagg_reveal")
        if not reveals:
            return
        rec = reveals[-1]
        dead = [int(s) for s in rec.get("dead", [])]
        ids = self.aggregator.client_sampling(self.round_idx)
        for slot in dead:
            self.aggregator.quarantine.record(
                self.round_idx, slot + 1, "secagg_shed",
                client=int(ids[slot]))
            _obs.record_update_rejected("secagg_shed")
        _perf.record_secagg_round("shed")
        _perf.record_secagg_dropped(len(dead))
        self._last_secagg = {"outcome": "shed", "dead": dead}
        log.error("secagg round %d SHED (server crashed mid-reveal): "
                  "lost slots %s ledgered — the resume probe re-runs the "
                  "round clean", self.round_idx, dead)

    def _begin_recovery(self, survivors: list[int], dead: list[int]) -> None:
        agg: TAAggregator = self.aggregator
        agg._frozen = True
        self._phase = "recovery"
        if self.wal is not None:
            # journal the reveal fan-out (fsync'd): a crash from here to
            # the fold must recover as a SHED round, never a half-reveal
            self.wal.append("secagg_reveal", sync=True,
                            round=int(self.round_idx),
                            survivors=[int(s) for s in survivors],
                            dead=[int(d) for d in dead])
        self._maybe_crash("reveal")
        self._reveal = {"survivors": survivors, "dead": dead,
                        "seeds": {}, "t0": time.perf_counter()}
        self._reveal_retried = False
        log.warning("secagg round %d: slots %s dropped — asking %d "
                    "survivors to reveal their pairwise seeds",
                    self.round_idx, dead, len(survivors))
        self._send_reveal_requests(survivors, dead)

    def _send_reveal_requests(self, survivors, dead) -> None:
        """Fan s2c_reveal to the listed survivors. Deterministic frames
        (round + dead set), so the watchdog retry re-sends byte-identical
        requests and the client cache answers them verbatim."""
        for slot in survivors:
            msg = Message(MyMessage.MSG_TYPE_S2C_REVEAL_REQUEST, self.rank,
                          slot + 1)
            msg.add_params(MyMessage.MSG_ARG_KEY_SECAGG_DEAD,
                           np.asarray(dead, np.int64))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            self.send_message(msg)

    def handle_message_reveal_shares(self, msg_params):
        with self._round_lock:
            if self._phase != "recovery" or self._reveal is None:
                _obs.record_stale_upload("stale")
                return
            if int(msg_params.get(MyMessage.MSG_ARG_KEY_ROUND,
                                  self.round_idx)) != self.round_idx:
                _obs.record_stale_upload("stale")
                return
            slot = int(msg_params[Message.MSG_ARG_KEY_SENDER]) - 1
            rv = self._reveal
            if slot not in rv["survivors"] or slot in rv["seeds"]:
                return  # unknown or duplicate reveal: exactly-once fold
            dead = [int(d) for d in np.asarray(
                msg_params[MyMessage.MSG_ARG_KEY_SECAGG_DEAD])]
            seeds = np.asarray(
                msg_params[MyMessage.MSG_ARG_KEY_SECAGG_PAIR_SEEDS],
                np.int64)
            if dead != rv["dead"] or len(seeds) != len(dead):
                log.warning("secagg: reveal from slot %d names dead set "
                            "%s != %s — dropped", slot, dead, rv["dead"])
                return
            rv["seeds"][slot] = {j: int(s) for j, s in zip(dead, seeds)}
            if len(rv["seeds"]) < len(rv["survivors"]):
                return
            # every survivor revealed: strip, decode, and run the base
            # round advance (aggregate -> eval -> ckpt -> next broadcast)
            dt = time.perf_counter() - rv["t0"]
            agg: TAAggregator = self.aggregator
            agg.set_recovery(rv["survivors"], rv["dead"], rv["seeds"])
            _perf.record_secagg_round("recovered")
            _perf.record_secagg_recovery_seconds(dt)
            self._last_secagg = {"outcome": "recovered",
                                 "dead": list(rv["dead"]),
                                 "recovery_s": round(dt, 6)}
            self._phase, self._reveal = "uploads", None
            FedAvgServerManager._advance_round(self)

    def _shed_round(self, survivors: list[int], dead: list[int],
                    why: str) -> None:
        """Below-threshold / reveal-lost: ledger every lost slot, count
        the outcome, re-broadcast the SAME round (fresh fault draws; a
        recovered fleet re-converges). Caller holds _round_lock."""
        agg: TAAggregator = self.aggregator
        ids = agg.client_sampling(self.round_idx)
        for slot in dead:
            agg.quarantine.record(self.round_idx, slot + 1, "secagg_shed",
                                  client=int(ids[slot]))
            _obs.record_update_rejected("secagg_shed")
        _perf.record_secagg_round("shed")
        _perf.record_secagg_dropped(len(dead))
        log.error("secagg round %d SHED (%s): lost slots %s ledgered — "
                  "re-broadcasting the round", self.round_idx, why, dead)
        self._phase, self._reveal = "uploads", None
        self._last_secagg = {"outcome": "shed", "dead": list(dead)}
        # the all-uploads-lost wedge-fix path: clear the elastic
        # undeliverable marks (round_idx is NOT advancing, so the reprobe
        # cadence can never trigger) and re-broadcast; _broadcast_model's
        # begin_round resets the masked fold for the fresh attempt
        self._undeliverable.clear()
        self._update_alive_gauge()
        self._broadcast_model(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                              agg.get_global_model_params())

    def on_timeout(self, idle_s: float):
        with self._round_lock:
            if self._phase == "recovery" and not self._finished.is_set():
                rv = self._reveal or {"survivors": [], "dead": [],
                                      "seeds": {}}
                missing = [s for s in rv["survivors"]
                           if s not in rv["seeds"]]
                if missing and not getattr(self, "_reveal_retried", True):
                    # one deterministic retry before shedding: the backoff
                    # IS the watchdog cadence (first fire retries, second
                    # sheds), the frames are byte-identical, and the
                    # client cache retransmits the same seeds verbatim
                    self._reveal_retried = True
                    log.warning(
                        "secagg round %d: reveal frames missing from "
                        "slots %s after %.1fs — retrying once",
                        self.round_idx, missing, idle_s)
                    self._send_reveal_requests(missing, rv["dead"])
                    return
                self._shed_round(
                    rv["survivors"], rv["dead"],
                    f"reveal frames lost from slots {missing} after "
                    f"{idle_s:.1f}s (post-retry)")
                return
        super().on_timeout(idle_s)

    def _round_record_extra(self) -> dict:
        extra = super()._round_record_extra()
        if self._last_secagg is not None:
            extra["secagg"] = dict(self._last_secagg)
        return extra


class TASecureEdgeManager(FedAvgEdgeManager):
    """Edge rank of the hierarchical masked tier (module docstring):
    folds its block's masked uploads mod p (the block's pair masks cancel
    HERE — workers drew them against block peers only), runs the tiered
    reveal recovery locally for in-block dead slots, and forwards ONE
    e2s_masked_agg frame carrying the unmasked int64 field partial.

    The edge watchdog arms at HALF the root deadline (the tiered
    contract): in-block recovery — including one deterministic reveal
    retry — resolves strictly before the root's own timeout would shed
    the whole block. Below ``recovery_min`` block survivors (or a reveal
    lost past the retry) the edge sheds its OWN block loudly: an empty
    partial whose dead list names every block slot, which the root
    ledgers ``secagg_shed`` while the other blocks' round proceeds."""

    def __init__(self, rank: int, topology, cfg: FedAvgConfig,
                 threshold_t=None, quant_scale=2**16,
                 defense_type: str = "none", norm_bound: float = 30.0,
                 secagg_max_abs: float = 4.0, backend: str = "LOOPBACK",
                 round_timeout_s: float | None = None, **kw):
        super().__init__(rank, topology, backend=backend,
                         round_timeout_s=round_timeout_s, robust=False,
                         **kw)
        self.cfg = cfg
        self.secagg = _secagg_config(cfg, threshold_t, quant_scale,
                                     defense_type, norm_bound,
                                     secagg_max_abs)
        if self.secagg.recovery_min > topology.block:
            raise ValueError(
                f"secagg recovery needs >= {self.secagg.recovery_min} "
                f"survivors, but an edge block holds only "
                f"{topology.block} slots — edge-local reveal could never "
                "succeed; lower threshold_t or enlarge the block")
        # masked block state (under self._lock; reset on every downlink)
        self._macc = None
        self._mslots: set[int] = set()
        self._mb_shares: dict[int, np.ndarray] = {}
        self._mextras: dict[int, list] = {}
        self._msamples: dict[int, float] = {}
        self._mreveal: dict | None = None

    def register_message_receive_handlers(self):
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_REVEAL_SHARES,
            self.handle_message_reveal_shares)

    def _handle_downlink(self, msg_type: str, msg_params) -> None:
        with self._lock:
            self._macc = None
            self._mslots = set()
            self._mb_shares = {}
            self._mextras = {}
            self._msamples = {}
            self._mreveal = None
        super()._handle_downlink(msg_type, msg_params)

    def _handle_child_upload(self, msg_params) -> None:
        """Fold one worker's [masked, b_shares, *extras] upload — the
        edge-tier twin of TAAggregator.add_local_trained_result, keyed by
        GLOBAL cohort slot so the forwarded frame needs no translation."""
        sender = int(msg_params[Message.MSG_ARG_KEY_SENDER])
        slot = self.topology.slot_of(sender)
        with self._lock:
            if self._round is None:
                return
            tag = msg_params.get(MyMessage.MSG_ARG_KEY_ROUND, self._round)
            if int(tag) != self._round:
                _obs.record_stale_upload("stale")
                log.warning("edge %d: drop stale masked upload from rank "
                            "%d (round %s, now %d)", self.edge_idx,
                            sender, tag, self._round)
                return
            if slot not in self._slots:
                _obs.record_stale_upload("unknown_rank")
                log.warning("edge %d: masked upload from rank %d outside "
                            "this block (slots %s)", self.edge_idx,
                            sender, self._slots)
                return
            if self._forwarded or slot in self._mslots:
                _obs.record_stale_upload("stale")
                return  # chaos duplicate / late: exactly-once folding
            if self._mreveal is not None:
                # recovery in flight: the block's survivor set (and the
                # reveal requests out for it) is FIXED — same freeze rule
                # as the flat aggregator's _frozen
                _obs.record_stale_upload("stale")
                log.warning("edge %d: dropping late upload from slot %d "
                            "— block mask recovery already in flight",
                            self.edge_idx, slot)
                return
            leaves = list(msg_params[MyMessage.MSG_ARG_KEY_MODEL_PARAMS])
            self._macc = sa.fold_masked(self._macc, leaves[0],
                                        self.secagg.p)
            self._mslots.add(slot)
            self._mb_shares[slot] = np.asarray(leaves[1], np.int64)
            self._mextras[slot] = list(leaves[2:])
            self._msamples[slot] = float(
                msg_params[MyMessage.MSG_ARG_KEY_NUM_SAMPLES])
            if len(self._mslots) == len(self._slots):
                self._finish_block()

    # ------------------------------------------------------ block recovery
    def _finish_block(self) -> None:
        """Full block -> unmask and forward; dead slots -> edge-local
        reveal (or shed below threshold). Caller holds _lock."""
        survivors = sorted(self._mslots)
        dead = [s for s in self._slots if s not in self._mslots]
        if not dead:
            field = self._unmask_block(survivors, [], {})
            self._send_masked_frame(field, survivors, [], "full", None)
            return
        if len(survivors) < self.secagg.recovery_min:
            self._shed_block(
                f"{len(survivors)} block survivors < recovery threshold "
                f"{self.secagg.recovery_min}")
            return
        self._begin_block_recovery(survivors, dead)

    def _unmask_block(self, survivors, dead, reveals) -> np.ndarray:
        """Strip the block's masks, staying in GF(p): self-mask seeds
        reconstructed from the BLOCK survivors' share entries (>= t+1 by
        the constructor guard), orphaned pairs from the reveals — every
        pair in a block-scoped upload is in-block, so block-local reveals
        cover every orphan. Caller holds _lock."""
        self_seeds = {
            i: sa.recover_self_seed(
                survivors, self._mb_shares[i][survivors],
                self.secagg.threshold_t, self.secagg.p)
            for i in survivors}
        return sa.unmask_partial(self._macc, survivors, dead, self_seeds,
                                 reveals, self.secagg)

    def _begin_block_recovery(self, survivors, dead) -> None:
        self._mreveal = {"survivors": list(survivors), "dead": list(dead),
                         "seeds": {}, "t0": time.perf_counter(),
                         "retried": False}
        log.warning("edge %d round %d: block slots %s dropped — asking "
                    "%d block survivors to reveal their pairwise seeds",
                    self.edge_idx, self._round, dead, len(survivors))
        self._send_block_reveals(survivors, dead)

    def _send_block_reveals(self, survivors, dead) -> None:
        """s2c_reveal to the listed block survivors' worker ranks, naming
        GLOBAL dead slot ids — byte-identical on retry, so the client
        reveal cache retransmits the same seeds verbatim."""
        for slot in survivors:
            msg = Message(MyMessage.MSG_TYPE_S2C_REVEAL_REQUEST, self.rank,
                          self.topology.worker_rank(slot))
            msg.add_params(MyMessage.MSG_ARG_KEY_SECAGG_DEAD,
                           np.asarray(dead, np.int64))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self._round)
            self.send_message(msg)

    def handle_message_reveal_shares(self, msg_params) -> None:
        with self._lock:
            rv = self._mreveal
            if rv is None or self._forwarded:
                _obs.record_stale_upload("stale")
                return
            if int(msg_params.get(MyMessage.MSG_ARG_KEY_ROUND,
                                  self._round)) != self._round:
                _obs.record_stale_upload("stale")
                return
            slot = self.topology.slot_of(
                int(msg_params[Message.MSG_ARG_KEY_SENDER]))
            if slot not in rv["survivors"] or slot in rv["seeds"]:
                return  # unknown or duplicate reveal: exactly-once fold
            dead = [int(d) for d in np.asarray(
                msg_params[MyMessage.MSG_ARG_KEY_SECAGG_DEAD])]
            seeds = np.asarray(
                msg_params[MyMessage.MSG_ARG_KEY_SECAGG_PAIR_SEEDS],
                np.int64)
            if dead != rv["dead"] or len(seeds) != len(dead):
                log.warning("edge %d: reveal from slot %d names dead set "
                            "%s != %s — dropped", self.edge_idx, slot,
                            dead, rv["dead"])
                return
            rv["seeds"][slot] = {j: int(s) for j, s in zip(dead, seeds)}
            if len(rv["seeds"]) < len(rv["survivors"]):
                return
            dt = time.perf_counter() - rv["t0"]
            field = self._unmask_block(rv["survivors"], rv["dead"],
                                       rv["seeds"])
            self._mreveal = None
            self._send_masked_frame(field, rv["survivors"], rv["dead"],
                                    "recovered", dt)

    def _shed_block(self, why: str) -> None:
        """Below-threshold / reveal-lost: forward an EMPTY partial whose
        dead list names every block slot — the root sheds exactly this
        block (ledgered secagg_shed there) and the other blocks' round
        proceeds. Caller holds _lock."""
        log.error("edge %d round %d block SHED (%s): forwarding an empty "
                  "partial — the root ledgers slots %s secagg_shed",
                  self.edge_idx, self._round, why, list(self._slots))
        self._mreveal = None
        self._send_masked_frame(None, [], list(self._slots), "shed", None)

    def _send_masked_frame(self, field, survivors, dead, outcome,
                           recovery_s) -> None:
        """The ONE per-round uplink (root ingress stays O(edges)): the
        unmasked field partial + the block's survivor/dead slots, sample
        counts, plaintext extras, and how the block decoded. Caller holds
        _lock."""
        msg = Message(MyMessage.MSG_TYPE_E2S_SEND_MASKED_AGG_TO_SERVER,
                      self.rank, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_FIELD_SUM,
                       np.zeros(0, np.int64) if field is None
                       else np.asarray(field, np.int64))
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_SURVIVORS,
                       [int(s) for s in survivors])
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_DEAD,
                       [int(d) for d in dead])
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_SLOT_SAMPLES,
                       [float(self._msamples[s]) for s in survivors])
        msg.add_params(MyMessage.MSG_ARG_KEY_EDGE_EXTRAS,
                       [self._mextras[s] for s in survivors])
        msg.add_params(MyMessage.MSG_ARG_KEY_SECAGG_OUTCOME, str(outcome))
        if recovery_s is not None:
            msg.add_params(MyMessage.MSG_ARG_KEY_SECAGG_RECOVERY_S,
                           float(recovery_s))
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self._round)
        self._forwarded = True
        self.send_message(msg)

    def on_timeout(self, idle_s: float) -> None:
        """Tiered recovery clock: uploads stalled -> run the block
        decision (reveal or shed); a reveal stalled -> one deterministic
        retry (the watchdog cadence IS the backoff), then shed. A block
        with NO uploads waits — the root watchdog owns that recovery."""
        with self._lock:
            if (self._round is None or self._forwarded
                    or self.round_timeout_s is None):
                return
            rv = self._mreveal
            if rv is not None:
                missing = [s for s in rv["survivors"]
                           if s not in rv["seeds"]]
                if missing and not rv["retried"]:
                    rv["retried"] = True
                    log.warning("edge %d round %d: reveal frames missing "
                                "from slots %s after %.1fs — retrying "
                                "once", self.edge_idx, self._round,
                                missing, idle_s)
                    self._send_block_reveals(missing, rv["dead"])
                    return
                self._shed_block(f"reveal frames lost from slots "
                                 f"{missing} after {idle_s:.1f}s "
                                 "(post-retry)")
                return
            if not self._mslots:
                log.error("edge %d: round %s stalled %.1fs with no masked "
                          "uploads — waiting (root watchdog owns "
                          "recovery)", self.edge_idx, self._round, idle_s)
                return
            self._finish_block()


class HierTAAggregator(TAAggregator):
    """Root-side aggregator of the hierarchical masked tier: slots are
    EDGES (the barrier counts E frames), but the fold state stays keyed
    by GLOBAL cohort slot — each e2s_masked_agg frame's unmasked field
    partial is one more streaming add mod p, and ``aggregate`` decodes
    ONCE over the union of surviving slots. Mod-p addition is exact and
    associative, so the result is bitwise the flat masked aggregate."""

    def __init__(self, dataset, task, cfg: FedAvgConfig, topology,
                 threshold_t=None, quant_scale=2**16,
                 defense_type: str = "none", norm_bound: float = 30.0,
                 noise_multiplier: float = 1.0,
                 secagg_max_abs: float = 4.0, fused_ingest: bool = False):
        if cfg.client_num_per_round != topology.workers:
            raise ValueError(
                f"client_num_per_round={cfg.client_num_per_round} != "
                f"topology workers={topology.workers}")
        super().__init__(dataset, task, cfg, worker_num=topology.edges,
                         threshold_t=threshold_t, quant_scale=quant_scale,
                         defense_type=defense_type, norm_bound=norm_bound,
                         noise_multiplier=noise_multiplier,
                         secagg_max_abs=secagg_max_abs,
                         fused_ingest=fused_ingest)
        self.topology = topology
        if self.secagg.recovery_min > topology.block:
            raise ValueError(
                f"secagg recovery needs >= {self.secagg.recovery_min} "
                f"survivors, but an edge block holds only "
                f"{topology.block} slots — edge-local reveal could never "
                "succeed; lower threshold_t or enlarge the block")
        self.fanin_history: list[int] = []
        # edge idx -> {survivors, dead, outcome, recovery_s} for the
        # round's secagg record + the tiered ledger attribution
        self._edge_frames: dict[int, dict] = {}

    def begin_round(self, round_idx: int) -> None:
        super().begin_round(round_idx)
        self._edge_frames = {}

    def add_edge_masked_result(self, edge_idx: int, field_sum, survivors,
                               dead, slot_samples, extras, outcome: str,
                               recovery_s=None,
                               round_idx: int | None = None) -> None:
        """Slot one edge's e2s_masked_agg frame: fold the unmasked field
        partial mod p, stage the block's per-slot samples/extras under
        their GLOBAL slot ids. Same stale/unknown/duplicate rejection
        semantics as the per-worker path."""
        edge_idx = int(edge_idx)
        if edge_idx not in self.flag_client_model_uploaded:
            _obs.record_stale_upload("unknown_rank")
            log.warning("reject masked partial for unknown edge index %s "
                        "(edges 0..%d)", edge_idx, self.worker_num - 1)
            return
        if round_idx is not None and int(round_idx) != self.current_round:
            _obs.record_stale_upload("stale")
            log.warning("reject out-of-round masked partial from edge %s "
                        "(tagged round %s, current %d)", edge_idx,
                        round_idx, self.current_round)
            return
        if self.flag_client_model_uploaded.get(edge_idx):
            _obs.record_stale_upload("stale")
            log.warning("drop duplicate masked partial from edge %s",
                        edge_idx)
            return
        survivors = [int(s) for s in survivors]
        if survivors:
            self._acc = self._fold(self._acc,
                                   np.asarray(field_sum, np.int64),
                                   self.secagg.p)
            for s, n, ex in zip(survivors, slot_samples, extras):
                self._round_slots.add(s)
                self.sample_num_dict[s] = float(n)
                self._extras[s] = list(ex)
        self._edge_frames[edge_idx] = {
            "survivors": survivors, "dead": [int(d) for d in dead],
            "outcome": str(outcome),
            "recovery_s": None if recovery_s is None else float(recovery_s)}
        self.flag_client_model_uploaded[edge_idx] = True

    def aggregate(self):
        """Ledger the tiered outcomes (a missing/shed edge's whole block
        -> secagg_shed; an edge-recovered block's dead slots ->
        secagg_dropout — the SAME verdicts the flat tier records for the
        same fates), then decode the folded field partials ONCE and run
        the shared decode-side tail."""
        t0 = time.perf_counter()
        ids = self.client_sampling(self.current_round)
        missing = [e for e in range(self.topology.edges)
                   if e not in self._edge_frames]
        shed_slots: list[int] = []
        drop_slots: list[int] = []
        for e in missing:
            shed_slots.extend(self.topology.slots_of_edge(e))
        for fr in self._edge_frames.values():
            (shed_slots if fr["outcome"] == "shed"
             else drop_slots).extend(fr["dead"])
        for s in sorted(shed_slots):
            self.quarantine.record(self.current_round, s + 1,
                                   "secagg_shed", client=int(ids[s]))
            _obs.record_update_rejected("secagg_shed")
        for s in sorted(drop_slots):
            self.quarantine.record(self.current_round, s + 1,
                                   "secagg_dropout", client=int(ids[s]))
            _obs.record_update_rejected("secagg_dropout")
        if shed_slots or drop_slots:
            _perf.record_secagg_dropped(len(shed_slots) + len(drop_slots))
        if missing:
            log.warning("hier secagg round %d: edge frame(s) %s lost — "
                        "their blocks shed (ledgered secagg_shed)",
                        self.current_round, missing)
        self.fanin_history.append(len(self._edge_frames))
        survivors = sorted(self._round_slots)
        if not survivors:
            log.warning("hier secagg round %d: every block lost — "
                        "keeping the current global model",
                        self.current_round)
            self._acc, self._recovery = None, None
            self._round_slots, self._b_shares, self._extras = set(), {}, {}
            self.sample_num_dict.clear()
            return pack_pytree(self.net)
        vec_sum = sa.field_decode_sum(self._acc, self.secagg)
        return self._finish_aggregate(vec_sum, survivors, t0)


class HierTASecureServerManager(FedAvgServerManager):
    """Root manager of the hierarchical masked tier: broadcasts one frame
    per EDGE, advances on E e2s_masked_agg frames. The tiered recovery
    lives at the edges — the root never sees a reveal; its only dropout
    duty is the base elastic watchdog, whose partial advance sheds a
    whole lost edge's block (HierTAAggregator ledgers it). Cannot subclass
    HierFedAvgServerManager (its type check demands the dense hier
    aggregator); the shared behavior is all in FedAvgServerManager."""

    def __init__(self, aggregator: HierTAAggregator, topology=None, **kw):
        if not isinstance(aggregator, HierTAAggregator):
            raise TypeError("HierTASecureServerManager needs a "
                            "HierTAAggregator")
        self.topology = topology or aggregator.topology
        for flag, name in ((kw.get("async_buffer_k"), "async_buffer_k"),
                           (kw.get("delta_broadcast"), "delta_broadcast"),
                           (kw.get("heartbeat_max_age_s"),
                            "heartbeat_max_age_s")):
            if flag:
                raise ValueError(
                    f"{name} is not wired through the masked edge tier — "
                    "run the flat topology for that mode")
        super().__init__(aggregator, **kw)
        if not hasattr(self, "_last_secagg"):
            self._last_secagg: dict | None = None

    def _validate_world_size(self, size: int) -> None:
        if size != self.topology.world_size:
            raise ValueError(
                f"world size {size} != 1 + {self.topology.edges} edges + "
                f"{self.topology.workers} workers")

    def register_message_receive_handlers(self):
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_E2S_SEND_MASKED_AGG_TO_SERVER,
            self.handle_message_masked_partial)

    def _broadcast_model(self, msg_type: str, global_params) -> None:
        """One frame per EDGE (fan-out O(edges)), mirroring the dense
        hier root: model + the edge block's client assignments + round."""
        from fedml_tpu.comm.message import codec_roundtrip

        topo = self.topology
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        self._round_ids = [int(c) for c in client_indexes]
        self.aggregator.begin_round(self.round_idx)
        self._bcast_leaves = codec_roundtrip(global_params)
        self._stash_version(self.round_idx, self._bcast_leaves)
        for e in range(topo.edges):
            msg = Message(msg_type, self.rank, topo.edge_rank(e))
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                           global_params)
            msg.add_params(
                MyMessage.MSG_ARG_KEY_CHILD_CLIENTS,
                [int(client_indexes[s]) for s in topo.slots_of_edge(e)])
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            self.send_message(msg)

    def handle_message_masked_partial(self, msg_params) -> None:
        with self._round_lock:
            sender = int(msg_params[Message.MSG_ARG_KEY_SENDER])
            msg_round = int(msg_params.get(MyMessage.MSG_ARG_KEY_ROUND,
                                           self.round_idx))
            if msg_round != self.round_idx:
                _obs.record_stale_upload("stale")
                log.warning("drop stale masked partial from rank %d "
                            "(round %s, now %d)", sender, msg_round,
                            self.round_idx)
                return
            rs = msg_params.get(MyMessage.MSG_ARG_KEY_SECAGG_RECOVERY_S)
            self.aggregator.add_edge_masked_result(
                sender - 1,
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_FIELD_SUM],
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_SURVIVORS],
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_DEAD],
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_SLOT_SAMPLES],
                msg_params[MyMessage.MSG_ARG_KEY_EDGE_EXTRAS],
                str(msg_params[MyMessage.MSG_ARG_KEY_SECAGG_OUTCOME]),
                recovery_s=None if rs is None else float(rs),
                round_idx=msg_round)
            if self.aggregator.check_whether_all_receive():
                self._advance_round()

    def _advance_round(self):
        """Fix the round's secagg verdict (for the metric + the round
        record) from the edge frames BEFORE the base advance consumes
        them: any missing or shed block makes the round a shed; recovered
        blocks alone make it recovered. Caller holds _round_lock."""
        agg: HierTAAggregator = self.aggregator
        frames = agg._edge_frames
        missing = [e for e in range(self.topology.edges)
                   if e not in frames]
        dead = sorted(
            {s for e in missing for s in self.topology.slots_of_edge(e)}
            | {int(d) for fr in frames.values() for d in fr["dead"]})
        outcomes = [fr["outcome"] for fr in frames.values()]
        if missing or "shed" in outcomes:
            outcome = "shed"
        elif dead:
            outcome = "recovered"
        else:
            outcome = "full"
        _perf.record_secagg_round(outcome)
        self._last_secagg = {"outcome": outcome, "dead": dead}
        rts = [fr["recovery_s"] for fr in frames.values()
               if fr["recovery_s"] is not None]
        if rts:
            self._last_secagg["recovery_s"] = round(max(rts), 6)
            _perf.record_secagg_recovery_seconds(max(rts))
        super()._advance_round()

    def _round_record_extra(self) -> dict:
        extra = super()._round_record_extra()
        hist = self.aggregator.fanin_history
        extra["hier"] = {"edges": self.topology.edges,
                         "block": self.topology.block,
                         "fan_in": hist[-1] if hist else 0}
        if self._last_secagg is not None:
            extra["secagg"] = dict(self._last_secagg)
        return extra


def run_simulated(dataset, task, cfg: FedAvgConfig, backend="LOOPBACK",
                  job_id="turboagg-sim", base_port=50000, threshold_t=None,
                  quant_scale=2**16, defense_type: str = "none",
                  norm_bound: float = 30.0, noise_multiplier: float = 1.0,
                  secagg_max_abs: float = 4.0, chaos_plan=None,
                  round_timeout_s: float | None = None, telemetry=None,
                  ckpt_dir: str | None = None, n_shares=None,
                  edges: int | None = None, fused_ingest: bool = False):
    """All ranks as threads (mpirun-on-localhost analogue); returns the
    aggregator with .net/.history. ``chaos_plan`` + ``round_timeout_s``
    arm the dropout-recovery scenario deterministically; ``defense_type=
    'dp'`` runs accounted DP on the masked path (privacy block on every
    round record). ``edges=E`` runs the hierarchical masked tier (module
    docstring) — bitwise the flat aggregate; ``fused_ingest`` keeps the
    fold accumulator device-resident (also bitwise)."""
    if edges:
        return _run_simulated_tree(
            dataset, task, cfg, backend, job_id, base_port, threshold_t,
            quant_scale, defense_type, norm_bound, noise_multiplier,
            secagg_max_abs, chaos_plan, round_timeout_s, telemetry,
            ckpt_dir, int(edges), fused_ingest)
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port)
    from fedml_tpu import chaos as _chaos

    if chaos_plan is not None:  # None must not clobber an installed plan
        _chaos.install_plan(chaos_plan)
    try:
        # rank-0 crash rules are supervised server restarts (docs/
        # ROBUSTNESS.md §Server crash recovery) — the masked tier rides
        # the same driver as the fedavg runtime: kill at the scheduled
        # point, recover through checkpoint + WAL, shed any half-revealed
        # round (never a half-recovered fold)
        active = _chaos.active_plan()
        crash_points = (active.server_crash_points()
                        if active is not None else [])
        if crash_points and ckpt_dir is None:
            raise ValueError(
                "a chaos crash rule naming rank 0 (server restart) needs "
                "ckpt_dir= — recovery replays checkpoint + WAL")

        def build_server():
            agg = TAAggregator(
                dataset, task, cfg, worker_num=size - 1,
                threshold_t=threshold_t, quant_scale=quant_scale,
                defense_type=defense_type, norm_bound=norm_bound,
                noise_multiplier=noise_multiplier,
                secagg_max_abs=secagg_max_abs, n_shares=n_shares,
                fused_ingest=fused_ingest)
            return TASecureServerManager(
                agg, rank=0, size=size, backend=backend,
                round_timeout_s=round_timeout_s, telemetry=telemetry,
                ckpt_dir=ckpt_dir, **kw)

        server = build_server()
        aggregator = server.aggregator
        clients = []
        for r in range(1, size):
            trainer = SecureTrainer(
                r, dataset, task, cfg, threshold_t=threshold_t,
                quant_scale=quant_scale, defense_type=defense_type,
                norm_bound=norm_bound, secagg_max_abs=secagg_max_abs)
            clients.append(TASecureClientManager(
                trainer, rank=r, size=size, backend=backend, **kw))
        if crash_points:
            from fedml_tpu.distributed.fedavg.api import (
                run_supervised_simulated,
            )

            server = run_supervised_simulated(server, clients,
                                              crash_points, build_server)
            aggregator = server.aggregator
        else:
            launch_simulated(server, clients)
    finally:
        if chaos_plan is not None:
            _chaos.install_plan(None)
    return aggregator


def _run_simulated_tree(dataset, task, cfg: FedAvgConfig, backend, job_id,
                        base_port, threshold_t, quant_scale, defense_type,
                        norm_bound, noise_multiplier, secagg_max_abs,
                        chaos_plan, round_timeout_s, telemetry, ckpt_dir,
                        edges: int, fused_ingest: bool):
    """The 2-tier masked runtime: 1 root + E edges + W workers as
    threads. Workers mask against their edge block's peers (global slot
    ids — masks cancel at the edge); cohort/slot/client assignments
    coincide with the flat runtime round-for-round, so tree ≡ flat is
    bitwise (model bits AND ledger — the tests pin it)."""
    topo = EdgeTopology(edges=edges, workers=cfg.client_num_per_round)
    kw = backend_kwargs(backend, job_id, base_port)
    from fedml_tpu import chaos as _chaos

    if chaos_plan is not None:
        _chaos.install_plan(chaos_plan)
    try:
        active = _chaos.active_plan()
        crash_points = (active.server_crash_points()
                        if active is not None else [])
        if crash_points and ckpt_dir is None:
            raise ValueError(
                "a chaos crash rule naming rank 0 (server restart) needs "
                "ckpt_dir= — recovery replays checkpoint + WAL")

        def build_server():
            agg = HierTAAggregator(
                dataset, task, cfg, topo, threshold_t=threshold_t,
                quant_scale=quant_scale, defense_type=defense_type,
                norm_bound=norm_bound, noise_multiplier=noise_multiplier,
                secagg_max_abs=secagg_max_abs, fused_ingest=fused_ingest)
            return HierTASecureServerManager(
                agg, rank=0, size=topo.world_size, backend=backend,
                round_timeout_s=round_timeout_s, telemetry=telemetry,
                ckpt_dir=ckpt_dir, **kw)

        server = build_server()
        aggregator = server.aggregator
        # edge watchdogs at HALF the root deadline (the tiered contract:
        # in-block reveal recovery — including its one retry — resolves
        # strictly before the root's own timeout sheds the whole block)
        edge_timeout = (round_timeout_s / 2.0
                        if round_timeout_s is not None else None)
        peers = [
            TASecureEdgeManager(
                topo.edge_rank(e), topo, cfg, threshold_t=threshold_t,
                quant_scale=quant_scale, defense_type=defense_type,
                norm_bound=norm_bound, secagg_max_abs=secagg_max_abs,
                backend=backend, round_timeout_s=edge_timeout, **kw)
            for e in range(topo.edges)
        ]
        for slot in range(topo.workers):
            rank = topo.worker_rank(slot)
            trainer = SecureTrainer(
                rank, dataset, task, cfg, threshold_t=threshold_t,
                quant_scale=quant_scale, defense_type=defense_type,
                norm_bound=norm_bound, secagg_max_abs=secagg_max_abs,
                slot=slot,
                peers=list(topo.slots_of_edge(topo.edge_of_slot(slot))))
            peers.append(TASecureClientManager(
                trainer, rank=rank, size=topo.world_size, backend=backend,
                server_rank=topo.edge_rank(topo.edge_of_slot(slot)), **kw))
        if crash_points:
            from fedml_tpu.distributed.fedavg.api import (
                run_supervised_simulated,
            )

            server = run_supervised_simulated(server, peers, crash_points,
                                              build_server)
            aggregator = server.aggregator
        else:
            launch_simulated(server, peers)
    finally:
        if chaos_plan is not None:
            _chaos.install_plan(None)
    return aggregator
