"""Distributed TurboAggregate — dropout-tolerant masked secure aggregation.

The original mirror of fedml_api/distributed/turboaggregate/ shipped whole
Shamir share MATRICES per client and died the moment any client dropped
mid-round. This tier is the SecAgg-mold replacement (core/secure_agg.py,
docs/ROBUSTNESS.md §Secure aggregation):

- clients upload ONE masked field vector (their weighted update quantized
  into GF(2^31-1) plus cancelling pairwise masks and a Shamir-shared
  self-mask) — the server never sees a cleartext update, and its
  per-upload cost is a single streaming add mod p (``fold_masked``);
- with ``round_timeout_s`` armed, clients that crash/partition inside the
  deadline degrade the round instead of wedging it: the server asks each
  survivor for the pairwise seeds of exactly the dead slots
  (``s2c_reveal``/``c2s_reveal`` frames), strips the orphaned masks and
  the survivors' self-masks, and lands the EXACT elastic partial
  aggregate (survivor reweighting, sample-weight exact vs a numpy
  oracle); below ``threshold_t + 1`` survivors — or with a reveal lost
  past the deadline — the round sheds loudly: every lost slot is
  ledgered, ``fed_secagg_rounds_total{outcome="shed"}`` counts it, and
  the round re-broadcasts (the all-uploads-lost wedge-fix path) so a
  recovered fleet re-converges;
- ``defense_type='dp'`` runs accounted DP-FedAvg ON the masked path:
  clients clip their round delta to C before masking, the server
  calibrates Gaussian noise ``z*C/m`` over the REALIZED survivor count m,
  and every round record carries the ``privacy`` block (ε@δ, q, z, C,
  cumulative RDP — core/privacy.privacy_block). DP state (RDP totals +
  noise RNG) rides the server checkpoint, so resume neither under-reports
  ε nor replays noise keys.

Replay is bit-for-bit: every mask seed derives from the session seed via
sha256 (core/secure_agg.derive_secret — the fedlint determinism
discipline), so a chaos run's masked aggregates, ledger, and recovery
frames replay exactly.
"""

from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgConfig
from fedml_tpu.collectives import finite_field as ff
from fedml_tpu.comm.message import Message, pack_pytree
from fedml_tpu.core import secure_agg as sa
from fedml_tpu.core.local import NetState
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.client_manager import FedAvgClientManager
from fedml_tpu.distributed.fedavg.message_define import MyMessage
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.distributed.fedavg.trainer import DistributedTrainer
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated
from fedml_tpu.obs import comm_instrument as _obs
from fedml_tpu.obs import perf_instrument as _perf
from fedml_tpu.utils.tree import (tree_unvectorize, tree_vectorize,
                                  tree_weighted_mean)

log = logging.getLogger("fedml_tpu.distributed.fedavg")


def _batch_cap(dataset, cfg: FedAvgConfig) -> int:
    """The trainer's num_batches formula (trainer.num_batches_for — ONE
    definition) as a sample cap: the server must compute the SAME
    per-client cap to reproduce the deterministic cohort weight total
    (sample counts are public; the masked sum is not)."""
    from fedml_tpu.distributed.fedavg.trainer import num_batches_for

    max_count = max(len(v) for v in dataset.train_idx_map.values())
    return num_batches_for(max_count, cfg) * cfg.batch_size


def cohort_sample_counts(round_idx: int, cfg: FedAvgConfig, dataset,
                         cap: int) -> tuple[np.ndarray, list[int]]:
    """(sampled client ids, per-slot sample counts) — computable by every
    party from the deterministic sampler, which is what lets clients
    pre-normalize their weights without a weight-exchange phase."""
    from fedml_tpu.core.sampling import sample_clients

    ids = sample_clients(round_idx, cfg.client_num_in_total,
                         cfg.client_num_per_round, cfg.seed)
    counts = [min(len(dataset.train_idx_map[int(i)]), cap) for i in ids]
    return ids, counts


def _secagg_config(cfg: FedAvgConfig, threshold_t: int | None,
                   quant_scale: float, defense_type: str,
                   norm_bound: float,
                   secagg_max_abs: float) -> sa.SecAggConfig:
    """One construction rule for every party: DP mode's clip bound IS the
    capacity promise (||delta||_2 <= C bounds every coordinate); the
    weighted path promises ``secagg_max_abs`` and enforces it at mask
    time. ``threshold_t=None`` adapts to the cohort (min(2, K-1) — a
    2-client cohort cannot carry t=2); an EXPLICIT t out of range stays
    a loud error. Raises at construction when the cohort would wrap
    GF(p)."""
    if threshold_t is None:
        threshold_t = sa.default_threshold_t(cfg.client_num_per_round)
    max_abs = float(norm_bound) if defense_type == "dp" \
        else float(secagg_max_abs)
    return sa.SecAggConfig(cohort=cfg.client_num_per_round,
                           threshold_t=threshold_t,
                           quant_scale=quant_scale, max_abs=max_abs)


class SecureTrainer(DistributedTrainer):
    """DistributedTrainer whose wire format is [masked_vec, b_shares,
    *extra_leaves] — the update never leaves the client unmasked."""

    def __init__(self, client_rank, dataset, task, cfg, threshold_t=None,
                 quant_scale=2**16, defense_type: str = "none",
                 norm_bound: float = 30.0, secagg_max_abs: float = 4.0,
                 n_shares=None):
        from fedml_tpu.core.client_source import ClientDataSource

        if isinstance(dataset, ClientDataSource):
            raise ValueError(
                "the masked secure-aggregation tier is cross-silo: it "
                "needs every cohort member's public sample count "
                "(train_idx_map) for the pre-normalized weights — "
                "streamed ClientDataSources are refused")
        super().__init__(client_rank, dataset, task, cfg)
        if n_shares is not None:
            log.debug("SecureTrainer: n_shares is ignored — self-mask "
                      "seeds are Shamir-shared across the whole cohort")
        # cohort SLOT (stable per rank) — not the per-round dataset client
        # id the server re-assigns via CLIENT_INDEX
        self.slot = client_rank - 1
        self.defense_type = defense_type
        self.norm_bound = float(norm_bound)
        self.secagg = _secagg_config(cfg, threshold_t, quant_scale,
                                     defense_type, norm_bound,
                                     secagg_max_abs)

    def _round_weight(self, round_idx: int, n: int) -> float:
        """This client's n_k / sum_cohort(n_j), from the public sampler —
        pre-normalized so encoded field values stay inside the capacity
        promise (an n_k-scaled upload would burn mod-p headroom and wrap
        silently at scale)."""
        _, counts = cohort_sample_counts(round_idx, self.cfg, self.dataset,
                                         _batch_cap(self.dataset, self.cfg))
        return n / max(sum(counts), 1)

    def reveal_pair_seeds(self, round_idx: int,
                          dead_slots: list[int]) -> list[int]:
        """The recovery reveal: this survivor's pairwise seeds for exactly
        the DEAD slots (each masks nothing once the dead contribution is
        gone) — never a seed for a live pair, never the self-mask seed."""
        sk = sa.secret_key(self.cfg.seed, round_idx, self.slot,
                           self.secagg.p)
        pks = sa.public_keys(self.cfg.seed, round_idx, self.secagg.cohort,
                             self.secagg.p)
        return [sa.pair_seed(sk, pks[int(j)], self.secagg.p)
                for j in dead_slots]

    def train(self, round_idx: int):
        if self.defense_type == "dp":
            # snapshot the broadcast BEFORE the fit overwrites self.net:
            # the clipped ROUND DELTA is what gets masked
            global_vec = np.asarray(tree_vectorize(self.net.params),
                                    np.float64)
        n = self.fit(round_idx)  # self.net now holds the local fit
        if self.defense_type == "dp":
            # clip the ROUND DELTA to the L2 ball C, mask unweighted: the
            # server divides by the realized survivor count and the noise
            # z*C/m assumes exactly this sensitivity
            vec = np.asarray(tree_vectorize(self.net.params),
                             np.float64) - global_vec
            nrm = float(np.linalg.norm(vec))
            if nrm > self.norm_bound:
                vec = vec * (self.norm_bound / nrm)
            weight = 1.0
        else:
            vec = np.asarray(tree_vectorize(self.net.params), np.float64)
            weight = self._round_weight(round_idx, n)
        # mask_update enforces the capacity promise (max_abs) for every
        # engine — a coordinate past it would wrap the cohort sum
        masked = sa.mask_update(vec, weight, self.slot, self.cfg.seed,
                                round_idx, self.secagg)
        b_shares = sa.self_mask_shares(self.cfg.seed, round_idx, self.slot,
                                       self.secagg)
        extras = pack_pytree(self.net.extra)
        return [masked, b_shares] + extras, n


class TAAggregator(FedAvgAggregator):
    """Folds masked uploads mod p (one add per arrival); decodes only the
    survivor SUM after mask recovery."""

    # masked vectors are int64 host math (mod-p numpy) — device staging at
    # arrival would buy nothing and jnp would truncate the field elements
    _stage_uploads_on_arrival = False

    def __init__(self, dataset, task, cfg: FedAvgConfig, worker_num: int,
                 threshold_t=None, quant_scale=2**16,
                 defense_type: str = "none",  # 'none' | 'dp'
                 norm_bound: float = 30.0, noise_multiplier: float = 1.0,
                 secagg_max_abs: float = 4.0, n_shares=None):
        from fedml_tpu.core.client_source import ClientDataSource

        if isinstance(dataset, ClientDataSource):
            raise ValueError(
                "the masked secure-aggregation tier is cross-silo: "
                "streamed ClientDataSources are refused (public cohort "
                "sample counts need train_idx_map)")
        super().__init__(dataset, task, cfg, worker_num)
        if defense_type not in ("none", "dp"):
            raise ValueError(f"unknown defense_type {defense_type!r} for "
                             "the secure-aggregation tier ('none' | 'dp')")
        # capacity guard at CONSTRUCTION (collectives/finite_field.py):
        # K terms * 2 * quant_scale * max_abs must stay inside GF(p)
        self.secagg = _secagg_config(cfg, threshold_t, quant_scale,
                                     defense_type, norm_bound,
                                     secagg_max_abs)
        self.quant_scale = float(quant_scale)
        self.defense_type = defense_type
        self.accountant = None
        self._privacy_cache = None
        if defense_type == "dp":
            from fedml_tpu.core.privacy import DPAccountant

            if noise_multiplier <= 0:
                raise ValueError("defense_type='dp' needs noise_multiplier"
                                 f" > 0, got {noise_multiplier}")
            self.accountant = DPAccountant()
            self._dp_z, self._dp_C = float(noise_multiplier), float(norm_bound)
            self._noise_rng = jax.random.PRNGKey(cfg.seed + 7)
        _perf.ensure_secagg_families()
        # per-round masked-fold state (begin_round resets; _frozen parks
        # the fold while a recovery phase is in flight so a late upload
        # cannot corrupt the already-fixed survivor sum)
        self._acc = None
        self._round_slots: set[int] = set()
        self._b_shares: dict[int, np.ndarray] = {}
        self._extras: dict[int, list] = {}
        self._frozen = False
        self._recovery: tuple[list[int], list[int], dict] | None = None

    def begin_round(self, round_idx: int) -> None:
        super().begin_round(round_idx)
        self._acc = None
        self._round_slots = set()
        self._b_shares = {}
        self._extras = {}
        self._frozen = False
        self._recovery = None
        self.sample_num_dict.clear()

    def add_local_trained_result(self, index: int, wire_leaves,
                                 sample_num: int,
                                 round_idx: int | None = None) -> None:
        if not self._admit_upload(index, round_idx):
            return
        if self._frozen:
            # recovery in flight: the survivor set (and the reveal
            # requests out for it) is FIXED — folding a late slot now
            # would leave its masks unstrippable; the shed/re-broadcast
            # path gives the rank a fresh shot at the round
            _obs.record_stale_upload("stale")
            log.warning("secagg: dropping late upload from slot %d — "
                        "mask recovery already in flight", index)
            return
        if index in self._round_slots:
            # chaos-duplicated upload: the fold is additive, so exactly-
            # once matters here where the dense path's slot overwrite was
            # naturally idempotent
            _obs.record_stale_upload("stale")
            log.warning("secagg: dropping duplicate upload from slot %d",
                        index)
            return
        masked, b_shares = wire_leaves[0], wire_leaves[1]
        self._acc = sa.fold_masked(self._acc, masked, self.secagg.p)
        self._round_slots.add(index)
        self._b_shares[index] = np.asarray(b_shares, np.int64)
        self._extras[index] = list(wire_leaves[2:])
        self.sample_num_dict[index] = sample_num
        self.flag_client_model_uploaded[index] = True

    def set_recovery(self, survivors, dead,
                     pair_reveals: dict[int, dict[int, int]]) -> None:
        """Fix the survivor/dead split (and the survivor-revealed pairwise
        seeds) the next ``aggregate()`` decodes with. Dead slots are
        ledgered ``secagg_dropout`` with the clients they would have
        trained."""
        survivors = sorted(int(s) for s in survivors)
        dead = sorted(int(d) for d in dead)
        if len(survivors) < self.secagg.recovery_min:
            raise ValueError(
                f"secagg recovery needs >= {self.secagg.recovery_min} "
                f"survivors, got {len(survivors)}")
        self._recovery = (survivors, dead, dict(pair_reveals))
        if dead:
            ids = self.client_sampling(self.current_round)
            for j in dead:
                self.quarantine.record(self.current_round, j + 1,
                                       "secagg_dropout",
                                       client=int(ids[j]))
                _obs.record_update_rejected("secagg_dropout")
            _perf.record_secagg_dropped(len(dead))

    def aggregate(self):
        if self._recovery is None:
            # full barrier (no elastic manager in the stack): every slot
            self.set_recovery(sorted(self._round_slots), [], {})
        survivors, dead, reveals = self._recovery
        t0 = time.perf_counter()
        # strip survivors' self-masks from the shares the SURVIVOR slots
        # hold (>= t+1 by the recovery threshold) + the dead slots'
        # orphaned pairwise masks from the survivor reveals
        self_seeds = {
            i: sa.recover_self_seed(
                survivors, self._b_shares[i][survivors],
                self.secagg.threshold_t, self.secagg.p)
            for i in survivors}
        vec_sum = sa.unmask_sum(self._acc, survivors, dead, self_seeds,
                                reveals, self.secagg)
        nsamp = np.asarray([self.sample_num_dict[i] for i in survivors],
                           np.float64)
        if self.defense_type == "dp":
            # clients masked UNWEIGHTED clipped deltas: uniform average
            # over the realized m + noise z*C/m, accountant charged with
            # the realized sampling rate (elastic rounds shrink m)
            m = len(survivors)
            delta = vec_sum / m
            sd = self._dp_z * self._dp_C / m
            wal = getattr(self, "wal", None)
            if wal is not None:
                # WAL pre-charge, fsync'd BEFORE the noise key is drawn
                # (docs/ROBUSTNESS.md §Server crash recovery): a restarted
                # accountant replays this record, so the reported ε can
                # never be lower than the charges actually incurred
                wal.append("precharge", sync=True,
                           round=int(self.current_round),
                           q=float(m / self.cfg.client_num_in_total),
                           z=float(self._dp_z), clip=float(self._dp_C),
                           m=int(m))
            self._noise_rng, k = jax.random.split(self._noise_rng)
            noise = np.asarray(
                jax.random.normal(k, np.shape(delta), jnp.float32),
                np.float64) * sd
            global_vec = np.asarray(tree_vectorize(self.net.params),
                                    np.float64)
            new_vec = global_vec + delta + noise
            from fedml_tpu.core.privacy import charge_and_record

            self._privacy_cache = charge_and_record(
                self.accountant, m / self.cfg.client_num_in_total,
                self._dp_z, self._dp_C, realized_m=m)
        else:
            # clients pre-normalized by the FULL cohort total T; the
            # decoded sum is sum_S (n_i/T) x_i — rescale by T / sum_S n_i
            # for the exact survivor-weighted mean (the elastic rule)
            _, counts = cohort_sample_counts(
                self.current_round, self.cfg, self.dataset,
                _batch_cap(self.dataset, self.cfg))
            new_vec = vec_sum * (max(sum(counts), 1)
                                 / max(float(nsamp.sum()), 1e-12))
        new_params = tree_unvectorize(
            jnp.asarray(np.asarray(new_vec, np.float32)), self.net.params)

        # extras (BN stats) are not secret: plain weighted mean over the
        # survivors' cleartext extra leaves
        extra_leaves = jax.tree.leaves(self.net.extra)
        if extra_leaves and survivors:
            stacked = [
                jnp.stack([jnp.asarray(self._extras[i][k])
                           for i in survivors])
                for k in range(len(extra_leaves))
            ]
            avg = tree_weighted_mean(stacked,
                                     jnp.asarray(nsamp, jnp.float32))
            new_extra = jax.tree.unflatten(
                jax.tree.structure(self.net.extra), avg)
        else:
            new_extra = self.net.extra

        self.net = NetState(new_params, new_extra)
        self._acc, self._recovery = None, None
        self._round_slots, self._b_shares, self._extras = set(), {}, {}
        self.sample_num_dict.clear()
        _perf.record_flush_seconds(time.perf_counter() - t0)
        return pack_pytree(self.net)

    def privacy_record(self) -> dict | None:
        """The round record's ``privacy`` block (None outside dp mode) —
        the server manager rides it on every emitted round."""
        return self._privacy_cache


class TASecureClientManager(FedAvgClientManager):
    """FedAvgClientManager that answers mask-recovery reveal requests."""

    def register_message_receive_handlers(self):
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_REVEAL_REQUEST,
            self.handle_message_reveal_request)

    def handle_message_reveal_request(self, msg_params):
        round_idx = int(msg_params[MyMessage.MSG_ARG_KEY_ROUND])
        dead = [int(d) for d in
                np.asarray(msg_params[MyMessage.MSG_ARG_KEY_SECAGG_DEAD])]
        seeds = self.trainer.reveal_pair_seeds(round_idx, dead)
        msg = Message(MyMessage.MSG_TYPE_C2S_REVEAL_SHARES, self.rank,
                      self.server_rank)
        msg.add_params(MyMessage.MSG_ARG_KEY_SECAGG_DEAD,
                       np.asarray(dead, np.int64))
        msg.add_params(MyMessage.MSG_ARG_KEY_SECAGG_PAIR_SEEDS,
                       np.asarray(seeds, np.int64))
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, round_idx)
        # reveals bypass the async uplink sender: tiny frames, and the
        # round cannot advance until they land — FIFO with nothing
        self.send_message(msg)


class TASecureServerManager(FedAvgServerManager):
    """FedAvgServerManager with the mask-recovery state machine.

    Phases per round: ``uploads`` (the ordinary barrier / elastic
    timeout) -> when slots are missing and survivors >= t+1, ``recovery``
    (reveal requests out, replies folding in) -> aggregate. Below
    threshold, or on a reveal lost past the watchdog deadline, the round
    SHEDS: every lost slot is ledgered, the outcome metric counts it, and
    the round re-broadcasts (the wedge-fix path) so a recovered fleet
    re-converges instead of wedging."""

    def __init__(self, aggregator: TAAggregator, **kw):
        if kw.get("async_buffer_k") is not None:
            raise ValueError("the masked secure-aggregation tier needs "
                             "the synchronous cohort — async_buffer_k is "
                             "refused")
        if kw.get("delta_broadcast"):
            raise ValueError("delta_broadcast is not wired for the "
                             "masked secure-aggregation tier (uploads "
                             "prove no base version — run dense)")
        if kw.get("heartbeat_max_age_s") is not None:
            raise ValueError("heartbeat cohort admission is not wired for "
                             "the masked secure-aggregation tier (an "
                             "excluded slot's masks would orphan every "
                             "round) — rely on round_timeout_s recovery")
        super().__init__(aggregator, **kw)
        self._phase = "uploads"
        self._reveal: dict | None = None
        if not hasattr(self, "_last_secagg"):
            # crash recovery (_recover_in_flight, called from the base
            # __init__) may already have recorded a shed outcome here —
            # don't clobber it
            self._last_secagg: dict | None = None

    def register_message_receive_handlers(self):
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_REVEAL_SHARES,
            self.handle_message_reveal_shares)

    # ------------------------------------------------------------ recovery
    def _advance_round(self):
        """Route through mask recovery before the base aggregate: a full
        cohort decodes immediately; missing slots start the reveal phase
        (or shed below threshold). Caller holds _round_lock."""
        agg: TAAggregator = self.aggregator
        survivors = sorted(agg._round_slots)
        dead = [s for s in range(agg.worker_num) if s not in agg._round_slots]
        if not dead:
            agg.set_recovery(survivors, [], {})
            _perf.record_secagg_round("full")
            self._last_secagg = {"outcome": "full", "dead": []}
            super()._advance_round()
            return
        if len(survivors) < agg.secagg.recovery_min:
            self._shed_round(
                survivors, dead,
                f"{len(survivors)} survivors < recovery threshold "
                f"{agg.secagg.recovery_min}")
            return
        self._begin_recovery(survivors, dead)

    def _recover_in_flight(self, committed: int, replay) -> None:
        """Crash recovery × the secagg state machine (docs/ROBUSTNESS.md
        §Server crash recovery): the base recovery ledgers the accepted
        masked uploads as ``server_restart`` and re-dispatches the open
        round — which for the masked tier IS the shed-and-rebroadcast
        path (fresh boot = fresh fold state: ``_acc``/``_recovery``/
        ``_phase`` reset, clients re-mask for the re-broadcast round, so
        a half-revealed fold can never survive a restart). If the WAL
        shows a reveal was in flight, the dead slots it was recovering
        are additionally ledgered ``secagg_shed`` — the same verdict the
        live shed path records — and the outcome metric counts a shed."""
        super()._recover_in_flight(committed, replay)
        if replay is None or self._resume_round is None:
            return
        reveals = replay.since_last_commit("secagg_reveal")
        if not reveals:
            return
        rec = reveals[-1]
        dead = [int(s) for s in rec.get("dead", [])]
        ids = self.aggregator.client_sampling(self.round_idx)
        for slot in dead:
            self.aggregator.quarantine.record(
                self.round_idx, slot + 1, "secagg_shed",
                client=int(ids[slot]))
            _obs.record_update_rejected("secagg_shed")
        _perf.record_secagg_round("shed")
        _perf.record_secagg_dropped(len(dead))
        self._last_secagg = {"outcome": "shed", "dead": dead}
        log.error("secagg round %d SHED (server crashed mid-reveal): "
                  "lost slots %s ledgered — the resume probe re-runs the "
                  "round clean", self.round_idx, dead)

    def _begin_recovery(self, survivors: list[int], dead: list[int]) -> None:
        agg: TAAggregator = self.aggregator
        agg._frozen = True
        self._phase = "recovery"
        if self.wal is not None:
            # journal the reveal fan-out (fsync'd): a crash from here to
            # the fold must recover as a SHED round, never a half-reveal
            self.wal.append("secagg_reveal", sync=True,
                            round=int(self.round_idx),
                            survivors=[int(s) for s in survivors],
                            dead=[int(d) for d in dead])
        self._maybe_crash("reveal")
        self._reveal = {"survivors": survivors, "dead": dead,
                        "seeds": {}, "t0": time.perf_counter()}
        log.warning("secagg round %d: slots %s dropped — asking %d "
                    "survivors to reveal their pairwise seeds",
                    self.round_idx, dead, len(survivors))
        for slot in survivors:
            msg = Message(MyMessage.MSG_TYPE_S2C_REVEAL_REQUEST, self.rank,
                          slot + 1)
            msg.add_params(MyMessage.MSG_ARG_KEY_SECAGG_DEAD,
                           np.asarray(dead, np.int64))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
            self.send_message(msg)

    def handle_message_reveal_shares(self, msg_params):
        with self._round_lock:
            if self._phase != "recovery" or self._reveal is None:
                _obs.record_stale_upload("stale")
                return
            if int(msg_params.get(MyMessage.MSG_ARG_KEY_ROUND,
                                  self.round_idx)) != self.round_idx:
                _obs.record_stale_upload("stale")
                return
            slot = int(msg_params[Message.MSG_ARG_KEY_SENDER]) - 1
            rv = self._reveal
            if slot not in rv["survivors"] or slot in rv["seeds"]:
                return  # unknown or duplicate reveal: exactly-once fold
            dead = [int(d) for d in np.asarray(
                msg_params[MyMessage.MSG_ARG_KEY_SECAGG_DEAD])]
            seeds = np.asarray(
                msg_params[MyMessage.MSG_ARG_KEY_SECAGG_PAIR_SEEDS],
                np.int64)
            if dead != rv["dead"] or len(seeds) != len(dead):
                log.warning("secagg: reveal from slot %d names dead set "
                            "%s != %s — dropped", slot, dead, rv["dead"])
                return
            rv["seeds"][slot] = {j: int(s) for j, s in zip(dead, seeds)}
            if len(rv["seeds"]) < len(rv["survivors"]):
                return
            # every survivor revealed: strip, decode, and run the base
            # round advance (aggregate -> eval -> ckpt -> next broadcast)
            dt = time.perf_counter() - rv["t0"]
            agg: TAAggregator = self.aggregator
            agg.set_recovery(rv["survivors"], rv["dead"], rv["seeds"])
            _perf.record_secagg_round("recovered")
            _perf.record_secagg_recovery_seconds(dt)
            self._last_secagg = {"outcome": "recovered",
                                 "dead": list(rv["dead"]),
                                 "recovery_s": round(dt, 6)}
            self._phase, self._reveal = "uploads", None
            FedAvgServerManager._advance_round(self)

    def _shed_round(self, survivors: list[int], dead: list[int],
                    why: str) -> None:
        """Below-threshold / reveal-lost: ledger every lost slot, count
        the outcome, re-broadcast the SAME round (fresh fault draws; a
        recovered fleet re-converges). Caller holds _round_lock."""
        agg: TAAggregator = self.aggregator
        ids = agg.client_sampling(self.round_idx)
        for slot in dead:
            agg.quarantine.record(self.round_idx, slot + 1, "secagg_shed",
                                  client=int(ids[slot]))
            _obs.record_update_rejected("secagg_shed")
        _perf.record_secagg_round("shed")
        _perf.record_secagg_dropped(len(dead))
        log.error("secagg round %d SHED (%s): lost slots %s ledgered — "
                  "re-broadcasting the round", self.round_idx, why, dead)
        self._phase, self._reveal = "uploads", None
        self._last_secagg = {"outcome": "shed", "dead": list(dead)}
        # the all-uploads-lost wedge-fix path: clear the elastic
        # undeliverable marks (round_idx is NOT advancing, so the reprobe
        # cadence can never trigger) and re-broadcast; _broadcast_model's
        # begin_round resets the masked fold for the fresh attempt
        self._undeliverable.clear()
        self._update_alive_gauge()
        self._broadcast_model(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                              agg.get_global_model_params())

    def on_timeout(self, idle_s: float):
        with self._round_lock:
            if self._phase == "recovery" and not self._finished.is_set():
                rv = self._reveal or {"survivors": [], "dead": [],
                                      "seeds": {}}
                missing = [s for s in rv["survivors"]
                           if s not in rv["seeds"]]
                self._shed_round(
                    rv["survivors"], rv["dead"],
                    f"reveal frames lost from slots {missing} after "
                    f"{idle_s:.1f}s")
                return
        super().on_timeout(idle_s)

    def _round_record_extra(self) -> dict:
        extra = super()._round_record_extra()
        if self._last_secagg is not None:
            extra["secagg"] = dict(self._last_secagg)
        return extra


def run_simulated(dataset, task, cfg: FedAvgConfig, backend="LOOPBACK",
                  job_id="turboagg-sim", base_port=50000, threshold_t=None,
                  quant_scale=2**16, defense_type: str = "none",
                  norm_bound: float = 30.0, noise_multiplier: float = 1.0,
                  secagg_max_abs: float = 4.0, chaos_plan=None,
                  round_timeout_s: float | None = None, telemetry=None,
                  ckpt_dir: str | None = None, n_shares=None):
    """All ranks as threads (mpirun-on-localhost analogue); returns the
    aggregator with .net/.history. ``chaos_plan`` + ``round_timeout_s``
    arm the dropout-recovery scenario deterministically; ``defense_type=
    'dp'`` runs accounted DP on the masked path (privacy block on every
    round record)."""
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port)
    from fedml_tpu import chaos as _chaos

    if chaos_plan is not None:  # None must not clobber an installed plan
        _chaos.install_plan(chaos_plan)
    try:
        # rank-0 crash rules are supervised server restarts (docs/
        # ROBUSTNESS.md §Server crash recovery) — the masked tier rides
        # the same driver as the fedavg runtime: kill at the scheduled
        # point, recover through checkpoint + WAL, shed any half-revealed
        # round (never a half-recovered fold)
        active = _chaos.active_plan()
        crash_points = (active.server_crash_points()
                        if active is not None else [])
        if crash_points and ckpt_dir is None:
            raise ValueError(
                "a chaos crash rule naming rank 0 (server restart) needs "
                "ckpt_dir= — recovery replays checkpoint + WAL")

        def build_server():
            agg = TAAggregator(
                dataset, task, cfg, worker_num=size - 1,
                threshold_t=threshold_t, quant_scale=quant_scale,
                defense_type=defense_type, norm_bound=norm_bound,
                noise_multiplier=noise_multiplier,
                secagg_max_abs=secagg_max_abs, n_shares=n_shares)
            return TASecureServerManager(
                agg, rank=0, size=size, backend=backend,
                round_timeout_s=round_timeout_s, telemetry=telemetry,
                ckpt_dir=ckpt_dir, **kw)

        server = build_server()
        aggregator = server.aggregator
        clients = []
        for r in range(1, size):
            trainer = SecureTrainer(
                r, dataset, task, cfg, threshold_t=threshold_t,
                quant_scale=quant_scale, defense_type=defense_type,
                norm_bound=norm_bound, secagg_max_abs=secagg_max_abs)
            clients.append(TASecureClientManager(
                trainer, rank=r, size=size, backend=backend, **kw))
        if crash_points:
            from fedml_tpu.distributed.fedavg.api import (
                run_supervised_simulated,
            )

            server = run_supervised_simulated(server, clients,
                                              crash_points, build_server)
            aggregator = server.aggregator
        else:
            launch_simulated(server, clients)
    finally:
        if chaos_plan is not None:
            _chaos.install_plan(None)
    return aggregator
