"""Distributed FedOpt — the FedAvg cross-process runtime + a server optimizer.

Mirror of fedml_api/distributed/fedopt/ (6-file pattern): the message flow,
trainer, and managers are exactly FedAvg's (the reference's are near-copies
too); only the aggregator differs — after the weighted average it applies
the pseudo-gradient server step (FedOptAggregator.py:70-121), here the same
jitted optax update the SPMD engine uses (algorithms/fedopt.py), so the two
runtimes stay numerically aligned.
"""

from __future__ import annotations

import jax
import optax

from fedml_tpu.algorithms.fedavg import FedAvgConfig
from fedml_tpu.algorithms.fedopt import make_server_optimizer
from fedml_tpu.comm.message import pack_pytree
from fedml_tpu.core.local import NetState
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.api import init_client
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated
from fedml_tpu.utils.tree import tree_sub


class FedOptAggregator(FedAvgAggregator):
    def __init__(self, dataset, task, cfg: FedAvgConfig, worker_num: int,
                 server_optimizer: str = "sgd", server_lr: float = 1.0,
                 server_momentum: float = 0.9, **agg_kw):
        # agg_kw: the base aggregator's robust-aggregation surface
        # (aggregator= / sanitize=) — the server step composes on top of
        # whatever estimator produced the "average"
        super().__init__(dataset, task, cfg, worker_num, **agg_kw)
        tx = make_server_optimizer(server_optimizer, server_lr, server_momentum)
        self._server_opt_state = tx.init(self.net.params)
        if self._partitioner is not None:
            # the moments shard like the params they mirror, and the
            # exported per-device bytes must count the whole server plane
            self._server_opt_state = self._partitioner.shard(
                self._server_opt_state)
        self._record_server_state_bytes(self._server_opt_state)

        def step(old: NetState, avg: NetState, opt_state):
            pseudo_grad = tree_sub(old.params, avg.params)
            updates, new_state = tx.update(pseudo_grad, opt_state, old.params)
            return NetState(optax.apply_updates(old.params, updates), avg.extra), new_state

        jit_kw = {}
        if self._partitioner is not None:
            # pin the step's outputs to the rule-table layout so the server
            # plane stays partitioned round over round inside the compiled
            # program — no eager re-sharding pass per round
            jit_kw["out_shardings"] = (
                self._partitioner.shardings(self.net),
                self._partitioner.shardings(self._server_opt_state))
        self._server_step = jax.jit(step, **jit_kw)

    def aggregate(self):
        old = self.net
        self._aggregate_core()  # weighted average -> self.net, unpacked
        self.net, self._server_opt_state = self._server_step(
            old, self.net, self._server_opt_state
        )
        return pack_pytree(self.net)


def run_simulated(dataset, task, cfg: FedAvgConfig, backend="LOOPBACK",
                  job_id="fedopt-sim", base_port=50000, **opt_kw):
    """All ranks as threads (mpirun-on-localhost analogue); returns the
    aggregator with .net/.history."""
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port)
    aggregator = FedOptAggregator(dataset, task, cfg, worker_num=size - 1, **opt_kw)
    server = FedAvgServerManager(aggregator, rank=0, size=size, backend=backend, **kw)
    clients = [init_client(dataset, task, cfg, r, size, backend, **kw)
               for r in range(1, size)]
    launch_simulated(server, clients)
    return aggregator
