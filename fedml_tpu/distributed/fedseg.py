"""Distributed FedSeg — federated segmentation over the cross-process runtime.

Mirror of fedml_api/distributed/fedseg/ (6-file pattern): the round machinery
is distributed FedAvg's (FedSegAggregator mirrors FedAVGAggregator); the
FedSeg substance — pixel-wise CE/focal loss with ignore_index, scheduled
client LR, and confusion-matrix evaluation reported as Pixel Acc / mIoU /
FWIoU (Evaluator, fedseg/utils.py:246-288) — comes from the same
segmentation task + LocalSpec the SPMD FedSegAPI builds, so the two runtimes
stay numerically aligned. Eval accumulates the [C, C] confusion matrix on
device; only the final matrix crosses to the host.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fedml_tpu.algorithms.fedseg import FedSegAPI, FedSegConfig
from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
from fedml_tpu.distributed.fedavg.client_manager import FedAvgClientManager
from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager
from fedml_tpu.distributed.fedavg.trainer import DistributedTrainer
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated
from fedml_tpu.utils.seg_metrics import confusion_matrix, seg_scores

log = logging.getLogger("fedml_tpu.distributed.fedseg")


def _build_components(dataset, module, cfg: FedSegConfig):
    """One FedSegAPI (no mesh) supplies the shared task/local_spec/eval so
    every rank derives them from identical code paths."""
    api = FedSegAPI(dataset, module, cfg)
    return api.task, api.local_spec, api


class FedSegAggregator(FedAvgAggregator):
    """FedAvg collection/average + segmentation eval per round."""

    def __init__(self, dataset, task, cfg: FedSegConfig, worker_num: int,
                 ignore_index: int = 255):
        super().__init__(dataset, task, cfg, worker_num)
        C = dataset.class_num
        ignore = ignore_index

        def eval_fn(net, xb, yb, mb):
            def body(acc, batch):
                x, y, m = batch
                logits = task.predict(net.params, net.extra, x)
                pred = jnp.argmax(logits, -1)
                valid = (y != ignore).astype(jnp.float32) * m[:, None, None]
                return acc + confusion_matrix(pred, y, C, valid), None

            conf, _ = lax.scan(body, jnp.zeros((C, C)), (xb, yb, mb))
            return conf

        self._conf_fn = jax.jit(eval_fn)

    ci_eval_cap = 64  # segmentation eval batches are heavy

    def _record_eval(self, round_idx: int) -> None:
        conf = self._conf_fn(self.net, *self._test_cache)
        rec = {"round": round_idx, **seg_scores(np.asarray(conf))}
        self.history.append(rec)
        log.info("server seg eval %s", rec)


def run_simulated(dataset, module, cfg: FedSegConfig, backend="LOOPBACK",
                  job_id="fedseg-sim", base_port=50000):
    """All ranks as threads (mpirun-on-localhost analogue); returns the
    aggregator with .net/.history (mIoU/FWIoU per eval round)."""
    task, local_spec, _ = _build_components(dataset, module, cfg)
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port)
    aggregator = FedSegAggregator(dataset, task, cfg, worker_num=size - 1,
                                  ignore_index=cfg.ignore_index)
    server = FedAvgServerManager(aggregator, rank=0, size=size, backend=backend, **kw)
    clients = [
        FedAvgClientManager(
            DistributedTrainer(r, dataset, task, cfg, local_spec=local_spec),
            rank=r, size=size, backend=backend, **kw)
        for r in range(1, size)
    ]
    launch_simulated(server, clients)
    return aggregator
