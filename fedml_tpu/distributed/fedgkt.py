"""Distributed FedGKT — split computing + group knowledge transfer.

Mirror of fedml_api/distributed/fedgkt/: each client trains its small
extractor+head locally (with KL distillation from last round's server logits,
GKTClientTrainer.py:49-60), then ships per-batch feature maps + logits +
labels to the server (the reference's C2S message); the server trains the
large trunk on all clients' features with bidirectional KL
(GKTServerTrainer.train_large_model_on_the_server, GKTServerTrainer.py:233)
and returns fresh per-client server logits for the next round's KD.

Both phases are the exact jitted programs the SPMD FedGKTAPI builds
(algorithms/fedgkt.py), borrowed via a shared API instance, so the
cross-process runtime matches the in-process simulation exactly (tested).
"""

from __future__ import annotations

import logging
import threading

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedgkt import FedGKTAPI, FedGKTConfig
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.client_data import (FederatedData, pack_clients,
                                        pad_batches)
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.distributed.utils import backend_kwargs, launch_simulated

log = logging.getLogger("fedml_tpu.distributed.fedgkt")


class GKTMessage:
    MSG_TYPE_S2C_SYNC = 1       # server logits (or round-0 empty) + client index
    MSG_TYPE_C2S_FEATURES = 2   # feats, client logits, labels, mask, n
    MSG_TYPE_S2C_FINISH = 3

    ARG_ROUND = "round"
    ARG_CLIENT_INDEX = "client_idx"
    ARG_S_LOGITS = "s_logits"
    ARG_FEATS = "feats"
    ARG_C_LOGITS = "c_logits"
    ARG_LABELS = "labels"
    ARG_MASK = "mask"


class GKTClientWorker:
    """One worker slot: persistent extractor+head for whichever client id the
    server assigns it each round (slot semantics match the SPMD engine's
    vmapped K axis, so the two runtimes agree bit-for-bit)."""

    def __init__(self, slot: int, dataset: FederatedData, api: FedGKTAPI):
        self.slot, self.data, self.api = slot, dataset, api
        cfg = api.cfg
        counts = [len(v) for v in dataset.train_idx_map.values()]
        b = int(np.ceil(max(counts) / cfg.batch_size))
        self.num_batches = min(cfg.max_batches or b, b)
        # this slot's row of the API's stacked per-client params
        self.ext_p = jax.tree.map(lambda v: v[slot], api.ext_params)
        self.head_p = jax.tree.map(lambda v: v[slot], api.head_params)
        self._phase = api._client_phase  # vmapped; called with K=1

    def train(self, round_idx: int, client_index: int, s_logits):
        cfg = self.api.cfg
        cb = pack_clients(self.data, [client_index], cfg.batch_size,
                          max_batches=self.num_batches, seed=cfg.seed,
                          round_idx=round_idx)
        # pad the INPUT block to the global batch budget before the phase:
        # per-slot B varies with the client's sample count, the server stacks
        # uploads into one [K, B, ...] block, and the engine pads the same
        # way (FedGKTAPI.run_round) — running the phase over the padded
        # batches (masked no-ops for training) makes the shipped features /
        # logits of padded rows bit-identical to the in-process oracle's
        # (they feed next round's KD teacher, so zero-padding uploads
        # instead would silently diverge the runtimes)
        cb = pad_batches(cb, self.num_batches)
        x, y, m = jnp.asarray(cb.x), jnp.asarray(cb.y), jnp.asarray(cb.mask)
        if s_logits is None:
            sl = jnp.zeros(x.shape[:3] + (self.api.num_classes,))
            use_kd = 0.0
        else:
            sl, use_kd = jnp.asarray(s_logits)[None], 1.0
        add1 = lambda t: jax.tree.map(lambda v: v[None], t)
        ep, hp, feats, logits, aux = self._phase(
            add1(self.ext_p), add1(self.head_p), x, y, m, sl, use_kd)
        self.ext_p = jax.tree.map(lambda v: v[0], ep)
        self.head_p = jax.tree.map(lambda v: v[0], hp)
        return (np.asarray(feats[0]), np.asarray(logits[0]),
                np.asarray(cb.y[0]), np.asarray(cb.mask[0]))


class GKTServerManager(ServerManager):
    def __init__(self, dataset: FederatedData, api: FedGKTAPI, rank=0, size=0,
                 backend="LOOPBACK", **kw):
        self.data, self.api = dataset, api
        self.round_idx = 0
        self.round_num = api.cfg.comm_round
        self._uploads: dict[int, tuple] = {}
        self._s_logits = None  # [K, B, bs, C] after the first server phase
        self._lock = threading.Lock()
        super().__init__(rank, size, backend, **kw)

    def run(self):
        self._send_sync()
        super().run()

    def _send_sync(self):
        cfg = self.api.cfg
        ids = sample_clients(self.round_idx, cfg.client_num_in_total,
                             cfg.client_num_per_round, cfg.seed)
        for rank in range(1, self.size):
            msg = Message(GKTMessage.MSG_TYPE_S2C_SYNC, self.rank, rank)
            msg.add_params(GKTMessage.ARG_ROUND, self.round_idx)
            msg.add_params(GKTMessage.ARG_CLIENT_INDEX, int(ids[rank - 1]))
            if self._s_logits is not None:
                msg.add_params(GKTMessage.ARG_S_LOGITS,
                               np.asarray(self._s_logits[rank - 1]))
            self.send_message(msg)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            GKTMessage.MSG_TYPE_C2S_FEATURES, self.handle_features)

    def handle_features(self, msg_params):
        with self._lock:
            sender = msg_params[Message.MSG_ARG_KEY_SENDER]
            self._uploads[sender - 1] = (
                msg_params[GKTMessage.ARG_FEATS],
                msg_params[GKTMessage.ARG_C_LOGITS],
                msg_params[GKTMessage.ARG_LABELS],
                msg_params[GKTMessage.ARG_MASK],
            )
            if len(self._uploads) < self.size - 1:
                return
            slots = sorted(self._uploads)
            stack = lambda i: jnp.stack(
                [jnp.asarray(self._uploads[s][i]) for s in slots])
            feats, c_logits, y, m = (stack(i) for i in range(4))
            api = self.api
            api.server_params, api.server_opt, self._s_logits = api._server_phase(
                api.server_params, api.server_opt, feats, c_logits, y, m)
            self._uploads.clear()
            self.round_idx += 1
            if self.round_idx == self.round_num:
                for rank in range(1, self.size):
                    self.send_message(
                        Message(GKTMessage.MSG_TYPE_S2C_FINISH, self.rank, rank))
                self.finish()
                return
            self._send_sync()


class GKTClientManager(ClientManager):
    def __init__(self, worker: GKTClientWorker, rank, size, backend="LOOPBACK", **kw):
        self.worker = worker
        super().__init__(rank, size, backend, **kw)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            GKTMessage.MSG_TYPE_S2C_SYNC, self.handle_sync)
        self.register_message_receive_handler(
            GKTMessage.MSG_TYPE_S2C_FINISH, lambda _m: self.finish())

    def handle_sync(self, msg_params):
        round_idx = int(msg_params[GKTMessage.ARG_ROUND])
        client_index = int(msg_params[GKTMessage.ARG_CLIENT_INDEX])
        s_logits = msg_params.get(GKTMessage.ARG_S_LOGITS)
        feats, logits, y, m = self.worker.train(round_idx, client_index, s_logits)
        msg = Message(GKTMessage.MSG_TYPE_C2S_FEATURES, self.rank, 0)
        msg.add_params(GKTMessage.ARG_FEATS, feats)
        msg.add_params(GKTMessage.ARG_C_LOGITS, logits)
        msg.add_params(GKTMessage.ARG_LABELS, y)
        msg.add_params(GKTMessage.ARG_MASK, m)
        self.send_message(msg)


def run_simulated(dataset: FederatedData, extractor, client_head, server_model,
                  cfg: FedGKTConfig, num_classes: int, backend="LOOPBACK",
                  job_id="fedgkt-sim", base_port=50000) -> FedGKTAPI:
    """All ranks as threads (mpirun-on-localhost analogue); returns the shared
    API whose .server_params hold the trained trunk."""
    api = FedGKTAPI(dataset, extractor, client_head, server_model, cfg,
                    num_classes)
    size = cfg.client_num_per_round + 1
    kw = backend_kwargs(backend, job_id, base_port)
    server = GKTServerManager(dataset, api, rank=0, size=size, backend=backend, **kw)
    clients = [
        GKTClientManager(GKTClientWorker(r - 1, dataset, api),
                         rank=r, size=size, backend=backend, **kw)
        for r in range(1, size)
    ]
    launch_simulated(server, clients)
    # expose the trained per-slot client models on the shared API for eval
    for c in clients:
        w = c.worker
        api.ext_params = jax.tree.map(
            lambda all_, one: all_.at[w.slot].set(one), api.ext_params, w.ext_p)
        api.head_params = jax.tree.map(
            lambda all_, one: all_.at[w.slot].set(one), api.head_params, w.head_p)
    return api
