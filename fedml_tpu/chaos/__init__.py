"""Deterministic fault injection for the cross-process comm stack.

The resilience machinery this repo ships — elastic partial aggregation with
a round watchdog, dead-rank reprobe, checkpoint/resume, gRPC retry with
exactly-once dedup — only fires under real network faults, which makes it
untestable dead code on a quiet CI box. This package makes those paths
drivable from a CPU-only test: a seeded, declarative :class:`FaultPlan`
wraps any ``BaseCommManager`` (loopback / gRPC / MQTT) and injects frame
**drop, delay, duplicate, reorder, corrupt, partition**, plus **crash**
(a rank goes dark for a round window — its sends vanish, sends to it fail
like a dead TCP peer) and **straggle** (synchronous uplink slowdown) for
the loopback thread harness.

Every injection decision is a pure function of
``(plan seed, rule, direction, src, dst, per-link frame seq)`` — never of
wall clock or thread interleaving — so two runs with the same plan inject
the *identical* fault sequence (``FaultPlan.ledger.canonical()``) and, for
a deterministic protocol, converge to identical final models. That is what
turns "the server survives chaos" into a replayable, assertable invariant
(FL_PyTorch arXiv:2202.03099 and FedJAX arXiv:2108.02117 both argue FL
simulators must reproduce deployment failure modes deterministically).

Usage::

    plan = FaultPlan.from_json(spec)      # or FaultPlan(seed=..., rules=[...])
    with installed(plan):                 # process-global, like set_wire_codec
        run_simulated(...)                # every manager built inside is wrapped
    plan.ledger.canonical()               # the replayable injection record

With no plan installed, ``maybe_wrap`` returns the manager unchanged — the
no-chaos hot path costs nothing.

Scheduled availability (``chaos/churn.py``) is the third axis: a seeded
:class:`ChurnTrace` models the NORMAL state of a fleet — diurnal
availability curves, arrival/dropout point processes, device-class skew —
on a sha256 stream independent of FaultPlan's, so churn × chaos × byzantine
replays bit-for-bit (a :class:`ScenarioPlan` bundles all three for
``scripts/fleet_campaign.py`` profiles). See docs/ROBUSTNESS.md §Fleet
campaigns & client churn for the offline-vs-dead semantics.

Model-space adversaries (``chaos/adversary.py``) are the Byzantine-client
sibling: an :class:`AdversaryPlan` schedules sign_flip/scale/gaussian/
nan/shift uploads per (round-window, rank) with the same seeded
determinism, consumed by ``FedAvgAPI(adversary_plan=...)`` (in-graph) and
the cross-process client manager (on-the-wire) — see
docs/ROBUSTNESS.md §Byzantine-robust aggregation.
"""

from __future__ import annotations

import contextlib
import threading

from fedml_tpu.chaos.plan import FaultLedger, FaultPlan, FaultRule
from fedml_tpu.chaos.inject import ChaosCommManager
from fedml_tpu.chaos.adversary import AdversaryPlan, AdversaryRule
from fedml_tpu.chaos.churn import ChurnTrace, DeviceClass, ScenarioPlan

_active: FaultPlan | None = None
_lock = threading.Lock()


def install_plan(plan: FaultPlan | None) -> None:
    """Set the process-global plan picked up by ``make_comm_manager``.
    Every rank of an in-process (loopback) job shares it; cross-process
    jobs pass the same plan file to each rank (``--chaos-plan``)."""
    global _active
    with _lock:
        _active = plan


def active_plan() -> FaultPlan | None:
    return _active


@contextlib.contextmanager
def installed(plan: FaultPlan):
    """Scoped install — the test-suite idiom (always uninstalls)."""
    install_plan(plan)
    try:
        yield plan
    finally:
        install_plan(None)


def maybe_wrap(manager, rank: int):
    """Wrap ``manager`` in a ChaosCommManager when a plan is installed;
    return it untouched (zero added per-frame work) otherwise."""
    plan = _active
    if plan is None:
        return manager
    return ChaosCommManager(manager, plan, rank)


__all__ = [
    "FaultPlan", "FaultRule", "FaultLedger", "ChaosCommManager",
    "AdversaryPlan", "AdversaryRule",
    "ChurnTrace", "DeviceClass", "ScenarioPlan",
    "install_plan", "active_plan", "installed", "maybe_wrap",
]
