"""FaultPlan — the declarative, seeded schedule the chaos layer executes.

A plan is JSON-serializable so a soak run can be replayed bit-for-bit from
its seed (``scripts/chaos_soak.py``, ``--chaos-plan`` on the launcher)::

    {
      "seed": 1234,
      "rules": [
        {"fault": "drop",      "direction": "send", "src": [1], "dst": [0],
         "rounds": [1, 3], "prob": 0.5},
        {"fault": "corrupt",   "direction": "recv", "dst": [0], "prob": 0.2},
        {"fault": "duplicate", "direction": "send", "src": [2], "dst": [0]},
        {"fault": "partition", "groups": [[0, 1], [2, 3]], "rounds": [2, 4]},
        {"fault": "crash",     "ranks": [3], "rounds": [1, 3]},
        {"fault": "straggle",  "src": [2], "delay_s": 0.3}
      ]
    }

Determinism contract: whether a rule fires on a given frame is a pure
function of ``(plan.seed, rule index, direction, src, dst, link_seq)``
where ``link_seq`` is that (direction, src, dst) link's frame counter.
Link counters are deterministic because each link's frames are emitted in
one thread's program order; nothing reads the wall clock or a shared RNG,
so concurrent links cannot perturb each other's draws. The global
interleaving OF links still varies run to run — which is why the ledger's
``canonical()`` view is sorted — but the *set* of injected faults, and
each link's injection order, replays exactly.
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass, field
from typing import Any

FAULTS = ("drop", "delay", "duplicate", "reorder", "corrupt",
          "partition", "crash", "straggle")
DIRECTIONS = ("send", "recv")


def _decide(seed: int, rule_idx: int, direction: str, src, dst,
            seq: int) -> float:
    """Uniform [0, 1) draw, pure in its arguments (sha256 counter mode)."""
    key = f"{seed}|{rule_idx}|{direction}|{src}|{dst}|{seq}".encode()
    h = hashlib.sha256(key).digest()
    return int.from_bytes(h[:8], "little") / 2.0 ** 64


@dataclass
class FaultRule:
    """One (fault, round-window, rank, direction) schedule entry.

    ``src``/``dst`` filter by sender/receiver rank (None = any);
    ``rounds`` is a half-open [lo, hi) window of protocol rounds (None =
    always); ``prob`` is the per-frame firing probability; ``max_per_link``
    caps injections per (direction, src, dst) link — per-link, not global,
    so the cap is deterministic under thread interleaving. ``delay_s``
    parameterizes delay/straggle; ``groups`` parameterizes partition
    (ranks in different groups cannot reach each other); ``ranks``
    parameterizes crash (those ranks go dark for the window).

    A crash rule naming RANK 0 is a **server crash** (docs/ROBUSTNESS.md
    §Server crash recovery): the wire layer does not black-hole it — the
    supervision layer executes it as a deterministic kill-and-restart
    through the checkpoint + WAL recovery path (``run_simulated`` in
    loopback; the real process dies under ``--supervise``).
    ``after_uploads`` refines WHERE in the window's first round the
    server dies: None = between commits (entering the round, before any
    frame of it leaves); an integer m >= 0 = mid-round, once m uploads of
    the round were accepted (their WAL records durable, their payloads
    lost with the process); -1 = at the secure-aggregation reveal
    fan-out (the masked tier's recovery state machine — the crash must
    shed the round, never half-recover the fold)."""

    fault: str
    direction: str = "send"
    src: list[int] | None = None
    dst: list[int] | None = None
    rounds: list[int] | None = None
    prob: float = 1.0
    delay_s: float = 0.05
    max_per_link: int | None = None
    groups: list[list[int]] | None = None
    ranks: list[int] | None = None
    after_uploads: int | None = None

    def __post_init__(self):
        if self.fault not in FAULTS:
            raise ValueError(f"unknown fault {self.fault!r} (one of {FAULTS})")
        if self.direction not in DIRECTIONS:
            raise ValueError(
                f"unknown direction {self.direction!r} (send|recv)")
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")
        if self.rounds is not None and len(self.rounds) != 2:
            raise ValueError(f"rounds must be [lo, hi), got {self.rounds}")
        if self.fault == "partition" and not self.groups:
            raise ValueError("partition rule needs 'groups': [[...], [...]]")
        if self.fault == "crash" and not self.ranks:
            raise ValueError("crash rule needs 'ranks': [...]")
        if self.after_uploads is not None and self.fault != "crash":
            raise ValueError("after_uploads only parameterizes crash rules")
        if self.after_uploads is not None and self.after_uploads < -1:
            # -1 = the secagg reveal fan-out; anything below can never
            # match a crash point and would be silently inert
            raise ValueError(
                f"after_uploads must be >= -1, got {self.after_uploads}")
        if self.fault == "crash" and 0 in (self.ranks or ()) \
                and self.rounds is None:
            # a rank-0 crash is a supervised server restart: an unbounded
            # window would re-kill the server the moment it recovered,
            # forever — demand an explicit round
            raise ValueError("a crash rule naming rank 0 (server restart) "
                             "needs a 'rounds' window")

    def in_window(self, round_idx: int | None) -> bool:
        if self.rounds is None:
            return True
        if round_idx is None:
            return False  # round unknown -> a windowed rule stays quiet
        return self.rounds[0] <= round_idx < self.rounds[1]

    def matches_link(self, direction: str, src: int | None,
                     dst: int | None) -> bool:
        if self.direction != direction:
            return False
        if self.src is not None and src not in self.src:
            return False
        if self.dst is not None and dst not in self.dst:
            return False
        return True

    def partition_cut(self, src: int | None, dst: int | None) -> bool:
        """True when src and dst sit in different partition groups."""
        g_src = g_dst = None
        for i, g in enumerate(self.groups or ()):
            if src in g:
                g_src = i
            if dst in g:
                g_dst = i
        return g_src is not None and g_dst is not None and g_src != g_dst


class FaultLedger:
    """Thread-safe record of every injected fault — the replay artifact.

    ``canonical()`` sorts entries into a thread-interleaving-independent
    order; two runs of the same plan over the same workload produce equal
    canonical ledgers (the determinism acceptance test)."""

    def __init__(self):
        self._entries: list[dict] = []
        self._lock = threading.Lock()

    def record(self, fault: str, direction: str, src, dst, seq: int,
               round_idx) -> None:
        with self._lock:
            self._entries.append({
                "fault": fault, "direction": direction, "src": src,
                "dst": dst, "seq": seq, "round": round_idx,
            })

    def canonical(self) -> list[tuple]:
        def key(t):
            # src/round can be None (an undecodable frame has no sender /
            # no round tag) — map None below any int so mixed ledgers sort
            return tuple(-1 if v is None else v for v in t[2:]), t[:2]

        with self._lock:
            return sorted(
                ((e["fault"], e["direction"], e["src"], e["dst"], e["seq"],
                  e["round"]) for e in self._entries), key=key)

    def for_round(self, round_idx, faults: tuple[str, ...] | None = None
                  ) -> list[dict]:
        """Entries of one round (optionally one fault subset) — an O(n)
        filtered scan, no sort/full copy: per-round consumers (the trace
        stitcher cross-references straggle/delay per upload) must not
        re-canonicalize a soak run's whole ledger every frame."""
        with self._lock:
            return [dict(e) for e in self._entries
                    if e["round"] == round_idx
                    and (faults is None or e["fault"] in faults)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        with self._lock:
            for e in self._entries:
                out[e["fault"]] = out.get(e["fault"], 0) + 1
        return out


@dataclass
class FaultPlan:
    """A seed plus an ordered rule list; carries the run's ledger."""

    seed: int = 0
    rules: list[FaultRule] = field(default_factory=list)
    ledger: FaultLedger = field(default_factory=FaultLedger, repr=False)

    # ------------------------------------------------------------- decisions
    def fires(self, rule_idx: int, direction: str, src, dst,
              seq: int) -> bool:
        rule = self.rules[rule_idx]
        if rule.prob >= 1.0:
            return True
        return _decide(self.seed, rule_idx, direction, src, dst,
                       seq) < rule.prob

    def server_crash_points(self) -> list[tuple[int, int | None]]:
        """The supervision schedule a rank-0 crash rule encodes (docs/
        ROBUSTNESS.md §Server crash recovery): sorted ``(round,
        after_uploads)`` points, one per rule, each consumed by exactly
        one kill-and-restart. The wire injector ignores rank 0 in crash
        rules — a dead server is a restart, not a black hole."""
        return sorted(
            ((int(r.rounds[0]), r.after_uploads)
             for r in self.rules
             if r.fault == "crash" and 0 in (r.ranks or ())),
            # None (between commits) sorts before any mid-round point of
            # the same round; mixing None and int must not TypeError
            key=lambda p: (p[0], p[1] is not None, p[1] or 0))

    # --------------------------------------------------------- serialization
    @classmethod
    def from_json(cls, spec: str | dict[str, Any]) -> "FaultPlan":
        doc = json.loads(spec) if isinstance(spec, str) else spec
        rules = [FaultRule(**r) for r in doc.get("rules", [])]
        return cls(seed=int(doc.get("seed", 0)), rules=rules)

    @classmethod
    def from_file(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_json(json.load(f))

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """The CLI dual form — a JSON file path or inline JSON (the one
        dispatch rule every --*-plan flag shares)."""
        import os

        return cls.from_file(spec) if os.path.exists(spec) \
            else cls.from_json(spec)

    def to_json(self) -> str:
        def rule_doc(r: FaultRule) -> dict:
            doc = {"fault": r.fault, "direction": r.direction}
            for k in ("src", "dst", "rounds", "max_per_link", "groups",
                      "ranks", "after_uploads"):
                v = getattr(r, k)
                if v is not None:
                    doc[k] = v
            if r.prob != 1.0:
                doc["prob"] = r.prob
            if r.fault in ("delay", "straggle"):
                doc["delay_s"] = r.delay_s
            return doc

        return json.dumps({"seed": self.seed,
                           "rules": [rule_doc(r) for r in self.rules]})

    def fresh(self) -> "FaultPlan":
        """Same schedule, empty ledger — for replaying a plan."""
        return FaultPlan(seed=self.seed, rules=list(self.rules))
