"""ChurnTrace / ScenarioPlan — scheduled client availability, declaratively.

Chaos faults (``plan.py``) model the *abnormal*: dropped frames, dead
peers, byzantine uploads. This module models the *normal* state of a real
fleet — most clients are simply not there most of the time (FedJAX
arXiv:2108.02117 and FL_PyTorch arXiv:2202.03099 both treat availability
traces as a first-class experiment axis). A :class:`ChurnTrace` is a
seeded, declarative schedule of **scheduled** unavailability::

    {
      "seed": 7,
      "base": 0.6, "amplitude": 0.35, "period": 24, "tz_spread": 0.5,
      "rounds_per_window": 2,
      "arrival_spread": 8, "departure_rate": 0.001,
      "device_classes": [
        {"name": "phone",  "weight": 0.8, "size_scale": 0.5},
        {"name": "tablet", "weight": 0.2, "size_scale": 2.0}
      ],
      "rank_base": 0.9, "rank_amplitude": 0.1
    }

Per client: a diurnal sine curve (``base`` ± ``amplitude`` over ``period``
windows, phase-shifted per client across ``tz_spread`` of the cycle — the
time-zone picture), an arrival window (staggered over the first
``arrival_spread`` windows) and a geometric permanent-departure window
(per-window hazard ``departure_rate``) — the arrival/dropout point
processes. ``device_classes`` assigns each client a class by weighted
draw; ``size_skew``/``skewed_sizes`` feed the size-bucketed packer so
device heterogeneity shows up as data-size heterogeneity.

Determinism contract (the churn × chaos replay invariant): every draw is
a pure sha256 function of ``(trace seed, stream, entity, window)`` under
the ``"churn|"`` namespace — a stream *independent* of FaultPlan's
``_decide`` (which hashes ``seed|rule|direction|src|dst|seq`` with no
namespace), so composing a trace with a fault plan and an adversary plan
replays bit-for-bit: same seeds ⇒ same availability timeline, same
injected faults, same final model, same quarantine ledger.

Offline vs dead (docs/ROBUSTNESS.md §Fleet campaigns & client churn):
*scheduled-offline* — the trace says the rank is away; the server skips
it silently (no suspect bookkeeping, no reprobe/backoff churn, quorum
denominators shrink). *Suspected-dead* — the trace says it should be
here and it is not; the existing heartbeat/undeliverable machinery fires.

Client availability carries a **min-one floor**: if a window's Bernoulli
draws leave the active population empty, the active client with the
lowest draw is deemed available (deterministic) — a planetary fleet is
never literally empty, and the floor keeps single-process engines live
through troughs. Rank availability has NO floor: an all-offline window
is a legitimate idle round, handled by the watchdog's idle rate-limit.
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, field
from typing import Any

import numpy as np


def _draw(seed: int, stream: str, entity, window: int) -> float:
    """Uniform [0, 1), pure in its arguments. The leading ``churn|`` tag
    keeps this stream disjoint from FaultPlan's ``_decide`` even for
    colliding argument tuples — churn × chaos draws never correlate."""
    key = f"churn|{seed}|{stream}|{entity}|{window}".encode()
    h = hashlib.sha256(key).digest()
    return int.from_bytes(h[:8], "little") / 2.0 ** 64


@dataclass
class DeviceClass:
    """One hardware tier: ``weight`` is the population share (normalized
    over the class list), ``size_scale`` multiplies the client's local
    dataset size for the size-bucketed packer, ``speed_scale`` divides
    its virtual-clock dispatch duration (reserved for duration models)."""

    name: str
    weight: float = 1.0
    size_scale: float = 1.0
    speed_scale: float = 1.0

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"device class {self.name!r}: weight must be "
                             f"> 0, got {self.weight}")
        if self.size_scale <= 0 or self.speed_scale <= 0:
            raise ValueError(f"device class {self.name!r}: scales must be "
                             "> 0")


@dataclass
class ChurnTrace:
    """A seeded availability schedule over (client | rank, window).

    ``base``/``amplitude``/``period``/``tz_spread`` shape the diurnal
    curve; ``rounds_per_window`` maps protocol rounds onto trace windows;
    ``arrival_spread``/``departure_rate`` are the point processes;
    ``rank_base``/``rank_amplitude`` (None = always-on) give cross-process
    worker RANKS their own curve on an independent stream — engines
    sample *clients*, the server schedules *ranks*."""

    seed: int = 0
    base: float = 1.0
    amplitude: float = 0.0
    period: int = 24
    rounds_per_window: int = 1
    tz_spread: float = 1.0
    arrival_spread: int = 0
    departure_rate: float = 0.0
    device_classes: list[DeviceClass] = field(default_factory=list)
    rank_base: float | None = None
    rank_amplitude: float | None = None

    def __post_init__(self):
        if not 0.0 <= self.base <= 1.0:
            raise ValueError(f"base must be in [0, 1], got {self.base}")
        if self.amplitude < 0.0:
            raise ValueError(f"amplitude must be >= 0, got {self.amplitude}")
        if self.period < 1:
            raise ValueError(f"period must be >= 1, got {self.period}")
        if self.rounds_per_window < 1:
            raise ValueError("rounds_per_window must be >= 1, got "
                             f"{self.rounds_per_window}")
        if not 0.0 <= self.tz_spread <= 1.0:
            raise ValueError(f"tz_spread must be in [0, 1], got "
                             f"{self.tz_spread}")
        if self.arrival_spread < 0:
            raise ValueError("arrival_spread must be >= 0")
        if not 0.0 <= self.departure_rate < 1.0:
            raise ValueError("departure_rate must be in [0, 1), got "
                             f"{self.departure_rate}")
        if self.rank_base is not None and not 0.0 <= self.rank_base <= 1.0:
            raise ValueError(f"rank_base must be in [0, 1], got "
                             f"{self.rank_base}")
        self.device_classes = [
            c if isinstance(c, DeviceClass) else DeviceClass(**c)
            for c in self.device_classes]

    # ------------------------------------------------------------- windowing
    def window(self, round_idx: int) -> int:
        """The trace window a protocol round (or async wave) falls in."""
        return int(round_idx) // self.rounds_per_window

    # ------------------------------------------------------ client processes
    def arrival_window(self, client: int) -> int:
        if self.arrival_spread <= 0:
            return 0
        return int(_draw(self.seed, "arrive", client, 0)
                   * self.arrival_spread)

    def departure_window(self, client: int) -> int | None:
        """The window this client permanently drops out (None = never) —
        a geometric draw with per-window hazard ``departure_rate``,
        offset past the client's arrival."""
        if self.departure_rate <= 0.0:
            return None
        u = _draw(self.seed, "depart", client, 0)
        life = int(math.log(1.0 - u) / math.log(1.0 - self.departure_rate))
        return self.arrival_window(client) + 1 + life

    def _phase(self, stream: str, entity) -> float:
        return (_draw(self.seed, stream, entity, 0)
                * self.period * self.tz_spread)

    def _curve(self, base: float, amplitude: float, phase: float,
               window: int) -> float:
        p = base + amplitude * math.sin(
            2.0 * math.pi * (window + phase) / self.period)
        return min(1.0, max(0.0, p))

    def availability(self, client: int, window: int) -> float:
        """The curve value p(client, window) in [0, 1] — 0 outside the
        client's [arrival, departure) lifetime."""
        if window < self.arrival_window(client):
            return 0.0
        dep = self.departure_window(client)
        if dep is not None and window >= dep:
            return 0.0
        return self._curve(self.base, self.amplitude,
                           self._phase("phase", client), window)

    def is_available(self, client: int, window: int) -> bool:
        p = self.availability(client, window)
        if p >= 1.0:
            return True
        if p <= 0.0:
            return False
        return _draw(self.seed, "avail", client, window) < p

    def available_clients(self, window: int, n_total: int) -> np.ndarray:
        """Sorted int64 ids available in ``window``, with the min-one
        floor (the lowest-draw active client — or overall, if nobody is
        active — is available even when every Bernoulli draw misses)."""
        avail = [c for c in range(n_total) if self.is_available(c, window)]
        if not avail:
            active = [c for c in range(n_total)
                      if self.availability(c, window) > 0.0] \
                or list(range(n_total))
            avail = [min(active,
                         key=lambda c: _draw(self.seed, "avail", c, window))]
        return np.asarray(avail, np.int64)

    def availability_timeline(self, windows: int, n_total: int) -> list[int]:
        """Available-cohort size per window — the determinism oracle's
        artifact and the docs' curve illustration."""
        return [len(self.available_clients(w, n_total))
                for w in range(windows)]

    # --------------------------------------------------------- rank schedule
    def rank_available(self, rank: int, window: int) -> bool:
        """Scheduled availability of a cross-process worker rank — its own
        ``"rank"`` stream and curve, so the same trace drives engines
        (clients) and the server (ranks) without draw coupling. Rank 0 is
        the server: always on (its failures are chaos, not churn)."""
        if rank == 0 or self.rank_base is None:
            return True
        amp = self.rank_amplitude if self.rank_amplitude is not None else 0.0
        p = self._curve(self.rank_base, amp,
                        self._phase("rank_phase", rank), window)
        if p >= 1.0:
            return True
        if p <= 0.0:
            return False
        return _draw(self.seed, "rank", rank, window) < p

    def scheduled_offline_ranks(self, round_idx: int,
                                world_size: int) -> set[int]:
        """Ranks 1..world_size-1 the trace marks away for this round's
        window — the set every server-side skip/admission path consults."""
        w = self.window(round_idx)
        return {r for r in range(1, world_size)
                if not self.rank_available(r, w)}

    # -------------------------------------------------------- device classes
    def device_class(self, client: int) -> DeviceClass | None:
        if not self.device_classes:
            return None
        total = sum(c.weight for c in self.device_classes)
        u = _draw(self.seed, "class", client, 0) * total
        acc = 0.0
        for c in self.device_classes:
            acc += c.weight
            if u < acc:
                return c
        return self.device_classes[-1]

    def size_skew(self, n_total: int) -> np.ndarray:
        """Per-client dataset-size multipliers (all-ones without classes)
        — the device-class skew the size-bucketed packer consumes."""
        if not self.device_classes:
            return np.ones(n_total, np.float64)
        return np.asarray([self.device_class(c).size_scale
                           for c in range(n_total)], np.float64)

    def skewed_sizes(self, base_sizes) -> np.ndarray:
        """Apply the class skew to a base per-client size vector, floored
        at 1 sample (a device class never empties a client)."""
        base = np.asarray(base_sizes, np.float64)
        scaled = base * self.size_skew(len(base))
        return np.maximum(1, np.round(scaled)).astype(np.int64)

    # --------------------------------------------------------- serialization
    @classmethod
    def from_json(cls, spec: str | dict[str, Any]) -> "ChurnTrace":
        doc = json.loads(spec) if isinstance(spec, str) else dict(spec)
        classes = [DeviceClass(**c) for c in doc.pop("device_classes", [])]
        return cls(device_classes=classes, **doc)

    @classmethod
    def from_file(cls, path: str) -> "ChurnTrace":
        with open(path) as f:
            return cls.from_json(json.load(f))

    @classmethod
    def from_spec(cls, spec: str) -> "ChurnTrace":
        """The CLI dual form — a JSON file path or inline JSON (the same
        dispatch rule --chaos-plan uses)."""
        import os

        return cls.from_file(spec) if os.path.exists(spec) \
            else cls.from_json(spec)

    def to_json(self) -> str:
        doc: dict[str, Any] = {"seed": self.seed}
        defaults = ChurnTrace()
        for k in ("base", "amplitude", "period", "rounds_per_window",
                  "tz_spread", "arrival_spread", "departure_rate",
                  "rank_base", "rank_amplitude"):
            v = getattr(self, k)
            if v != getattr(defaults, k):
                doc[k] = v
        if self.device_classes:
            doc["device_classes"] = [
                {"name": c.name, "weight": c.weight,
                 "size_scale": c.size_scale, "speed_scale": c.speed_scale}
                for c in self.device_classes]
        return json.dumps(doc)


@dataclass
class ScenarioPlan:
    """One named campaign scenario: a churn trace × a fault plan × an
    adversary plan, serialized as a single committed document — the unit
    ``scripts/fleet_campaign.py`` profiles carry and replay. Each member
    keeps its own independent seed stream, so the composition replays
    bit-for-bit whenever each member does."""

    name: str = ""
    churn: ChurnTrace | None = None
    faults: Any = None        # chaos.plan.FaultPlan
    adversary: Any = None     # chaos.adversary.AdversaryPlan
    meta: dict = field(default_factory=dict)

    @classmethod
    def from_json(cls, spec: str | dict[str, Any]) -> "ScenarioPlan":
        from fedml_tpu.chaos.adversary import AdversaryPlan
        from fedml_tpu.chaos.plan import FaultPlan

        doc = json.loads(spec) if isinstance(spec, str) else spec
        return cls(
            name=str(doc.get("name", "")),
            churn=(ChurnTrace.from_json(doc["churn"])
                   if doc.get("churn") else None),
            faults=(FaultPlan.from_json(doc["faults"])
                    if doc.get("faults") else None),
            adversary=(AdversaryPlan.from_json(doc["adversary"])
                       if doc.get("adversary") else None),
            meta=dict(doc.get("meta", {})))

    @classmethod
    def from_file(cls, path: str) -> "ScenarioPlan":
        with open(path) as f:
            return cls.from_json(json.load(f))

    @classmethod
    def from_spec(cls, spec: str) -> "ScenarioPlan":
        import os

        return cls.from_file(spec) if os.path.exists(spec) \
            else cls.from_json(spec)

    def to_json(self) -> str:
        doc: dict[str, Any] = {}
        if self.name:
            doc["name"] = self.name
        if self.churn is not None:
            doc["churn"] = json.loads(self.churn.to_json())
        if self.faults is not None:
            doc["faults"] = json.loads(self.faults.to_json())
        if self.adversary is not None:
            doc["adversary"] = json.loads(self.adversary.to_json())
        if self.meta:
            doc["meta"] = self.meta
        return json.dumps(doc)

    def fresh(self) -> "ScenarioPlan":
        """Same scenario, fresh fault ledger — the replay idiom."""
        return ScenarioPlan(
            name=self.name, churn=self.churn,
            faults=self.faults.fresh() if self.faults is not None else None,
            adversary=self.adversary, meta=dict(self.meta))
