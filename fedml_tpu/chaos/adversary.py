"""Model-space adversaries — the chaos layer's Byzantine-client sibling.

PR 2 made *wire-level* faults (drops, corruption, crashes) deterministic
and replayable; this module does the same for *model-level* hostility: a
seeded, declarative :class:`AdversaryPlan` turns chosen worker ranks into
Byzantine clients for chosen round windows, so "f Byzantine of n clients
vs. defense D" is a replayable experiment instead of an anecdote. The
same plan drives both runtimes:

- **standalone / scan** — :func:`make_in_graph_injector` compiles the plan
  into a pure function applied to the stacked client nets INSIDE the
  jitted round program (slot ``i`` plays worker rank ``i+1``, the same
  client the loopback runtime's rank ``i+1`` trains, so quarantine
  ledgers agree across runtimes);
- **cross-process** — :func:`perturb_leaves` runs host-side in the client
  manager right before the upload is packed (the Byzantine client lies on
  the wire; every server defense sees exactly what a real attacker would
  send).

Attacks (``u = w_k - g`` is the client's honest update):

- ``sign_flip``   ``w' = g - factor * u`` — the scaled sign-flip /
                  ascent attack (factor 1 is a pure flip; the classic
                  attack scales, factor >= 5, to overpower the mean);
- ``scale``       ``w' = g + factor * u`` — model replacement /
                  boosting (Bagdasaryan et al.);
- ``gaussian``    ``w' = w + sigma * N(0, I)`` — noise injection;
- ``nan``         ``w' = NaN`` everywhere — the availability attack the
                  sanitation gate must catch before ``tree_weighted_mean``;
- ``shift``       ``w' = w - z * std(u)`` per leaf — a little-is-enough
                  style perturbation: small (z ~ 1) aligned bias that
                  hides inside benign variance instead of overpowering it.

Determinism: WHETHER a rule fires is a pure function of (rule's static
rank set, round window) — no probability draws, so both runtimes agree by
construction. The only randomness (``gaussian``) is seeded per
``(plan.seed, rule index, rank, round)``: sha256-derived on the host path,
``jax.random.fold_in`` chains in-graph — each path replays bit-for-bit
(the two paths draw different bits from the same logical seed; the
*schedule* and hence the quarantine ledger is what must agree).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field

import numpy as np

ATTACKS = ("sign_flip", "scale", "gaussian", "nan", "shift")


def _attack_seed(seed: int, rule_idx: int, rank: int, round_idx: int) -> int:
    """Pure sha256 seed for a rule's noise draw on one (rank, round) —
    the same counter-mode construction as chaos/plan._decide."""
    key = f"adv|{seed}|{rule_idx}|{rank}|{round_idx}".encode()
    return int.from_bytes(hashlib.sha256(key).digest()[:4], "little")


@dataclass
class AdversaryRule:
    """One (attack, round-window, rank set) schedule entry. ``ranks`` are
    1-based COHORT ranks (standalone slot = rank - 1, which in the flat
    cross-process topology is also the transport rank; in a 2-tier
    ``--edges`` topology each worker matches by its cohort slot + 1 — the
    client manager's ``adversary_rank`` — so ONE plan drives flat and
    tree runs identically, quarantine-ledger parity included); ``rounds``
    is a half-open ``[lo, hi)`` window (None = every round). ``factor``
    parameterizes sign_flip/scale, ``sigma`` gaussian, ``z`` shift."""

    attack: str
    ranks: list[int] = field(default_factory=list)
    rounds: list[int] | None = None
    factor: float = 10.0
    sigma: float = 1.0
    z: float = 1.5

    def __post_init__(self):
        if self.attack not in ATTACKS:
            raise ValueError(
                f"unknown attack {self.attack!r} (one of {ATTACKS})")
        if not self.ranks:
            raise ValueError("adversary rule needs 'ranks': [...] "
                             "(1-based worker ranks)")
        if any(r < 1 for r in self.ranks):
            raise ValueError(f"ranks are 1-based worker ranks, got "
                             f"{self.ranks}")
        if self.rounds is not None and len(self.rounds) != 2:
            raise ValueError(f"rounds must be [lo, hi), got {self.rounds}")

    def in_window(self, round_idx: int) -> bool:
        if self.rounds is None:
            return True
        return self.rounds[0] <= round_idx < self.rounds[1]


@dataclass
class AdversaryPlan:
    """A seed plus an ordered rule list — JSON round-trippable so an
    attack/defense experiment replays from its file alone (the
    ``--adversary-plan`` launcher/soak flag)."""

    seed: int = 0
    rules: list[AdversaryRule] = field(default_factory=list)

    @classmethod
    def from_json(cls, spec: str | dict) -> "AdversaryPlan":
        doc = json.loads(spec) if isinstance(spec, str) else spec
        rules = [AdversaryRule(**r) for r in doc.get("rules", [])]
        return cls(seed=int(doc.get("seed", 0)), rules=rules)

    @classmethod
    def from_file(cls, path: str) -> "AdversaryPlan":
        with open(path) as f:
            return cls.from_json(json.load(f))

    @classmethod
    def from_spec(cls, spec: str) -> "AdversaryPlan":
        """The CLI dual form — a JSON file path or inline JSON (the one
        dispatch rule every --*-plan flag shares)."""
        import os

        return cls.from_file(spec) if os.path.exists(spec) \
            else cls.from_json(spec)

    def to_json(self) -> str:
        def rule_doc(r: AdversaryRule) -> dict:
            doc = {"attack": r.attack, "ranks": r.ranks}
            if r.rounds is not None:
                doc["rounds"] = r.rounds
            if r.attack in ("sign_flip", "scale"):
                doc["factor"] = r.factor
            if r.attack == "gaussian":
                doc["sigma"] = r.sigma
            if r.attack == "shift":
                doc["z"] = r.z
            return doc

        return json.dumps({"seed": self.seed,
                           "rules": [rule_doc(r) for r in self.rules]})

    def byzantine_ranks(self) -> set[int]:
        return {r for rule in self.rules for r in rule.ranks}


# ------------------------------------------------------------------ host
def perturb_leaves(plan: AdversaryPlan, leaves, global_leaves, rank: int,
                   round_idx: int):
    """Apply every rule matching ``(rank, round_idx)`` to the wire leaves
    (numpy arrays), in rule order — the cross-process client's attack
    path. Returns new arrays; the honest leaves are never mutated. Only
    FLOATING leaves are attacked (both paths agree): integer leaves (step
    counters and the like) carry no gradient signal, and perturbing them
    would silently promote their wire dtype."""
    out = [np.array(v, copy=True) for v in leaves]
    g = [np.asarray(v) for v in global_leaves]

    def each(fn):
        return [fn(v, gv).astype(v.dtype)
                if np.issubdtype(v.dtype, np.floating) else v
                for v, gv in zip(out, g)]

    for rule_idx, rule in enumerate(plan.rules):
        if rank not in rule.ranks or not rule.in_window(round_idx):
            continue
        if rule.attack == "sign_flip":
            out = each(lambda v, gv: gv - rule.factor * (v - gv))
        elif rule.attack == "scale":
            out = each(lambda v, gv: gv + rule.factor * (v - gv))
        elif rule.attack == "gaussian":
            rs = np.random.RandomState(
                _attack_seed(plan.seed, rule_idx, rank, round_idx))
            out = each(lambda v, gv: v + rule.sigma
                       * rs.standard_normal(v.shape))
        elif rule.attack == "nan":
            out = each(lambda v, gv: np.full_like(v, np.nan))
        else:  # shift
            out = each(lambda v, gv: v - rule.z * np.std(v - gv))
    return out


# --------------------------------------------------------------- in-graph
def make_in_graph_injector(plan: AdversaryPlan, num_slots: int):
    """Compile ``plan`` into ``fn(stacked_params, global_params,
    round_idx) -> stacked_params`` for the jitted round program. Rules are
    static (they shape the program); ``round_idx`` is traced, so the scan
    block runs one compiled program for every round — window membership
    becomes a traced predicate feeding ``jnp.where`` masks. Perturbed
    values replace honest ones via ``where`` (never arithmetic blending:
    ``s + m*(nan - s)`` would leak NaN through a zero mask)."""
    import jax
    import jax.numpy as jnp

    rules = list(plan.rules)
    slot_masks = []
    for rule in rules:
        m = np.zeros((num_slots,), np.float32)
        for r in rule.ranks:
            if 1 <= r <= num_slots:
                m[r - 1] = 1.0
        slot_masks.append(m)

    def injector(stacked, global_tree, round_idx):
        out = stacked
        for rule_idx, (rule, slots) in enumerate(zip(rules, slot_masks)):
            if rule.rounds is None:
                active = jnp.bool_(True)
            else:
                active = ((round_idx >= rule.rounds[0])
                          & (round_idx < rule.rounds[1]))
            mask = jnp.asarray(slots) * active

            if rule.attack == "gaussian":
                key = jax.random.fold_in(
                    jax.random.fold_in(
                        jax.random.PRNGKey(plan.seed), rule_idx), round_idx)
                n_leaves = len(jax.tree.leaves(out))
                keys = iter(jax.random.split(key, n_leaves))

            def attack(s, g):
                if rule.attack == "sign_flip":
                    return g[None] - rule.factor * (s - g[None])
                if rule.attack == "scale":
                    return g[None] + rule.factor * (s - g[None])
                if rule.attack == "gaussian":
                    return s + rule.sigma * jax.random.normal(
                        next(keys), s.shape, s.dtype)
                if rule.attack == "nan":
                    return jnp.full_like(s, jnp.nan)
                # shift: per-client, per-leaf std of the own update
                return s - rule.z * jnp.std(
                    s - g[None], axis=tuple(range(1, s.ndim)),
                    keepdims=True).astype(s.dtype)

            # floating leaves only, matching perturb_leaves (the host
            # path): integer leaves carry no gradient signal, and
            # jax.random.normal cannot even draw in their dtype. The slot
            # mask is sliced to the stacked leading dim: under a churn
            # trace a round's cohort can be smaller than num_slots, and
            # slot i keeps meaning cohort position i
            out = jax.tree.map(
                lambda s, g: jnp.where(
                    mask[: s.shape[0]].reshape(
                        (s.shape[0],) + (1,) * (s.ndim - 1)) > 0,
                    attack(s, g).astype(s.dtype), s)
                if jnp.issubdtype(s.dtype, jnp.floating) else s,
                out, global_tree)
        return out

    return injector
