"""ChaosCommManager — executes a FaultPlan around any BaseCommManager.

The wrapper intercepts the two choke points every transport shares:

- **send**: ``send_message`` applies message-level faults (drop, delay,
  duplicate, reorder, straggle, partition, crash) before delegating; a
  send-direction *corrupt* is applied to the encoded bytes by hooking the
  inner manager's ``_encode`` (so it works identically for loopback, gRPC,
  and MQTT — all of which route their outbound frames through it);
- **recv**: the inner manager's ``_receive_frame`` is replaced, so inbound
  raw frames can be dropped / delayed / duplicated / reordered / corrupted
  *before* decode — which is exactly where a corrupt frame must then be
  caught by the CRC32 integrity check and counted, not raised
  (``comm/base.py``).

Fault semantics (chosen to mirror the deployment failure each models):

- ``drop``       the frame vanishes (lossy link);
- ``delay``      the frame arrives ``delay_s`` later, off-thread (latency
                 spike — subsequent frames are NOT held back);
- ``duplicate``  the frame is delivered twice (at-least-once redelivery);
                 on gRPC the SAME stamped (rank, epoch, seq) wire frame is
                 re-sent, so the receiver's exactly-once dedup gate is what
                 must drop it; seq-less transports re-deliver the message;
- ``reorder``    the frame is held until the next frame on its link passes
                 it (out-of-order delivery; a 0.2 s backstop timer releases
                 a held frame with no successor so protocols can't wedge);
- ``corrupt``    one byte of the wire frame is flipped (bit rot / truncated
                 write) — the receiver must drop-and-count, not crash;
- ``partition``  ranks in different groups black-hole each other's frames
                 (netsplit: silent loss, like a firewalled TCP link);
- ``crash``      the rank goes dark: its sends vanish, its inbound drops,
                 and sends TO it raise ConnectionError (connection refused
                 by a dead process) — which is what drives the server's
                 elastic undeliverable-rank bookkeeping and the dead-rank
                 reprobe rejoin when the window ends ("restart");
- ``straggle``   a synchronous ``delay_s`` sleep in the sender's thread
                 (slow client compute/uplink) — the round watchdog's prey.

Composition on one frame: the first firing rule of each fault kind wins;
``drop`` suppresses every other fault (nothing was delivered, so nothing
else "happened"); ``reorder`` supersedes ``delay`` (the hold IS a delay);
``duplicate``/``corrupt`` compose with either. The ledger and the
``comm_faults_injected_total`` metric record exactly the faults APPLIED
under these rules — never a decision that was then suppressed. Every
decision is deterministic per (seed, rule, link, link-seq) — see
chaos/plan.py.
"""

from __future__ import annotations

import collections
import logging
import threading
import time

from fedml_tpu.chaos.plan import FaultPlan
from fedml_tpu.comm.message import Message
from fedml_tpu.obs import comm_instrument as _obs

log = logging.getLogger("fedml_tpu.chaos")

# fedavg tags frames with "round_idx" (distributed/fedavg/message_define);
# other protocols use "round" — either marks the frame's protocol round
_ROUND_KEYS = ("round_idx", "round")

_REORDER_BACKSTOP_S = 0.2

_Firing = collections.namedtuple("_Firing", ["idx", "rule"])


def corrupt_bytes(frame: bytes, seed: int, seq: int) -> bytes:
    """Flip one deterministically-chosen byte of the frame, never in the
    first 8: the magic survives (so the CRC path, not the unknown-frame
    path, is exercised) and so does byte range 4:8 — which in a
    zlib-wrapped frame is the ADVISORY raw_len the decoder ignores; a flip
    there would be a counted-but-no-op corruption. From byte 8 on, every
    position is integrity-checked in both framings (FMT2: the CRC field
    itself or the CRC-covered body; FMZ1: the deflate stream)."""
    import hashlib

    if len(frame) <= 9:
        return bytes([frame[0] ^ 0xFF]) + frame[1:] if frame else frame
    h = hashlib.sha256(f"corrupt|{seed}|{seq}".encode()).digest()
    pos = 8 + int.from_bytes(h[:8], "little") % (len(frame) - 8)
    return frame[:pos] + bytes([frame[pos] ^ 0xFF]) + frame[pos + 1:]


class ChaosCommManager:
    """Duck-typed BaseCommManager proxy executing a FaultPlan.

    Only built by ``chaos.maybe_wrap`` (via ``make_comm_manager``) when a
    plan is installed; with no plan the comm stack never sees this class.
    """

    def __init__(self, inner, plan: FaultPlan, rank: int):
        self.inner = inner
        self.plan = plan
        self.rank = int(rank)
        self._lock = threading.Lock()
        self._seq: dict[tuple, int] = {}        # link -> frames seen
        self._fired: dict[tuple, int] = {}      # (rule, link) -> injections
        self._round: dict[tuple, int] = {}      # link -> last round tag
        self._held: dict[tuple, object] = {}    # link -> reorder stash
        self._tls = threading.local()

        # hook the inner manager's shared frame choke points (instance
        # attributes, so each wrapped manager is hooked independently)
        self._orig_receive = inner._receive_frame
        inner._receive_frame = self._recv_hook
        self._orig_encode = inner._encode
        inner._encode = self._encode_hook
        # gRPC only: hook the stub so a 'duplicate' re-sends the SAME
        # stamped (rank, epoch, seq) wire frame — a true redelivery that
        # the receiver's exactly-once ``_accept_frame`` gate must drop.
        # (Calling send_message twice would stamp a fresh seq and slip
        # past dedup; transports without a seq layer — loopback/MQTT —
        # duplicate at the message level instead, exercising the
        # protocol's round-tag/slot-overwrite invariants.)
        self._orig_stub = getattr(inner, "_stub", None)
        if self._orig_stub is not None:
            inner._stub = self._stub_hook

    # --------------------------------------------------- BaseCommManager API
    @property
    def backend_name(self) -> str:
        return self.inner.backend_name

    def add_observer(self, observer) -> None:
        self.inner.add_observer(observer)

    def remove_observer(self, observer) -> None:
        self.inner.remove_observer(observer)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self._flush_held()
        self.inner.stop_receive_message()

    # -------------------------------------------------------------- helpers
    def _next_seq(self, link: tuple) -> int:
        with self._lock:
            s = self._seq.get(link, 0)
            self._seq[link] = s + 1
            return s

    def _round_of(self, params: dict | None, link: tuple) -> int | None:
        """The frame's protocol round: its own tag when present (updates the
        link's last-known round), else the link's last-known round — both
        derived from frame content / per-link history only, so windowed
        rules stay deterministic under thread interleaving."""
        if params is not None:
            for k in _ROUND_KEYS:
                r = params.get(k)
                if isinstance(r, (int, float)):
                    with self._lock:
                        self._round[link] = int(r)
                    return int(r)
        with self._lock:
            return self._round.get(link)

    def _would_fire(self, rule_idx: int, direction: str, src, dst, seq: int,
                    round_idx) -> bool:
        """Decision only — does NOT charge max_per_link (a fault that a
        higher-priority fault then suppresses must not consume budget, or
        a capped rule composed after an always-drop could never apply)."""
        rule = self.plan.rules[rule_idx]
        if not rule.matches_link(direction, src, dst):
            return False
        if not rule.in_window(round_idx):
            return False
        if not self.plan.fires(rule_idx, direction, src, dst, seq):
            return False
        if rule.max_per_link is not None:
            with self._lock:
                if self._fired.get((rule_idx, direction, src, dst),
                                   0) >= rule.max_per_link:
                    return False
        return True

    def _charge(self, rule_idx: int, direction: str, src, dst) -> None:
        """Consume one unit of the rule's per-link budget — called only
        when its fault is actually APPLIED (after suppression resolution)."""
        if self.plan.rules[rule_idx].max_per_link is None:
            return
        key = (rule_idx, direction, src, dst)
        with self._lock:
            self._fired[key] = self._fired.get(key, 0) + 1

    def _record(self, fault: str, direction: str, src, dst, seq: int,
                round_idx) -> None:
        self.plan.ledger.record(fault, direction, src, dst, seq, round_idx)
        _obs.record_fault(self.backend_name, fault, direction)
        log.info("chaos: %s %s %s->%s seq=%s round=%s",
                 fault, direction, src, dst, seq, round_idx)

    def _crashed(self, rank, round_idx) -> bool:
        # rank 0 is exempt: a crash rule naming the server is a SUPERVISED
        # RESTART (docs/ROBUSTNESS.md §Server crash recovery) executed by
        # the supervision layer through the checkpoint + WAL recovery
        # path — black-holing the coordinator's wire would model a
        # permanent outage no protocol can survive, not a restart
        if rank == 0:
            return False
        return any(r.fault == "crash" and rank in (r.ranks or ())
                   and r.in_window(round_idx) for r in self.plan.rules)

    def _partition_cut(self, src, dst, round_idx):
        """Index of the first partition rule cutting this link, else None."""
        for i, r in enumerate(self.plan.rules):
            if (r.fault == "partition" and r.in_window(round_idx)
                    and r.partition_cut(src, dst)):
                return i
        return None

    # ----------------------------------------------------------------- send
    def send_message(self, msg: "Message") -> None:
        src, dst = self.rank, int(msg.get_receiver_id())
        link = ("send", src, dst)
        seq = self._next_seq(link)
        round_idx = self._round_of(msg.get_params(), link)

        # rank-level faults first: a dead process sends nothing, and a send
        # to a dead process fails like a refused connection (the elastic
        # server's transport-error path; see module docstring)
        if self._crashed(src, round_idx):
            self._record("crash", "send", src, dst, seq, round_idx)
            return
        if self._crashed(dst, round_idx):
            self._record("crash", "send", src, dst, seq, round_idx)
            raise ConnectionError(
                f"chaos: rank {dst} crashed (round {round_idx})")
        cut = self._partition_cut(src, dst, round_idx)
        if cut is not None:
            self._record("partition", "send", src, dst, seq, round_idx)
            return  # netsplit: silent black hole

        # decide-then-apply: collect every firing rule first, record ONLY
        # what is actually applied (a drop suppresses everything else;
        # reorder supersedes delay) — the ledger must never claim a fault
        # that did not happen
        eff = self._firing_faults("send", src, dst, seq, round_idx,
                                  skip=("partition", "crash"))

        def apply(fault):  # ledger + metric + per-link budget, on APPLY only
            self._record(fault, "send", src, dst, seq, round_idx)
            self._charge(eff[fault].idx, "send", src, dst)

        if "drop" in eff:
            apply("drop")
            return
        # gRPC duplicates at the WIRE level (same stamped seq — the dedup
        # gate's prey); seq-less transports re-deliver the message instead
        wire_dup = "duplicate" in eff and self._orig_stub is not None
        copies = 2 if ("duplicate" in eff and not wire_dup) else 1
        corrupt_seq = seq if "corrupt" in eff else None
        for f in ("duplicate", "corrupt"):
            if f in eff:
                apply(f)
        if "straggle" in eff:
            apply("straggle")
            time.sleep(eff["straggle"].rule.delay_s)
        if "reorder" in eff:  # supersedes delay (the hold IS the delay)
            apply("reorder")
            self._hold(link, (msg, corrupt_seq, copies, wire_dup))
            return
        deliver = lambda: self._deliver_send(link, msg, corrupt_seq, copies,
                                             wire_dup)
        if "delay" in eff:
            apply("delay")
            t = threading.Timer(eff["delay"].rule.delay_s, deliver)
            t.daemon = True
            t.start()
        else:
            deliver()

    def _firing_faults(self, direction, src, dst, seq, round_idx, skip=()):
        """{fault: first firing rule of that kind} for this frame. The
        caller records + ``_charge``s exactly the faults it applies."""
        eff: dict[str, "_Firing"] = {}
        for i, rule in enumerate(self.plan.rules):
            if rule.fault in skip or rule.fault in eff:
                continue
            if self._would_fire(i, direction, src, dst, seq, round_idx):
                eff[rule.fault] = _Firing(i, rule)
        return eff

    def _deliver_send(self, link, msg, corrupt_seq, copies=1,
                      wire_dup=False) -> None:
        for _ in range(copies):
            if corrupt_seq is not None:
                self._tls.corrupt_seq = corrupt_seq
            if wire_dup:
                self._tls.wire_dup = True
            try:
                self.inner.send_message(msg)
            finally:
                self._tls.corrupt_seq = None
                self._tls.wire_dup = False
        self._release_held(link)

    def _encode_hook(self, msg, codec=None) -> bytes:
        frame = self._orig_encode(msg, codec)
        seq = getattr(self._tls, "corrupt_seq", None)
        if seq is not None:
            frame = corrupt_bytes(frame, self.plan.seed, seq)
        return frame

    def _stub_hook(self, dest):
        call = self._orig_stub(dest)

        def invoke(frame, **kw):
            out = call(frame, **kw)
            if getattr(self._tls, "wire_dup", False):
                self._tls.wire_dup = False
                try:  # identical stamped bytes: at-least-once redelivery
                    call(frame, **kw)
                except Exception:  # noqa: BLE001 — the dup IS the chaos;
                    # its delivery failing is just loss, not a send error
                    log.warning("chaos: wire-duplicate to %s failed", dest,
                                exc_info=True)
            return out

        return invoke

    # ----------------------------------------------------------------- recv
    def _peek(self, data: bytes):
        """(sender, params) from the raw frame — chaos-path only (the clean
        path decodes exactly once, in ``_receive_frame``). An undecodable
        frame (e.g. already corrupted by the sender's chaos) yields
        (None, None): src-filtered rules stay quiet and the frame proceeds
        to the integrity check."""
        try:
            msg = Message.from_bytes(data)
            return int(msg.get_sender_id()), msg.get_params()
        except Exception:  # noqa: BLE001 — expected under sender-side chaos
            # quiet by design (the integrity check downstream counts the
            # frame), but never invisible: a peek failing for a NON-chaos
            # reason (protocol drift, framing bug) must be diagnosable
            log.debug("chaos: peek failed on a %d-byte frame (proceeding "
                      "to the integrity check)", len(data), exc_info=True)
            return None, None

    def _recv_hook(self, data: bytes) -> None:
        dst = self.rank
        src, params = self._peek(data)
        link = ("recv", src, dst)
        seq = self._next_seq(link)
        round_idx = self._round_of(params, link)

        if self._crashed(dst, round_idx) or self._crashed(src, round_idx):
            self._record("crash", "recv", src, dst, seq, round_idx)
            return
        if self._partition_cut(src, dst, round_idx) is not None:
            self._record("partition", "recv", src, dst, seq, round_idx)
            return

        eff = self._firing_faults("recv", src, dst, seq, round_idx,
                                  skip=("partition", "crash", "straggle"))

        def apply(fault):  # ledger + metric + per-link budget, on APPLY only
            self._record(fault, "recv", src, dst, seq, round_idx)
            self._charge(eff[fault].idx, "recv", src, dst)

        if "drop" in eff:
            apply("drop")
            return
        copies = 2 if "duplicate" in eff else 1
        if "corrupt" in eff:
            apply("corrupt")
            data = corrupt_bytes(data, self.plan.seed, seq)
        if "duplicate" in eff:
            apply("duplicate")
        if "reorder" in eff:  # supersedes delay (the hold IS the delay)
            apply("reorder")
            self._hold(link, (data, copies))
            return
        deliver = lambda: self._deliver_recv(link, data, copies)
        if "delay" in eff:
            apply("delay")
            t = threading.Timer(eff["delay"].rule.delay_s, deliver)
            t.daemon = True
            t.start()
        else:
            deliver()

    def _deliver_recv(self, link, data, copies=1) -> None:
        for _ in range(copies):
            self._orig_receive(data)
        self._release_held(link)

    # -------------------------------------------------------------- reorder
    def _hold(self, link, item) -> None:
        """Stash a frame until the link's next frame passes it. A frame
        with no successor (last of its link) is released by a backstop
        timer so a reordered FINISH can't wedge the protocol forever. The
        timer is pinned to ITS item: a stale timer whose hold was already
        released by a successor must not prematurely release a newer hold
        on the same link."""
        with self._lock:
            prev = self._held.pop(link, None)
            self._held[link] = item
        if prev is not None:  # two holds back-to-back: release the older
            self._emit(link, prev)
        t = threading.Timer(_REORDER_BACKSTOP_S,
                            lambda: self._release_held(link, only=item))
        t.daemon = True
        t.start()

    def _release_held(self, link, only=None) -> None:
        """Release the link's held frame; with ``only`` set, release it
        only if it is still that exact frame (backstop-timer identity)."""
        with self._lock:
            item = self._held.get(link)
            if item is None or (only is not None and item is not only):
                return
            del self._held[link]
        self._emit(link, item)

    def _emit(self, link, item) -> None:
        try:
            if link[0] == "send":
                msg, corrupt_seq, copies, wire_dup = item
                for _ in range(copies):
                    if corrupt_seq is not None:
                        self._tls.corrupt_seq = corrupt_seq
                    if wire_dup:
                        self._tls.wire_dup = True
                    try:
                        self.inner.send_message(msg)
                    finally:
                        self._tls.corrupt_seq = None
                        self._tls.wire_dup = False
            else:
                data, copies = item
                for _ in range(copies):
                    self._orig_receive(data)
        except Exception:  # noqa: BLE001 — a held frame is already "in the
            # network"; its delayed delivery failing (peer gone) is loss,
            # not a sender error to re-raise on an unrelated thread
            log.warning("chaos: releasing held frame on %s failed", link,
                        exc_info=True)

    def _flush_held(self) -> None:
        with self._lock:
            held = list(self._held.items())
            self._held.clear()
        for link, item in held:
            self._emit(link, item)
