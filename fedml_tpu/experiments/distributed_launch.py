"""Cross-process distributed launcher — the mpirun / fed_launch analogue.

The reference launches `mpirun -np N+1 python3 main_fedavg.py ...`
(fedml_experiments/distributed/fedavg/run_fedavg_distributed_pytorch.sh:
16-35) with rank from MPI and routing from hostfiles/grpc_ipconfig.csv.
Here each party is started explicitly (or via run_fedavg_distributed.sh):

    # server
    python -m fedml_tpu.experiments.distributed_launch --rank 0 \
        --world_size 5 --backend grpc --dataset mnist --model lr
    # clients 1..4 likewise (same flags, different --rank)

Routing: --ip_config CSV (receiver_id,ip — grpc_ipconfig.csv parity) or
everything on 127.0.0.1 by default. The server process prints the eval
history when the job completes; worker count must be
client_num_per_round (one process per sampled client, FedAvgAPI.py:20-28).

--algo selects the algorithm on the shared runtime (the reference's unified
multi-algorithm launcher, fedml_experiments/distributed/fed_launch/main.py):
fedavg | fedopt (server optimizer) | fedprox (proximal clients) |
fedavg_robust (server defenses) | turboaggregate (masked secure
aggregation with dropout recovery — docs/ROBUSTNESS.md §Secure
aggregation).
"""

from __future__ import annotations

import argparse
import json
import logging


def add_args(p: argparse.ArgumentParser):
    p.add_argument("--rank", type=int, required=True, help="0 = server")
    p.add_argument("--algo", type=str, default="fedavg",
                   choices=["fedavg", "fedopt", "fedprox", "fedavg_robust",
                            "turboaggregate"])
    # fedopt (main_fedopt.py:54-60 flag parity)
    p.add_argument("--server_optimizer", type=str, default="sgd")
    p.add_argument("--server_lr", type=float, default=1.0)
    p.add_argument("--server_momentum", type=float, default=0.9)
    # fedprox
    p.add_argument("--fedprox_mu", type=float, default=0.1)
    # fedavg_robust (robust_aggregation.py:33-36 flag parity)
    p.add_argument("--defense_type", type=str, default="norm_diff_clipping")
    p.add_argument("--norm_bound", type=float, default=30.0)
    p.add_argument("--stddev", type=float, default=0.025)
    p.add_argument("--noise_multiplier", type=float, default=1.0,
                   help="z for --defense_type dp (accounted DP-FedAvg; "
                        "also the masked secure tier's DP mode — "
                        "--algo turboaggregate --defense_type dp)")
    # masked secure aggregation (--algo turboaggregate,
    # docs/ROBUSTNESS.md §Secure aggregation)
    p.add_argument("--secagg_threshold_t", "--secagg-threshold-t",
                   dest="secagg_threshold_t", type=int, default=None,
                   help="turboaggregate: Shamir threshold t — decoding "
                        "any round needs >= t+1 surviving cohort slots; "
                        "below that the round sheds + re-broadcasts "
                        "(default: min(2, cohort-1))")
    p.add_argument("--secagg_quant_scale", "--secagg-quant-scale",
                   dest="secagg_quant_scale", type=float, default=2**16,
                   help="turboaggregate: fixed-point scale quantizing "
                        "updates into GF(2^31-1); construction refuses "
                        "cohorts that would wrap the field "
                        "(collectives/finite_field.assert_field_capacity)")
    p.add_argument("--secagg_max_abs", "--secagg-max-abs",
                   dest="secagg_max_abs", type=float, default=4.0,
                   help="turboaggregate: promised bound on any masked "
                        "update coordinate (the field-capacity guard's "
                        "max|w|); DP mode uses --norm_bound instead")
    p.add_argument("--edges", type=int, default=0,
                   help="hierarchical 2-tier topology (docs/ROBUSTNESS.md "
                        "§Hierarchical tiers): ranks 1..E become EDGE "
                        "AGGREGATORS that tree-reduce their worker "
                        "block's sanitized uplinks and forward ONE "
                        "pre-aggregated update each — root fan-in is "
                        "O(edges), and tree == flat stays bitwise under "
                        "--sum_assoc pairwise. Pair with --aggregator to "
                        "arm two-phase cross-tier robust gating (edges "
                        "forward per-client evidence, the root returns "
                        "verdict frames, edges fold only survivors — "
                        "docs/ROBUSTNESS.md §Cross-tier robust gating). "
                        "With --algo turboaggregate the tree runs the "
                        "hierarchical MASKED tier instead: per-block "
                        "pairwise masks, edge-local dropout reveal, one "
                        "unmasked field partial per edge "
                        "(docs/ROBUSTNESS.md §Hierarchical secure "
                        "aggregation). "
                        "Workers are ranks E+1..world_size-1; the "
                        "per-edge block size (workers/edges) must be a "
                        "power of two. 0 = flat (default)")
    p.add_argument("--sum_assoc", "--sum-assoc", dest="sum_assoc",
                   type=str, default="auto", choices=["auto", "pairwise"],
                   help="rank 0: weighted-mean summation association. "
                        "'pairwise' = the canonical balanced-binary fold "
                        "(robust_agg.pairwise_sum) — a flat run becomes "
                        "bitwise-comparable with any --edges topology "
                        "over the same cohort; 'auto' keeps the "
                        "historical tensordot association")
    p.add_argument("--world_size", type=int, required=True,
                   help="client_num_per_round + 1")
    p.add_argument("--backend", type=str, default="grpc",
                   choices=["grpc", "loopback", "mqtt"])
    p.add_argument("--base_port", type=int, default=50000)
    p.add_argument("--ip_config", type=str, default=None,
                   help="csv receiver_id,ip (grpc_ipconfig.csv parity)")
    p.add_argument("--broker_host", type=str, default="127.0.0.1",
                   help="mqtt broker address; for multi-host --serve_broker "
                        "runs rank 0 must also widen --broker_bind")
    p.add_argument("--broker_port", type=int, default=1883)
    p.add_argument("--serve_broker", type=int, default=0,
                   help="mqtt: rank 0 also hosts the bundled loopback broker "
                        "(no external mosquitto needed)")
    p.add_argument("--broker_bind", type=str, default="127.0.0.1",
                   help="--serve_broker bind address; the bundled broker is "
                        "unauthenticated, so widen to 0.0.0.0 only on "
                        "networks where every peer is trusted")
    p.add_argument("--job_id", type=str, default=None,
                   help="mqtt: namespaces topics so jobs sharing a "
                        "persistent broker cannot cross-talk; every rank of "
                        "a job must pass the same value")
    p.add_argument("--warmup", type=int, default=1,
                   help="client ranks: AOT-compile the local-fit program "
                        "(through the persistent compile cache) before "
                        "entering the receive loop, so the first broadcast "
                        "hits a warm executable instead of paying the "
                        "compile inside round 0 (docs/PERFORMANCE.md; "
                        "--warmup 0 restores lazy first-round compiles)")
    p.add_argument("--timeout_s", type=float, default=None,
                   help="failure-detection watchdog (server logs stragglers)")
    p.add_argument("--round_timeout_s", type=float, default=None,
                   help="elastic round deadline: a round idle past this "
                        "aggregates over the clients that DID report and "
                        "moves on (dead/straggler clients are dropped; "
                        "their stale uploads are discarded by round id)")
    p.add_argument("--ckpt_dir", type=str, default=None,
                   help="server round checkpoints; restart resumes the job "
                        "(also arms the durable round WAL at "
                        "<ckpt_dir>/wal — docs/ROBUSTNESS.md §Server "
                        "crash recovery)")
    p.add_argument("--supervise", type=int, default=0, metavar="N",
                   help="rank 0: run the server as a SUPERVISED child "
                        "process and restart it up to N times when it "
                        "dies (SIGKILL, crash, OOM). The child recovers "
                        "through checkpoint + WAL (requires --ckpt_dir); "
                        "clients survive the outage via the gRPC backoff "
                        "and answer the restarted server's resume probe "
                        "(docs/ROBUSTNESS.md §Server crash recovery). "
                        "The child pid is published at "
                        "<ckpt_dir>/server.pid for chaos drivers. 0 = "
                        "run in-process (default)")
    p.add_argument("--async_buffer_k", "--async-buffer-k",
                   dest="async_buffer_k", type=int, default=None,
                   help="rank 0: buffered-async rounds (docs/ROBUSTNESS.md "
                        "§Asynchronous buffered rounds) — no round barrier; "
                        "clients train continuously and the server "
                        "aggregates every K sanitized arrivals with "
                        "staleness-discounted weights, so stragglers "
                        "degrade throughput instead of serializing the "
                        "fleet. K = cohort with --staleness_bound 0 is "
                        "bitwise the synchronous path. Unset = the "
                        "synchronous barrier. --algo fedavg/fedopt/"
                        "fedprox/fedavg_robust; incompatible with "
                        "--sparsify_ratio")
    p.add_argument("--staleness", type=str, default="constant",
                   help="async staleness discount: 'constant' | 'poly:A' "
                        "((1+s)^-A) | 'exp:A' (e^-As) "
                        "(core/async_buffer.py)")
    p.add_argument("--staleness_bound", "--staleness-bound",
                   dest="staleness_bound", type=int, default=None,
                   help="async admission bound: reject-and-requeue updates "
                        "staler than this many global updates (0 = the "
                        "synchronous barrier expressed async; unset = "
                        "admit any staleness, discount-only)")
    p.add_argument("--buffer_deadline_s", "--buffer-deadline-s",
                   dest="buffer_deadline_s", type=float, default=None,
                   help="async: flush a partially-filled buffer after this "
                        "many seconds from its first arrival (the async "
                        "analogue of --round_timeout_s)")
    p.add_argument("--heartbeat_max_age_s", "--heartbeat-max-age-s",
                   dest="heartbeat_max_age_s", type=float, default=None,
                   help="heartbeat-driven cohort admission (sync AND "
                        "async): exclude ranks whose "
                        "fed_last_heartbeat_age_seconds exceeds this from "
                        "the cohort, with a periodic reprobe so a resumed "
                        "rank rejoins (docs/ROBUSTNESS.md)")
    p.add_argument("--aggregator", type=str, default=None,
                   choices=["mean", "median", "trimmed_mean", "krum",
                            "multi_krum", "geometric_median"],
                   help="rank 0: Byzantine-robust aggregation strategy "
                        "(core/robust_agg.py) replacing the weighted mean, "
                        "fronted by the sanitation gate (non-finite + "
                        "norm-outlier rejection with survivor reweighting; "
                        "rejections land in the quarantine ledger / "
                        "fed_updates_rejected_total). Applies to --algo "
                        "fedavg, fedprox, and fedavg_robust "
                        "(docs/ROBUSTNESS.md §Byzantine-robust aggregation)")
    p.add_argument("--byzantine_f", type=int, default=None,
                   help="Byzantine budget f for krum/multi_krum/"
                        "trimmed_mean (default (n-3)//2; krum needs "
                        "n >= 2f+3)")
    p.add_argument("--shard_server_state", type=int, default=0,
                   help="rank 0: partition the global model over this "
                        "process's local devices per the regex "
                        "partition-rule table (core/partition_rules.py); "
                        "uploads stage straight to their shard's device "
                        "placement on arrival and the gather happens only "
                        "at broadcast-pack time (docs/PERFORMANCE.md "
                        "§Partitioned server state). No-op with one local "
                        "device; ignored by --algo turboaggregate (no "
                        "device-resident server plane).")
    p.add_argument("--partition-rules", "--partition_rules",
                   dest="partition_rules", type=str, default=None,
                   help="rank 0, with --shard_server_state: override the "
                        "default partition-rule table — a JSON file path "
                        "or inline JSON [[pattern, rule], ...] "
                        "(core/partition_rules.rules_from_json)")
    p.add_argument("--adversary-plan", "--adversary_plan",
                   dest="adversary_plan", type=str, default=None,
                   help="model-space adversary schedule "
                        "(fedml_tpu/chaos/adversary.py): a JSON file path "
                        "or inline JSON {seed, rules:[{attack, ranks, "
                        "rounds, ...}]} — the listed worker ranks upload "
                        "sign_flip/scale/gaussian/nan/shift attacks on "
                        "their scheduled rounds. Pass the SAME plan to "
                        "every rank (each client applies only its own "
                        "rules); pair with --aggregator on rank 0 for a "
                        "replayable attack-vs-defense experiment")
    p.add_argument("--chaos-plan", "--chaos_plan", dest="chaos_plan",
                   type=str, default=None,
                   help="seeded fault-injection plan (fedml_tpu/chaos): a "
                        "JSON file path or inline JSON with {seed, rules} — "
                        "frame drop/delay/duplicate/reorder/corrupt/"
                        "partition + rank crash/straggle schedules, "
                        "deterministic per seed so a soak run replays "
                        "bit-for-bit (docs/ROBUSTNESS.md). Pass the SAME "
                        "plan to every rank; pair with --round_timeout_s "
                        "so injected losses degrade elastically")
    p.add_argument("--telemetry-dir", "--telemetry_dir", dest="telemetry_dir",
                   type=str, default=None,
                   help="rank 0: write the structured run telemetry here — "
                        "events.jsonl (run header + per-round records: "
                        "sampled ids, span timings, update norm, comm "
                        "byte/message counters; docs/OBSERVABILITY.md) and "
                        "a Prometheus text dump at exit; render with "
                        "scripts/report.py")
    p.add_argument("--metrics_port", "--metrics-port", dest="metrics_port",
                   type=int, default=None, metavar="PORT",
                   help="every rank: serve live /metrics (Prometheus text) "
                        "+ /healthz (JSON run health) over HTTP "
                        "(docs/OBSERVABILITY.md §Live endpoints). Each "
                        "rank binds PORT + rank so one flag covers a "
                        "single-host launch; PORT 0 binds an ephemeral "
                        "port per rank (logged, and in rank 0's run "
                        "header). Rank 0 serves the full health verdict "
                        "(obs/health.py rule table + memory telemetry); "
                        "client ranks serve their process registry")
    p.add_argument("--fleet", type=int, default=0,
                   help="arm the fleet observability plane (docs/"
                        "OBSERVABILITY.md §Fleet rollup): every uplink "
                        "piggybacks a compact per-rank digest (round/wave, "
                        "counter deltas, phase-timing sketch, ε, memory) "
                        "and rank 0 serves the merged per-rank view as "
                        "/fleetz (watch live with scripts/fedtop.py). "
                        "Implies telemetry on rank 0; without an explicit "
                        "--metrics_port rank 0 binds an ephemeral HTTP "
                        "port (logged + in the run header) and CLIENT "
                        "ranks run no HTTP server at all — the in-band "
                        "rollup is their export path. Every rank also "
                        "arms a crash flight recorder (dumps under "
                        "<telemetry-dir|ckpt-dir>/flightrec; stitch with "
                        "scripts/report.py --post-mortem)")
    p.add_argument("--fleet_job", "--fleet-job", dest="fleet_job",
                   type=str, default="",
                   help="optional job label namespacing the fleet rollup "
                        "metric families (the reserved 'job' label on "
                        "fed_fleet_*; run identity itself rides the run_id "
                        "automatically)")
    p.add_argument("--trace-dir", "--trace_dir", dest="trace_dir",
                   type=str, default=None,
                   help="rank 0: enable cross-rank distributed tracing "
                        "(obs/tracing.py) and write the stitched per-round "
                        "timeline here as Chrome trace-event JSON "
                        "(trace.json — load in Perfetto or chrome://"
                        "tracing); round records gain critical-path/"
                        "straggler attribution (render with scripts/"
                        "report.py --critical-path). Implies telemetry; "
                        "clients need no flag — trace context propagates "
                        "in the message headers")
    # experiment surface (subset of cli.py, same names)
    p.add_argument("--model", type=str, default="lr")
    p.add_argument("--dataset", type=str, default="mnist")
    p.add_argument("--data_dir", type=str, default=None)
    p.add_argument("--partition_method", type=str, default=None)
    p.add_argument("--partition_alpha", type=float, default=0.5)
    p.add_argument("--client_num_in_total", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--client_optimizer", type=str, default="sgd")
    p.add_argument("--lr", type=float, default=0.03)
    p.add_argument("--wd", type=float, default=0.0)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--comm_round", type=int, default=10)
    p.add_argument("--frequency_of_the_test", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ci", type=int, default=0)
    p.add_argument("--sparsify_ratio", type=float, default=None,
                   help="top-k sparsified uplinks with error feedback "
                        "(comm/sparse.py): ship only this fraction of the "
                        "model delta per upload; 1.0 = exact dense "
                        "equivalence, unset = dense protocol. Composes "
                        "with --async_buffer_k (uplinks densify against "
                        "the version the dispatch wave carried)")
    p.add_argument("--update_codec", "--update-codec", dest="update_codec",
                   type=str, default=None,
                   choices=["dense", "delta", "delta-int8", "delta-sign1"],
                   help="delta/quantized uplink tier (comm/delta.py, "
                        "docs/PERFORMANCE.md §Wire efficiency): clients "
                        "upload local - global@version; 'delta-int8' "
                        "quantizes it to deadzoned int8 (+deflate, >= 8x "
                        "uplink vs dense f32), 'delta-sign1' to 1-bit "
                        "scaled sign (>= 25x), both with client-side "
                        "error feedback so convergence matches dense. "
                        "Mutually exclusive with --sparsify_ratio; "
                        "composes with --async_buffer_k and the frame "
                        "--compression (payloads are exempt from the "
                        "lossy f16/q8 frame tiers)")
    p.add_argument("--delta_broadcast", "--delta-broadcast",
                   dest="delta_broadcast", type=int, default=0,
                   help="rank 0: broadcast global@r - global@r-1 to warm "
                        "clients (ranks whose last upload proved they "
                        "hold r-1) with a dense fallback for joiners/"
                        "reprobes — the downlink half of the wire-"
                        "efficiency layer. Sync rounds only (ignored "
                        "with --async_buffer_k); delta payloads ride the "
                        "frame lossless, so pair with --compression "
                        "zlib, not f16/q8")
    p.add_argument("--error_feedback", "--error-feedback",
                   dest="error_feedback", type=int, default=1,
                   help="client-side error-feedback residual for the "
                        "lossy uplink tiers (comm/ef.py); 0 is the "
                        "convergence-ablation knob, never the production "
                        "setting")
    p.add_argument("--fused_agg", "--fused-agg", dest="fused_agg",
                   type=int, default=0,
                   help="fused on-device server aggregation (docs/"
                        "PERFORMANCE.md §Fused aggregation): uploads "
                        "stage as raw quantized leaves and one jit per "
                        "arrival runs decode -> densify against the "
                        "device stash, so the server never materializes "
                        "per-client f32 trees on host. Plain folds at "
                        "arrival; --aggregator / armed --sanitize ride "
                        "the staged fused mode (per-arrival evidence "
                        "rows, one verdict jit at flush), bitwise the "
                        "stacked route. Composes with "
                        "--shard_server_state, --async_buffer_k and "
                        "dense --edges; implies pairwise summation. "
                        "Under --algo turboaggregate it selects the "
                        "device-resident mod-p fold for masked ingest "
                        "(flat or --edges), bitwise equal to the host "
                        "fold")
    p.add_argument("--precision", type=str, default="f32",
                   choices=["f32", "bf16"],
                   help="client-compute precision policy (docs/"
                        "PERFORMANCE.md §Mixed precision): bf16 runs the "
                        "local fits on bfloat16 casts of the f32 master "
                        "weights (grad-scale-free; aggregation and the "
                        "server update stay f32); f32 is bit-identical "
                        "to the pre-policy engine")
    p.add_argument("--compression", type=str, default="none",
                   choices=["none", "f16", "q8", "zlib", "f16+zlib",
                            "q8+zlib", "json"],
                   help="wire codec for outgoing frames (comm/message.py): "
                        "f16 halves float32 payloads (lossy ~1e-3 rel), q8 "
                        "quarters them (int8, the aggressive tier), zlib "
                        "deflates losslessly; json emits the REFERENCE's "
                        "nested-list format (is_mobile interop, "
                        "fedavg/utils.py:7-16); receivers auto-detect, so "
                        "ranks may mix settings")
    return p


def init_role(args, data, task, cfg, backend_kw, telemetry=None):
    """Construct this rank's manager for --algo (does not run it)."""
    from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
    from fedml_tpu.distributed.fedavg.api import init_client
    from fedml_tpu.distributed.fedavg.server_manager import FedAvgServerManager

    backend = args.backend.upper()
    if args.algo == "turboaggregate":
        # the masked secure tier's refusal matrix — every unsupported
        # composition is a LOUD error on every rank (the former
        # warn-and-ignore for --shard_server_state included; ranks share
        # argv, so client and server refuse identically, test-pinned)
        # --fused_agg and --edges used to sit in this matrix; they are
        # compositions now (fused masked ingest folds arrivals mod p on
        # device; --edges runs the hierarchical masked tier —
        # docs/ROBUSTNESS.md §Hierarchical secure aggregation)
        incompatible = [name for name, v in (
            ("--shard_server_state",
             getattr(args, "shard_server_state", 0) or None),
            ("--async_buffer_k", getattr(args, "async_buffer_k", None)),
            ("--update_codec", getattr(args, "update_codec", None)),
            ("--sparsify_ratio", getattr(args, "sparsify_ratio", None)),
            ("--aggregator", getattr(args, "aggregator", None)),
            ("--byzantine_f", getattr(args, "byzantine_f", None)),
            ("--delta_broadcast",
             getattr(args, "delta_broadcast", 0) or None),
            ("--heartbeat_max_age_s",
             getattr(args, "heartbeat_max_age_s", None)),
            ("--sum_assoc", None if getattr(args, "sum_assoc", "auto")
             == "auto" else args.sum_assoc),
            # a masked upload carries no model-space structure an
            # adversary plan could perturb meaningfully — silently
            # running it would fake a Byzantine-robustness result
            ("--adversary_plan", getattr(args, "adversary_plan", None)),
        ) if v is not None]
        if incompatible:
            raise ValueError(
                f"--algo turboaggregate (masked secure aggregation) does "
                f"not compose with {incompatible}: masked field vectors "
                "aggregate mod p — there is no server plane to shard, no "
                "per-update structure for codecs or robust estimators, "
                "and the synchronous cohort is the protocol "
                "(docs/ROBUSTNESS.md §Secure aggregation)")
    edges = int(getattr(args, "edges", 0) or 0)
    if edges and args.algo == "turboaggregate":
        # hierarchical masked secure aggregation (docs/ROBUSTNESS.md
        # §Hierarchical secure aggregation): pairwise masks are drawn
        # within each edge block, so every edge strips its block's masks
        # locally (tiered reveal for in-block dropouts) and forwards ONE
        # unmasked field partial — root ingress stays O(edges) frames and
        # tree ≡ flat stays bitwise (mod-p addition is associative).
        from fedml_tpu.distributed.fedavg.hierarchy import EdgeTopology
        from fedml_tpu.distributed.turboaggregate import (
            HierTAAggregator,
            HierTASecureServerManager,
            SecureTrainer,
            TASecureClientManager,
            TASecureEdgeManager,
        )

        topo = EdgeTopology(edges=edges,
                            workers=args.world_size - 1 - edges)
        secagg_kw = dict(
            threshold_t=args.secagg_threshold_t,
            quant_scale=args.secagg_quant_scale,
            defense_type=("dp" if args.defense_type == "dp" else "none"),
            norm_bound=args.norm_bound,
            secagg_max_abs=args.secagg_max_abs)
        if args.rank == 0:
            agg = HierTAAggregator(
                data, task, cfg, topo,
                noise_multiplier=args.noise_multiplier,
                fused_ingest=bool(getattr(args, "fused_agg", 0)),
                **secagg_kw)
            return HierTASecureServerManager(
                agg, rank=0, size=args.world_size, backend=backend,
                ckpt_dir=args.ckpt_dir,
                round_timeout_s=args.round_timeout_s,
                telemetry=telemetry, **backend_kw)
        if args.rank <= edges:
            # edge watchdog at HALF the root deadline, same rationale as
            # the dense tier: block-local reveal/shed resolves before the
            # root's whole-edge elasticity (replay determinism)
            return TASecureEdgeManager(
                args.rank, topo, cfg, backend=backend,
                round_timeout_s=(args.round_timeout_s / 2.0
                                 if args.round_timeout_s else None),
                **secagg_kw, **backend_kw)
        slot = topo.slot_of(args.rank)
        trainer = SecureTrainer(
            args.rank, data, task, cfg, slot=slot,
            peers=list(topo.slots_of_edge(topo.edge_of_slot(slot))),
            **secagg_kw)
        return TASecureClientManager(
            trainer, rank=args.rank, size=args.world_size,
            backend=backend,
            server_rank=topo.edge_rank(topo.edge_of_slot(slot)),
            **backend_kw)
    if edges:
        # hierarchical 2-tier topology: rank 0 root, 1..E edges, rest
        # workers. Dense synchronous protocol; --aggregator (+ the
        # implied sanitation gate) arms the two-phase cross-tier robust
        # protocol (docs/ROBUSTNESS.md §Cross-tier robust gating).
        if args.algo not in ("fedavg", "fedprox"):
            raise ValueError(f"--edges is wired for fedavg/fedprox only "
                             f"(got --algo {args.algo}; "
                             f"--algo turboaggregate takes the masked "
                             f"tree route above)")
        incompatible = [name for name, v in (
            ("--async_buffer_k", getattr(args, "async_buffer_k", None)),
            ("--sparsify_ratio", getattr(args, "sparsify_ratio", None)),
            ("--update_codec", getattr(args, "update_codec", None)),
            ("--delta_broadcast", getattr(args, "delta_broadcast", 0)
             or None),
            ("--shard_server_state", getattr(args, "shard_server_state", 0)
             or None),
            ("--heartbeat_max_age_s", getattr(args, "heartbeat_max_age_s",
                                              None)),
            ("--sum_assoc", None if getattr(args, "sum_assoc", "auto")
             == "auto" else args.sum_assoc),  # tree IS pairwise already
            # --fused_agg used to sit in this matrix; it is a composition
            # now (edge ranks ingest per arrival; their uplink frames are
            # bitwise the stacked edge's, so the root is unchanged)
        ) if v is not None]
        if incompatible:
            raise ValueError(f"--edges does not compose with "
                             f"{incompatible} — run the flat topology")
        from fedml_tpu.distributed.fedavg.hierarchy import (
            EdgeTopology,
            FedAvgEdgeManager,
            HierFedAvgAggregator,
            HierFedAvgServerManager,
        )

        topo = EdgeTopology(edges=edges,
                            workers=args.world_size - 1 - edges)
        robust_agg_name = getattr(args, "aggregator", None)
        if args.rank == 0:
            hier_params = None
            if robust_agg_name and getattr(args, "byzantine_f",
                                           None) is not None:
                hier_params = {"f": args.byzantine_f}
            agg = HierFedAvgAggregator(data, task, cfg, topo,
                                       aggregator=robust_agg_name,
                                       aggregator_params=hier_params)
            return HierFedAvgServerManager(
                agg, rank=0, size=args.world_size, backend=backend,
                ckpt_dir=args.ckpt_dir,
                round_timeout_s=args.round_timeout_s,
                telemetry=telemetry, **backend_kw)
        if args.rank <= edges:
            # every rank shares argv, so the edge derives the two-phase
            # mode from the same --aggregator flag the root arms; the
            # edge watchdog runs at HALF the root deadline so tier-2
            # elasticity resolves before the root's (replay determinism)
            return FedAvgEdgeManager(
                args.rank, topo, backend=backend,
                round_timeout_s=(args.round_timeout_s / 2.0
                                 if args.round_timeout_s else None),
                robust=bool(robust_agg_name),
                fused=bool(getattr(args, "fused_agg", 0)), **backend_kw)
        local_spec = None
        if args.algo == "fedprox":
            from fedml_tpu.distributed.fedprox import prox_spec

            local_spec = prox_spec(cfg, args.fedprox_mu)
        adv = _load_adversary_plan(getattr(args, "adversary_plan", None))
        return init_client(
            data, task, cfg, args.rank, args.world_size, backend,
            local_spec=local_spec, adversary_plan=adv,
            server_rank=topo.edge_rank(
                topo.edge_of_slot(topo.slot_of(args.rank))),
            # adversary plans name 1-based COHORT ranks: tree workers
            # match by slot + 1, so one plan drives flat and tree alike
            adversary_rank=topo.slot_of(args.rank) + 1,
            **backend_kw)
    # robust aggregation (--aggregator): kwargs shared by every aggregator
    # that inherits the FedAvgAggregator gate (turboaggregate excluded —
    # a Shamir share is a masked tensor, not an update to sort or gate)
    agg_kw: dict = {}
    if getattr(args, "sum_assoc", "auto") != "auto":
        agg_kw["sum_assoc"] = args.sum_assoc
    if getattr(args, "fused_agg", 0):
        agg_kw["fused_agg"] = True
    if getattr(args, "aggregator", None):
        agg_kw["aggregator"] = args.aggregator
        if getattr(args, "byzantine_f", None) is not None:
            agg_kw["aggregator_params"] = {"f": args.byzantine_f}
    if getattr(args, "shard_server_state", 0):
        agg_kw["shard_server_state"] = True
        pr = getattr(args, "partition_rules", None)
        # server-only: clients never build an aggregator, and a multi-host
        # launch hands identical argv to every rank — a rules FILE that
        # exists only on the server host must not crash the clients
        if pr and args.rank == 0:
            import os

            from fedml_tpu.core.partition_rules import rules_from_json

            if os.path.exists(pr):
                with open(pr) as f:
                    pr = f.read()
            elif not pr.lstrip().startswith("["):
                # looks like a path, not inline JSON — a typo'd file must
                # fail as file-not-found, not 'Expecting value: line 1'
                raise FileNotFoundError(
                    f"--partition_rules file not found: {pr!r}")
            agg_kw["partition_rules"] = rules_from_json(pr)
    if args.rank == 0:
        if args.algo == "fedopt":
            from fedml_tpu.distributed.fedopt import FedOptAggregator

            agg = FedOptAggregator(
                data, task, cfg, worker_num=args.world_size - 1,
                server_optimizer=args.server_optimizer, server_lr=args.server_lr,
                server_momentum=args.server_momentum, **agg_kw)
        elif args.algo == "fedavg_robust":
            from fedml_tpu.distributed.fedavg_robust import FedAvgRobustAggregator

            agg = FedAvgRobustAggregator(
                data, task, cfg, worker_num=args.world_size - 1,
                defense_type=args.defense_type, norm_bound=args.norm_bound,
                stddev=args.stddev, noise_multiplier=args.noise_multiplier,
                **agg_kw)
        elif args.algo == "turboaggregate":
            from fedml_tpu.distributed.turboaggregate import (
                TAAggregator,
                TASecureServerManager,
            )

            agg = TAAggregator(
                data, task, cfg, worker_num=args.world_size - 1,
                threshold_t=args.secagg_threshold_t,
                quant_scale=args.secagg_quant_scale,
                defense_type=("dp" if args.defense_type == "dp"
                              else "none"),
                norm_bound=args.norm_bound,
                noise_multiplier=args.noise_multiplier,
                secagg_max_abs=args.secagg_max_abs,
                fused_ingest=bool(getattr(args, "fused_agg", 0)))
            return TASecureServerManager(
                agg, rank=0, size=args.world_size, backend=backend,
                ckpt_dir=args.ckpt_dir,
                round_timeout_s=args.round_timeout_s,
                telemetry=telemetry, **backend_kw)
        else:  # fedavg / fedprox share the plain weighted-average server
            agg = FedAvgAggregator(data, task, cfg,
                                   worker_num=args.world_size - 1, **agg_kw)
        srv_kw: dict = {}
        if getattr(args, "async_buffer_k", None) is not None:
            srv_kw.update(async_buffer_k=args.async_buffer_k,
                          staleness=args.staleness,
                          staleness_bound=args.staleness_bound,
                          buffer_deadline_s=args.buffer_deadline_s)
        return FedAvgServerManager(agg, rank=0, size=args.world_size,
                                   backend=backend, ckpt_dir=args.ckpt_dir,
                                   round_timeout_s=args.round_timeout_s,
                                   heartbeat_max_age_s=getattr(
                                       args, "heartbeat_max_age_s", None),
                                   delta_broadcast=bool(getattr(
                                       args, "delta_broadcast", 0)),
                                   telemetry=telemetry, **srv_kw,
                                   **backend_kw)

    # sparse/quantized uplinks apply where the upload is plain weights; a
    # turboaggregate share is a masked tensor whose top-k entries (and
    # round delta) are meaningless (the mask dominates), so it stays dense
    sp = getattr(args, "sparsify_ratio", None) or None
    codec_kw = dict(sparsify_ratio=sp,
                    update_codec=getattr(args, "update_codec", None),
                    error_feedback=bool(getattr(args, "error_feedback", 1)))
    adv = _load_adversary_plan(getattr(args, "adversary_plan", None))
    if args.algo == "fedprox":
        from fedml_tpu.distributed.fedprox import prox_spec

        return init_client(data, task, cfg, args.rank, args.world_size, backend,
                           local_spec=prox_spec(cfg, args.fedprox_mu),
                           adversary_plan=adv, **codec_kw, **backend_kw)
    if args.algo == "turboaggregate":
        from fedml_tpu.distributed.turboaggregate import (
            SecureTrainer,
            TASecureClientManager,
        )

        trainer = SecureTrainer(
            args.rank, data, task, cfg,
            threshold_t=args.secagg_threshold_t,
            quant_scale=args.secagg_quant_scale,
            defense_type=("dp" if args.defense_type == "dp" else "none"),
            norm_bound=args.norm_bound,
            secagg_max_abs=args.secagg_max_abs)
        return TASecureClientManager(trainer, rank=args.rank,
                                     size=args.world_size,
                                     backend=backend, **backend_kw)
    return init_client(data, task, cfg, args.rank, args.world_size, backend,
                       adversary_plan=adv, **codec_kw, **backend_kw)


def _load_adversary_plan(spec: str | None):
    """--adversary-plan: a JSON file path or inline JSON (same dual form
    as --chaos-plan)."""
    if not spec:
        return None
    from fedml_tpu.chaos import AdversaryPlan

    return AdversaryPlan.from_spec(spec)


def _supervise(args, argv) -> int:
    """Rank-0 supervision loop (docs/ROBUSTNESS.md §Server crash
    recovery): run the real server as a child process, restart it up to
    ``--supervise N`` times when it dies abnormally (SIGKILL, crash,
    OOM). Every restart recovers through checkpoint + WAL — the child's
    OWN boot path, nothing supervisor-special — so the supervisor stays
    a dumb loop: spawn, publish the pid, wait, decide. A clean exit (rc
    0) ends the job; exhausting the budget forwards the child's rc (the
    restart-storm health rule fires well before a runaway loop)."""
    import os
    import subprocess
    import sys

    log = logging.getLogger("fedml_tpu.launch")
    if not args.ckpt_dir:
        raise ValueError("--supervise needs --ckpt_dir: the restarted "
                         "server recovers through checkpoint + WAL")
    child_argv = list(sys.argv[1:] if argv is None else argv)
    # strip --supervise (both '--supervise N' and '--supervise=N' forms)
    # so the child runs the server in-process
    out, skip = [], False
    for tok in child_argv:
        if skip:
            skip = False
            continue
        if tok == "--supervise":
            skip = True
            continue
        if tok.startswith("--supervise="):
            continue
        out.append(tok)
    child_argv = out
    os.makedirs(args.ckpt_dir, exist_ok=True)
    pid_path = os.path.join(args.ckpt_dir, "server.pid")
    restarts = 0
    while True:
        child = subprocess.Popen(
            [sys.executable, "-m",
             "fedml_tpu.experiments.distributed_launch", *child_argv])
        # the pid file is the chaos driver's kill handle (ci.sh SIGKILLs
        # it mid-round); atomic-replace so a reader never sees a torn pid
        from fedml_tpu.core.wal import durable_write

        durable_write(pid_path, str(child.pid).encode())
        log.info("supervise: server child pid %d (restart %d/%d)",
                 child.pid, restarts, args.supervise)
        rc = child.wait()
        if rc == 0:
            log.info("supervise: server exited cleanly after %d "
                     "restart(s)", restarts)
            return 0
        restarts += 1
        if restarts > args.supervise:
            log.error("supervise: restart budget %d exhausted (last rc "
                      "%s) — giving up", args.supervise, rc)
            return rc if rc > 0 else 1
        log.warning("supervise: server died (rc %s) — restarting "
                    "(%d/%d); recovery replays checkpoint + WAL",
                    rc, restarts, args.supervise)


def main(argv=None):
    args = add_args(argparse.ArgumentParser("fedml_tpu.distributed")).parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s rank{args.rank} %(name)s %(levelname)s %(message)s",
    )
    if args.rank == 0 and args.supervise:
        raise SystemExit(_supervise(args, argv))
    from fedml_tpu.utils.metrics import set_process_title

    role = ("server" if args.rank == 0
            else f"edge{args.rank}" if args.rank <= (args.edges or 0)
            else f"client{args.rank}")
    set_process_title(f"fedml_tpu:{args.algo}:{role}")
    from fedml_tpu.utils.metrics import enable_compile_cache

    enable_compile_cache()

    # unconditional: an explicit --compression none must also OVERRIDE a
    # codec inherited from the FEDML_COMM_CODEC env var
    from fedml_tpu.comm.message import set_wire_codec

    set_wire_codec(args.compression)

    if args.chaos_plan:
        from fedml_tpu import chaos

        plan = chaos.FaultPlan.from_spec(args.chaos_plan)
        chaos.install_plan(plan)
        logging.getLogger("fedml_tpu.launch").warning(
            "CHAOS plan installed (seed=%d, %d rules) — faults will be "
            "injected on purpose", plan.seed, len(plan.rules))

    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.core.tasks import classification_task, sequence_task, tag_prediction_task
    from fedml_tpu.data.registry import DATASETS, load_dataset
    from fedml_tpu.models import create_model

    spec = DATASETS[args.dataset]
    data = load_dataset(
        args.dataset, data_dir=args.data_dir, client_num=args.client_num_in_total,
        partition_method=args.partition_method, partition_alpha=args.partition_alpha,
        seed=args.seed,
    )
    model = create_model(args.model, output_dim=spec.num_classes)
    task = {"classification": classification_task, "sequence": sequence_task,
            "tags": tag_prediction_task}[spec.task](model)
    n_total = data.num_clients
    n_workers = args.world_size - 1 - int(getattr(args, "edges", 0) or 0)
    if n_workers < 1:
        raise ValueError(f"--world_size {args.world_size} leaves no worker "
                         f"ranks after {args.edges} edges + 1 server")
    worker_slot = args.rank - 1 - int(getattr(args, "edges", 0) or 0)
    if (args.rank != 0 and worker_slot >= 0 and n_workers == n_total
            and args.algo != "turboaggregate"):
        # turboaggregate excluded: SecureTrainer's Shamir-share weights need
        # every cohort member's sample count (turboaggregate.py _round_weight),
        # which a rank-local shard no longer holds
        # full participation: rank r always trains client r-1, so this
        # process keeps only its own shard (load_partition_data_distributed_*
        # parity — the reference's per-rank loaders, cifar10/data_loader.py:433)
        from fedml_tpu.core.client_data import subset_clients

        data = subset_clients(data, [worker_slot])
    cfg = FedAvgConfig(
        comm_round=args.comm_round, client_num_in_total=n_total,
        client_num_per_round=n_workers, epochs=args.epochs,
        batch_size=args.batch_size, client_optimizer=args.client_optimizer,
        lr=args.lr, wd=args.wd, frequency_of_the_test=args.frequency_of_the_test,
        seed=args.seed, ci=bool(args.ci),
        eval_max_samples=(10_000 if args.dataset.startswith("stackoverflow")
                          else None),
        precision=args.precision,
    )

    backend_kw: dict = {"timeout_s": args.timeout_s}
    if args.backend == "grpc":
        backend_kw.update(base_port=args.base_port, ip_table=args.ip_config)
    elif args.backend == "mqtt":
        backend_kw.update(broker_host=args.broker_host,
                          broker_port=args.broker_port, job_id=args.job_id)
        if args.serve_broker and args.rank == 0:
            from fedml_tpu.comm.mqtt_mini import MiniMqttBroker

            broker = MiniMqttBroker(host=args.broker_bind, port=args.broker_port)
            logging.getLogger("fedml_tpu.launch").info(
                "serving MQTT broker on %s:%d", args.broker_bind, broker.port)
    else:
        backend_kw.update(job_id="launch")

    # --metrics_port N: rank r binds N + r (0 = ephemeral everywhere) —
    # live /metrics + /healthz per rank, docs/OBSERVABILITY.md §Live
    # endpoints. Rank 0's server rides its Telemetry bundle (health rules +
    # memwatch implied); client ranks serve a bare registry endpoint. With
    # --fleet and NO explicit --metrics_port, rank 0 still binds an
    # ephemeral port (so /fleetz exists; logged + run header) but client
    # ranks run no HTTP server — the in-band rollup IS their export path,
    # and N surprise listeners on a shared host is exactly what the fleet
    # plane exists to avoid.
    rank_port = (args.metrics_port + (args.rank if args.metrics_port else 0)
                 if args.metrics_port is not None else None)
    fleet_on = bool(args.fleet)
    metrics_server = None
    telemetry = None
    if args.rank == 0 and (args.telemetry_dir or args.trace_dir or fleet_on
                           or rank_port is not None):
        from fedml_tpu.obs import Telemetry

        # --trace-dir alone implies telemetry: the event log (with the
        # critical-path round records) lands next to trace.json;
        # --metrics_port alone gets an in-memory event log (the live
        # endpoints are the output)
        telemetry = Telemetry(log_dir=args.telemetry_dir or args.trace_dir,
                              trace_dir=args.trace_dir,
                              http_port=(0 if rank_port is None and fleet_on
                                         else rank_port),
                              fleet=fleet_on, fleet_job=args.fleet_job)
        if telemetry.http_port is not None:
            logging.getLogger("fedml_tpu.launch").info(
                "live endpoints: http://127.0.0.1:%d/metrics (+ /healthz%s)",
                telemetry.http_port, ", /fleetz" if fleet_on else "")
    elif args.rank != 0 and rank_port is not None:
        from fedml_tpu.obs import start_metrics_server

        metrics_server = start_metrics_server(port=rank_port)
        logging.getLogger("fedml_tpu.launch").info(
            "live endpoints: http://127.0.0.1:%d/metrics (+ /healthz)",
            metrics_server.port)
    if fleet_on:
        # crash flight recorder (obs/flightrec.py) on EVERY rank: rank 0's
        # Telemetry armed one above when a log dir exists; client/edge
        # ranks arm theirs here so a SIGKILL'd fleet still leaves durable
        # per-rank dumps for report.py --post-mortem. All ranks share the
        # launch argv, so <telemetry-dir|ckpt-dir>/flightrec is the same
        # directory everywhere.
        import os as _os

        from fedml_tpu.obs.flightrec import (active_recorder,
                                             install_flight_recorder,
                                             install_sigterm_dump)

        base = args.telemetry_dir or args.ckpt_dir
        if args.rank != 0 and base and active_recorder() is None:
            install_flight_recorder(
                rank=args.rank, out_dir=_os.path.join(base, "flightrec"))
        install_sigterm_dump()
    mgr = init_role(args, data, task, cfg, backend_kw, telemetry=telemetry)
    if args.warmup and args.rank != 0 and hasattr(mgr, "warmup"):
        # AOT-compile before blocking on the first broadcast; rides the
        # persistent compile cache enabled above, so across launches (and
        # across this launch's ranks on one host) only one rank pays the
        # real compile
        rep = mgr.warmup()
        if rep:
            logging.getLogger("fedml_tpu.launch").info(
                "warmup: %s in %.2fs (%d fresh compiles, %d cache hits)",
                rep.get("variants"), rep.get("seconds", 0.0),
                rep.get("fresh_compiles", 0), rep.get("cache_hits", 0))
    try:
        mgr.run()
    finally:
        if telemetry is not None:
            telemetry.close()
        if metrics_server is not None:
            metrics_server.close()
        if fleet_on and args.rank != 0:
            # rank 0's close dump rides telemetry.close(); client/edge
            # ranks flush their ring here so even a clean run leaves the
            # full per-rank post-mortem set
            from fedml_tpu.obs.flightrec import dump_active

            dump_active("close")
    if args.chaos_plan:
        from fedml_tpu import chaos

        plan = chaos.active_plan()
        if plan is not None:
            logging.getLogger("fedml_tpu.launch").info(
                "chaos: %d faults injected %s", len(plan.ledger),
                plan.ledger.counts())
    if args.rank == 0:
        # stdout IS this CLI's interface: the launching script parses the
        # final eval-history JSON from it (the one legitimate bare print
        # in the package — everything else routes through logging/EventLog)
        print(json.dumps(mgr.aggregator.history, default=float))  # fedlint: disable=no-bare-print


if __name__ == "__main__":
    main()
