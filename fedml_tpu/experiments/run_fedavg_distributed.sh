#!/usr/bin/env bash
# Localhost multi-process FedAvg over gRPC — the reference's
# run_fedavg_distributed_pytorch.sh (mpirun -np N+1 on one box) analogue.
#
# Usage: run_fedavg_distributed.sh [CLIENT_NUM] [ROUNDS] [DATASET] [MODEL]
set -euo pipefail
CLIENTS=${1:-4}
ROUNDS=${2:-5}
DATASET=${3:-mnist}
MODEL=${4:-lr}
WORLD=$((CLIENTS + 1))
PORT=${BASE_PORT:-50000}

pids=()
for rank in $(seq 1 "$CLIENTS"); do
  python -m fedml_tpu.experiments.distributed_launch \
    --rank "$rank" --world_size "$WORLD" --backend grpc --base_port "$PORT" \
    --dataset "$DATASET" --model "$MODEL" --comm_round "$ROUNDS" &
  pids+=($!)
done

python -m fedml_tpu.experiments.distributed_launch \
  --rank 0 --world_size "$WORLD" --backend grpc --base_port "$PORT" \
  --dataset "$DATASET" --model "$MODEL" --comm_round "$ROUNDS"

for p in "${pids[@]}"; do wait "$p"; done
