"""Unified experiment launcher (L5).

Mirror of the reference CLI surface: the ~20 argparse flags of
fedml_experiments/distributed/fedavg/main_fedavg.py:48-119 plus the
multi-algorithm dispatch of fedml_experiments/distributed/fed_launch/main.py
and algorithm-specific flags (--server_optimizer/--server_lr main_fedopt.py:
54-60; --defense_type/--norm_bound/--stddev robust_aggregation.py:33-36).

Where the reference wraps this in `mpirun -np N+1` + hostfiles + gpu_mapping
yamls, here `--mesh N` creates an N-device 'clients' mesh; no process
management exists to configure.

Usage:
    python -m fedml_tpu.experiments.cli --algo fedavg --dataset mnist \
        --model lr --client_num_in_total 50 --client_num_per_round 10 \
        --comm_round 20
"""

from __future__ import annotations

import argparse
import json
import logging
import time


def add_args(parser: argparse.ArgumentParser):
    # core flag surface (main_fedavg.py:48-119 parity)
    parser.add_argument("--algo", type=str, default="fedavg",
                        choices=["fedavg", "fedavg_seq", "fedopt", "fedprox",
                                 "fednova",
                                 "fedavg_robust", "hierarchical", "feddf",
                                 "feddf_hard", "fedcon", "fedavg_affinity", "fednas",
                                 "decentralized", "centralized", "turboaggregate",
                                 "fedseg", "split_nn", "fedgkt", "vfl"])
    parser.add_argument("--model", type=str, default="lr")
    parser.add_argument("--dataset", type=str, default="mnist")
    parser.add_argument("--data_dir", type=str, default=None,
                        help="real-dataset directory (fetch + layout: "
                             "scripts/download_<dataset>.sh); absent files "
                             "fall back to shape-identical synthetic data")
    parser.add_argument("--image_size", type=int, default=None,
                        help="square decode resolution for the folder/csv "
                             "image readers (imagenet/gld): 224 = reference "
                             "fidelity, default 64 = study scale")
    parser.add_argument("--partition_method", type=str, default=None,
                        help="homo | hetero (LDA) | hetero-bal | hetero-fix | natural")
    parser.add_argument("--partition_alpha", type=float, default=0.5)
    parser.add_argument("--partition_fix_path", type=str, default=None,
                        help="hetero-fix: frozen net_dataidx_map.txt "
                             "(reference checked-in format)")
    parser.add_argument("--client_num_in_total", type=int, default=None)
    parser.add_argument("--client_num_per_round", type=int, default=10)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--client_optimizer", type=str, default="sgd")
    parser.add_argument("--lr", type=float, default=0.03)
    parser.add_argument("--wd", type=float, default=0.0)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--comm_round", type=int, default=10)
    parser.add_argument("--frequency_of_the_test", type=int, default=5)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--ci", type=int, default=0)
    parser.add_argument("--eval_subset_mode", type=str, default="fixed",
                        choices=["fixed", "fresh"],
                        help="validation-subset policy when eval is capped: "
                             "'fresh' resamples per eval (reference "
                             "FedAVGAggregator semantics), 'fixed' reuses one "
                             "seeded subset")
    parser.add_argument("--local_test_on_all_clients", type=str,
                        default="auto", choices=["auto", "on", "off"],
                        help="per-client eval each eval round (the "
                             "reference's _local_test_on_all_clients, "
                             "fedavg_api.py:117-180); 'auto' = on exactly "
                             "when the dataset has per-client test splits "
                             "and no validation-subset cap")
    # TPU execution surface (replaces --backend/--gpu_mapping/--is_mobile)
    parser.add_argument("--mesh", type=int, default=0,
                        help="devices on the 'clients' mesh axis; 0 = "
                             "single-device vmap. For --algo centralized "
                             "the axis is 'data' (0 = ALL devices when "
                             "--model_parallel > 1 or with fedavg_seq, "
                             "which have no single-device analogue)")
    parser.add_argument("--seq_shards", type=int, default=2,
                        help="fedavg_seq: devices on the 'seq' axis (the "
                             "'clients' axis gets --mesh/seq_shards)")
    parser.add_argument("--seq_impl", type=str, default="ring",
                        choices=["ring", "ulysses"])
    parser.add_argument("--model_parallel", type=int, default=1,
                        help="centralized: devices on a 'model' axis — "
                             "Megatron-style tensor (+MoE expert) "
                             "parallelism via GSPMD specs; composes with "
                             "the remaining devices as the 'data' axis")
    parser.add_argument("--lm_dim", type=int, default=64)
    parser.add_argument("--lm_depth", type=int, default=2)
    parser.add_argument("--lm_heads", type=int, default=4)
    parser.add_argument("--max_batches", type=int, default=None)
    parser.add_argument("--remat", type=int, default=0,
                        help="1 = jax.checkpoint the local-fit forwards "
                             "(recompute activations in backward; fits "
                             "deeper models / longer contexts in HBM)")
    parser.add_argument("--device_data", type=int, default=0,
                        help="1 = HBM-resident train set + per-round index blocks")
    parser.add_argument("--working_set", type=int, default=0,
                        help="with --device_data 1: per-block working-set "
                             "park (upload only the rows a block touches) "
                             "instead of parking the whole train set")
    parser.add_argument("--uint8_pixels", type=int, default=0,
                        help="1 = ship image pixels as uint8, normalize on device")
    parser.add_argument("--bucket_batches", type=int, default=0,
                        help="1 = shrink each round/block's common batch "
                             "depth to the sampled clients' ladder bucket "
                             "(bit-exact; skips padded no-op batch compute "
                             "at the cost of <=4 jit variants)")
    # algorithm-specific
    parser.add_argument("--server_optimizer", type=str, default="sgd")
    parser.add_argument("--server_lr", type=float, default=1.0)
    parser.add_argument("--server_momentum", type=float, default=0.9)
    parser.add_argument("--mu", type=float, default=0.1, help="FedProx mu")
    parser.add_argument("--defense_type", type=str, default="norm_diff_clipping",
                        choices=["norm_diff_clipping", "weak_dp", "dp", "none"])
    parser.add_argument("--norm_bound", type=float, default=30.0)
    parser.add_argument("--stddev", type=float, default=0.025)
    # defense_type=dp (real DP-FedAvg with RDP accounting, core/privacy.py)
    parser.add_argument("--noise_multiplier", type=float, default=1.0)
    parser.add_argument("--dp_delta", type=float, default=1e-5)
    # attack side of fedavg_robust (reference --poison_type/--attack_case,
    # edge_case_examples/data_loader.py:283): 'pixel'/'edge' are the
    # synthetic generators (zero files needed); 'southwest'/'greencar'/
    # 'ardis' read the reference's real archives via --edge_case_train/
    # --edge_case_test (data/poisoning.py inject_edge_case_files). The
    # round log gains backdoor_acc (targeted-task accuracy) at eval rounds.
    parser.add_argument("--poison_type", type=str, default="none",
                        choices=["none", "pixel", "edge", "southwest",
                                 "greencar", "ardis"])
    parser.add_argument("--poison_clients", type=int, default=1,
                        help="first K clients are attacker-controlled")
    parser.add_argument("--poison_target_label", type=int, default=None,
                        help="default: the archive's reference convention "
                             "(southwest 9, greencar 2, ardis from file)")
    parser.add_argument("--edge_case_train", type=str, default=None)
    parser.add_argument("--edge_case_test", type=str, default=None)
    parser.add_argument("--sampling", type=str, default="uniform",
                        choices=["uniform", "size_weighted"],
                        help="per-round client sampling: uniform (reference "
                             "parity, sample-weighted aggregate) or "
                             "size_weighted (P ∝ client size, uniform "
                             "aggregate — the FedAvg paper's alt scheme)")
    parser.add_argument("--async_ckpt", type=int, default=1,
                        help="write round checkpoints off the training "
                             "thread (disk I/O overlaps later rounds; the "
                             "state snapshot still happens synchronously)")
    parser.add_argument("--group_num", type=int, default=2)
    parser.add_argument("--group_comm_round", type=int, default=2)
    parser.add_argument("--distill_steps", type=int, default=20)
    parser.add_argument("--distill_lr", type=float, default=1e-3)
    parser.add_argument("--hard_sample_ratio", type=float, default=1.0)
    parser.add_argument("--fedmix_server", type=int, default=0)
    parser.add_argument("--val_fraction", type=float, default=0.0,
                        help=">0: val-gated early stop of distillation")
    # fedcon (condense_api.py flag surface: train type + ipc)
    parser.add_argument("--condense_train_type", type=str, default="ce",
                        choices=["ce", "soft"])
    parser.add_argument("--images_per_class", type=int, default=2)
    parser.add_argument("--condense_iters", type=int, default=20)
    parser.add_argument("--condense_steps", type=int, default=10)
    parser.add_argument("--condense_init_only", type=int, default=1,
                        help="1 = fedcon_init (condense once); 0 = re-condense")
    parser.add_argument("--recondense_every", type=int, default=5)
    # fedseg (--loss_type/--lr_scheduler surface of the reference fedseg main)
    parser.add_argument("--loss_type", type=str, default="ce")
    parser.add_argument("--lr_scheduler", type=str, default="poly")
    parser.add_argument("--lr_step", type=int, default=30)
    # checkpoint / logging
    parser.add_argument("--ckpt_dir", type=str, default=None)
    parser.add_argument("--resume", action="store_true")
    parser.add_argument("--trace_dir", type=str, default=None,
                        help="capture a jax.profiler XLA/TPU trace "
                             "(TensorBoard/Perfetto; files are large)")
    parser.add_argument("--trace_rounds", type=int, default=3,
                        help="round-loop algos: trace only the first N "
                             "rounds (a whole-run trace of a long job is "
                             "unloadably large); 0 = whole run")
    parser.add_argument("--run_dir", type=str, default="./runs")
    parser.add_argument("--run_name", type=str, default=None)
    # FedNAS (reference main_fednas.py:44-45,78-98): search discovers a
    # genotype; train federatedly trains the derived NetworkCIFAR
    parser.add_argument("--stage", type=str, default="search",
                        choices=["search", "train"],
                        help="fednas: 'search' runs bilevel DARTS search; "
                             "'train' trains the derived fixed-genotype net")
    parser.add_argument("--arch", type=str, default="FedNAS_V1",
                        help="fednas --stage train: genotype name "
                             "(FedNAS_V1/DARTS_V2) or a json file from a "
                             "search run")
    parser.add_argument("--nas_layers", type=int, default=None,
                        help="fednas cell count (default: 4 search / "
                             "8 train, the reference --layers default)")
    parser.add_argument("--init_channels", type=int, default=16)
    parser.add_argument("--auxiliary", type=int, default=0,
                        help="fednas train stage: add the auxiliary head")
    parser.add_argument("--auxiliary_weight", type=float, default=0.4)
    parser.add_argument("--drop_path_prob", type=float, default=0.5)
    parser.add_argument("--nas_method", type=str, default="darts",
                        choices=["darts", "gdas"],
                        help="fednas search: softmax-mixture DARTS or "
                             "Gumbel hard-selection GDAS")
    parser.add_argument("--tau", type=float, default=10.0,
                        help="GDAS gumbel-softmax temperature (static per "
                             "run; the reference anneals it per epoch)")
    return parser


log = logging.getLogger("cli")


def build_api(args):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
    from fedml_tpu.core.tasks import (classification_task, sequence_task,
                                      tag_prediction_task)
    from fedml_tpu.data.registry import DATASETS, load_dataset
    from fedml_tpu.models import create_model

    if args.poison_type != "none" and args.algo != "fedavg_robust":
        # refuse rather than silently run a clean baseline the user
        # believes is poisoned
        raise SystemExit(
            f"--poison_type {args.poison_type} is only wired for "
            "--algo fedavg_robust (the attack/defense engine)")

    if args.algo == "vfl":
        # vertical datasets live in their own registry (feature-partitioned)
        from fedml_tpu.algorithms.vfl import VFLAPI, VFLConfig
        from fedml_tpu.data.tabular import load_vertical, train_test_split_vertical
        from fedml_tpu.models.vfl import DenseTower

        xg, xh, y, vspec = load_vertical(args.dataset, data_dir=args.data_dir,
                                         seed=args.seed)
        (tg, th, ty), _ = train_test_split_vertical(xg, xh, y, seed=args.seed)
        api = VFLAPI(
            DenseTower(num_classes=vspec.num_classes),
            DenseTower(num_classes=vspec.num_classes),
            tg, th, ty,
            VFLConfig(epochs=args.epochs * args.comm_round,
                      batch_size=args.batch_size, guest_lr=args.lr,
                      host_lr=args.lr, seed=args.seed),
            num_classes=vspec.num_classes,
        )
        return api, None

    spec = DATASETS[args.dataset]
    data = load_dataset(
        args.dataset, data_dir=args.data_dir, client_num=args.client_num_in_total,
        partition_method=args.partition_method, partition_alpha=args.partition_alpha,
        seed=args.seed, uint8_pixels=bool(getattr(args, "uint8_pixels", 0)),
        partition_fix_path=args.partition_fix_path, image_size=args.image_size,
    )
    n_total = data.num_clients

    if args.algo == "fedseg":
        from fedml_tpu.algorithms.fedseg import FedSegAPI, FedSegConfig
        from fedml_tpu.models.segmentation import DeepLabLite, UNetLite

        seg_model = (DeepLabLite(num_classes=spec.num_classes)
                     if args.model in ("deeplab", "deeplab_lite")
                     else UNetLite(num_classes=spec.num_classes))
        scfg = FedSegConfig(
            comm_round=args.comm_round, client_num_in_total=n_total,
            client_num_per_round=min(args.client_num_per_round, n_total),
            epochs=args.epochs, batch_size=args.batch_size, lr=args.lr,
            wd=args.wd, frequency_of_the_test=args.frequency_of_the_test,
            seed=args.seed, max_batches=args.max_batches, ci=bool(args.ci),
            loss_type=args.loss_type, lr_scheduler=args.lr_scheduler,
            lr_step=args.lr_step,
        )
        return FedSegAPI(data, seg_model, scfg), data

    if args.algo == "split_nn":
        from fedml_tpu.algorithms.split_nn import SplitNNAPI, SplitNNConfig
        from fedml_tpu.models.gkt import SplitLowerNet, SplitUpperNet

        return SplitNNAPI(
            data, SplitLowerNet(),
            SplitUpperNet(num_classes=spec.num_classes),
            SplitNNConfig(epochs=args.epochs, batch_size=args.batch_size,
                          lr=args.lr, client_num=min(args.client_num_per_round,
                                                     n_total),
                          max_batches=args.max_batches, seed=args.seed),
        ), data

    if args.algo == "fedgkt":
        from fedml_tpu.algorithms.fedgkt import FedGKTAPI, FedGKTConfig
        from fedml_tpu.models.gkt import (GKTClientExtractor, GKTClientHead,
                                          GKTServerModel)

        nclients = min(args.client_num_per_round, n_total)
        gcfg = FedGKTConfig(
            comm_round=args.comm_round, client_num_in_total=nclients,
            client_num_per_round=nclients, epochs_client=args.epochs,
            epochs_server=args.epochs, batch_size=args.batch_size,
            lr_client=args.lr, lr_server=args.lr,
            max_batches=args.max_batches, seed=args.seed,
        )
        return FedGKTAPI(
            data, GKTClientExtractor(norm_type="group", blocks=1),
            GKTClientHead(num_classes=spec.num_classes),
            GKTServerModel(norm_type="group", blocks_per_stage=2,
                           num_classes=spec.num_classes),
            gcfg, num_classes=spec.num_classes,
        ), data

    cfg = FedAvgConfig(
        comm_round=args.comm_round, client_num_in_total=n_total,
        client_num_per_round=min(args.client_num_per_round, n_total),
        epochs=args.epochs, batch_size=args.batch_size,
        client_optimizer=args.client_optimizer, lr=args.lr, wd=args.wd,
        frequency_of_the_test=args.frequency_of_the_test, seed=args.seed,
        max_batches=args.max_batches, ci=bool(args.ci),
        remat=bool(args.remat),
        # stackoverflow evals run on a 10k-sample validation subset
        # (FedAVGAggregator._generate_validation_set, :99-107)
        eval_max_samples=(10_000 if args.dataset.startswith("stackoverflow")
                          else None),
        eval_subset_mode=args.eval_subset_mode,
        sampling=args.sampling,
        local_test_on_all_clients=args.local_test_on_all_clients,
    )
    if args.algo == "fedavg_seq":
        from fedml_tpu.algorithms.fedavg_seq import FedAvgSeqAPI
        from fedml_tpu.models.transformer import TransformerLM

        if spec.task != "sequence":
            raise ValueError("fedavg_seq needs a sequence dataset "
                             "(shakespeare / fed_shakespeare / stackoverflow_nwp)")
        from fedml_tpu.mesh.mesh import make_2d_mesh

        # NOTE --mesh 0 means "all devices" here (a 2-axis mesh has no
        # single-device vmap analogue), unlike the 1-axis algos
        sd = max(1, args.seq_shards)
        smesh = make_2d_mesh(args.mesh, sd, ("clients", "seq"),
                             minor_flag="--seq_shards")
        cd = int(smesh.shape["clients"])
        T = int(spec.input_shape[0])
        log.info("fedavg_seq mesh: %d client-shards x %d seq-shards (T=%d)",
                 cd, sd, T)
        return FedAvgSeqAPI(
            data,
            lambda seq_axis: TransformerLM(
                vocab_size=spec.num_classes, dim=args.lm_dim,
                depth=args.lm_depth, num_heads=args.lm_heads, max_len=T,
                seq_axis=seq_axis, seq_impl=args.seq_impl),
            cfg, mesh=smesh), data

    model = create_model(args.model, output_dim=spec.num_classes)
    task = {"classification": classification_task,
            "sequence": sequence_task,
            "tags": tag_prediction_task}[spec.task](model)

    mesh = None
    if args.mesh and args.algo not in ("hierarchical", "centralized"):
        # hierarchical builds its own 2-axis ('groups','clients') mesh
        # below; centralized builds a ('data'[,'model']) mesh in its branch
        mesh = Mesh(np.asarray(jax.devices()[: args.mesh]), ("clients",))

    algo = args.algo
    if algo == "fedavg":
        return FedAvgAPI(
            data, task, cfg, mesh=mesh,
            device_data=bool(getattr(args, "device_data", 0)),
            block_working_set=bool(getattr(args, "device_data", 0))
            and bool(getattr(args, "working_set", 0)),
            bucket_batches=bool(getattr(args, "bucket_batches", 0))), data
    if algo == "fedopt":
        from fedml_tpu.algorithms.fedopt import FedOptAPI

        return FedOptAPI(data, task, cfg, mesh=mesh,
                         server_optimizer=args.server_optimizer,
                         server_lr=args.server_lr,
                         server_momentum=args.server_momentum), data
    if algo == "fedprox":
        from fedml_tpu.algorithms.fedprox import FedProxAPI

        return FedProxAPI(data, task, cfg, mesh=mesh, mu=args.mu), data
    if algo == "fednova":
        from fedml_tpu.algorithms.fednova import FedNovaAPI

        return FedNovaAPI(data, task, cfg, mesh=mesh), data
    if algo == "fedavg_robust":
        from fedml_tpu.algorithms.fedavg_robust import FedAvgRobustAPI

        poisoned_test = None
        if args.poison_type != "none":
            from fedml_tpu.data import poisoning

            if args.poison_clients < 1:
                raise SystemExit("--poison_clients must be >= 1 when "
                                 "--poison_type is set")
            ids = list(range(min(args.poison_clients, data.num_clients)))
            tl = args.poison_target_label
            if args.poison_type == "pixel":
                data, poisoned_test = poisoning.make_backdoor_dataset(
                    data, target_label=0 if tl is None else tl,
                    poison_client_ids=ids)
            elif args.poison_type == "edge":
                data, poisoned_test = poisoning.make_edge_case_dataset(
                    data, target_label=0 if tl is None else tl,
                    poison_client_ids=ids)
            else:  # real archive formats
                if not args.edge_case_train:
                    raise SystemExit(
                        f"--poison_type {args.poison_type} reads the real "
                        "archive: pass --edge_case_train (and optionally "
                        "--edge_case_test)")
                if tl is None:  # ardis: stays None -> labels from the file
                    tl = poisoning.EDGE_CASE_TARGETS.get(args.poison_type)
                data, poisoned_test = poisoning.inject_edge_case_files(
                    data, args.edge_case_train, args.edge_case_test,
                    poison_client_ids=ids, target_label=tl)
        return FedAvgRobustAPI(data, task, cfg, mesh=mesh,
                               defense_type=args.defense_type,
                               norm_bound=args.norm_bound,
                               stddev=args.stddev,
                               noise_multiplier=args.noise_multiplier,
                               poisoned_test=poisoned_test), data
    if algo == "hierarchical":
        from fedml_tpu.algorithms.hierarchical import HierarchicalFLAPI

        hmesh = None
        if args.mesh:
            # --mesh N with hierarchical: ('groups','clients') 2-axis mesh,
            # groups on the slow (DCN-able) axis, clients on ICI
            from fedml_tpu.mesh.mesh import make_hierarchical_mesh

            gd = min(args.group_num, max(1, args.mesh // 2))
            while args.group_num % gd or args.mesh % gd:
                gd -= 1
            if gd == 1:
                log.warning(
                    "hierarchical mesh degenerates to (1, %d): group_num=%d "
                    "shares no factor with --mesh %d, so intra-group syncs "
                    "span ALL devices instead of staying on the fast axis",
                    args.mesh, args.group_num, args.mesh)
            else:
                log.info("hierarchical mesh: %d groups x %d client-shards",
                         gd, args.mesh // gd)
            hmesh = make_hierarchical_mesh(gd, args.mesh // gd)
        return HierarchicalFLAPI(data, task, cfg, group_num=args.group_num,
                                 group_comm_round=args.group_comm_round,
                                 mesh=hmesh), data
    if algo in ("feddf", "feddf_hard"):
        from fedml_tpu.algorithms.feddf import FedDFAPI

        return FedDFAPI(data, task, cfg, mesh=mesh,
                        distill_steps=args.distill_steps,
                        distill_lr=args.distill_lr,
                        hard_sample_ratio=args.hard_sample_ratio,
                        fedmix_server=bool(args.fedmix_server),
                        val_fraction=args.val_fraction,
                        hard_label=(algo == "feddf_hard")), data
    if algo == "fedcon":
        from fedml_tpu.algorithms.fedcon import FedConAPI

        return FedConAPI(data, task, cfg, mesh=mesh,
                         images_per_class=args.images_per_class,
                         condense_iters=args.condense_iters,
                         condense_steps=args.condense_steps,
                         condense_train_type=args.condense_train_type,
                         init_only=bool(args.condense_init_only),
                         recondense_every=args.recondense_every), data
    if algo == "fedavg_affinity":
        from fedml_tpu.algorithms.fedavg_affinity import FedAvgAffinityAPI

        return FedAvgAffinityAPI(data, task, cfg), data
    if algo == "turboaggregate":
        from fedml_tpu.algorithms.turboaggregate import TurboAggregateAPI

        return TurboAggregateAPI(data, task, cfg), data
    if algo == "fednas":
        if args.stage == "train":
            from fedml_tpu.algorithms.fednas import FedNASTrainAPI

            return FedNASTrainAPI(
                data, cfg, mesh=mesh, genotype=args.arch,
                layers=args.nas_layers or 8,
                init_filters=args.init_channels,
                auxiliary=bool(args.auxiliary),
                auxiliary_weight=args.auxiliary_weight,
                drop_path_prob=args.drop_path_prob), data
        from fedml_tpu.algorithms.fednas import FedNASAPI

        return FedNASAPI(data, cfg, mesh=mesh, layers=args.nas_layers or 4,
                         init_filters=args.init_channels,
                         nas_method=args.nas_method, tau=args.tau), data
    if algo == "centralized":
        from fedml_tpu.centralized import CentralizedConfig, CentralizedTrainer

        ccfg = CentralizedConfig(epochs=args.epochs * args.comm_round,
                                 batch_size=args.batch_size, lr=args.lr,
                                 wd=args.wd, seed=args.seed)
        cmesh = None
        if args.mesh or args.model_parallel > 1:
            from fedml_tpu.mesh.mesh import make_2d_mesh, make_client_mesh

            tp = max(1, args.model_parallel)
            if tp > 1:
                cmesh = make_2d_mesh(args.mesh, tp, ("data", "model"),
                                     minor_flag="--model_parallel")
            else:
                cmesh = make_client_mesh(args.mesh or None, axis_name="data")
            dp = int(cmesh.shape["data"])
            if ccfg.batch_size % dp:
                raise ValueError(
                    f"--batch_size {ccfg.batch_size} not divisible by the "
                    f"data-parallel degree {dp} (batch rows shard over "
                    "'data')")
            if ccfg.eval_batch_size % dp:
                # eval batches are masked-padded, so rounding the eval
                # batch up to a divisible size changes layout only
                import dataclasses as _dc

                ccfg = _dc.replace(
                    ccfg,
                    eval_batch_size=-(-ccfg.eval_batch_size // dp) * dp)
        return CentralizedTrainer(task, data.train_x, data.train_y,
                                  data.test_x, data.test_y, ccfg,
                                  mesh=cmesh), data
    raise ValueError(f"unhandled algo {algo}")


def main(argv=None):
    from fedml_tpu.utils.metrics import (RunLogger, enable_compile_cache,
                                         set_process_title, setup_logging)

    args = add_args(argparse.ArgumentParser("fedml_tpu")).parse_args(argv)
    setup_logging(f"fedml-tpu-{args.algo}")
    set_process_title(f"fedml_tpu:{args.algo}:{args.dataset}")
    enable_compile_cache()
    log = logging.getLogger("cli")
    t0 = time.time()
    api, data = build_api(args)
    logger = RunLogger(args.run_dir, args.run_name,
                       config=vars(args))
    log.info("dataset=%s clients=%s algo=%s mesh=%d", args.dataset,
             data.num_clients if data is not None else "vertical", args.algo,
             args.mesh)

    import contextlib

    round_loop = args.algo not in ("centralized", "vfl", "split_nn")
    stack = contextlib.ExitStack()
    if args.trace_dir and not (round_loop and args.trace_rounds > 0):
        # whole-run trace: single-shot algos, or --trace_rounds 0
        from fedml_tpu.utils.tracing import trace

        stack.enter_context(trace(args.trace_dir))
        log.info("capturing XLA trace to %s", args.trace_dir)

    try:
        if args.algo == "centralized":
            api.train()
            for rec in api.history:
                logger.log(rec, step=rec.get("epoch"))
        elif args.algo in ("vfl", "split_nn"):
            hist = api.train(args.comm_round) if args.algo == "split_nn" else api.train()
            for i, rec in enumerate(hist or []):
                logger.log(rec, step=i)
                log.info("%s", rec)
        else:
            start_round = 0
            if args.resume and args.ckpt_dir:
                from fedml_tpu.core.checkpoint import latest_round, restore_round

                lr_ = latest_round(args.ckpt_dir)
                if lr_ is not None:
                    import numpy as np

                    tmpl = {"net": api.net, "server_opt_state": api.server_opt_state,
                            "rng": api.rng, "round": 0}
                    has_dp = getattr(api, "accountant", None) is not None
                    st = None
                    if has_dp:
                        # prefer the checkpoint's persisted RDP totals: a
                        # recompute with THIS run's q/z misstates epsilon
                        # when --noise_multiplier or client counts changed
                        # across the resume (server_manager persists the
                        # same key)
                        try:
                            st = restore_round(
                                args.ckpt_dir, lr_,
                                dict(tmpl, dp_rdp=np.asarray(
                                    api.accountant._rdp)))
                            api.accountant._rdp = np.asarray(st["dp_rdp"])
                        except Exception:
                            st = None  # pre-dp checkpoint: recompute below
                    if st is None:
                        st = restore_round(args.ckpt_dir, lr_, tmpl)
                        if has_dp:
                            # the epsilon claim is CUMULATIVE over the whole
                            # training run: re-charge the pre-resume rounds
                            # (only correct when q and z are unchanged; the
                            # persisted-totals path above avoids even that
                            # assumption)
                            api.accountant.step(api._dp_q, api._dp_z,
                                                rounds=int(st["round"]) + 1)
                    api.load_state(st["net"], st["server_opt_state"], st["rng"])
                    start_round = int(st["round"]) + 1
                    log.info("resumed from round %d", start_round - 1)
            trace_ctx = None
            if args.trace_dir and args.trace_rounds > 0:
                from fedml_tpu.utils.tracing import trace

                trace_ctx = stack.enter_context(contextlib.ExitStack())
                trace_ctx.enter_context(trace(args.trace_dir))
                log.info("tracing rounds %d..%d to %s", start_round,
                         start_round + args.trace_rounds - 1, args.trace_dir)
            ckptr = None  # AsyncCheckpointer, created on first save
            for r in range(start_round, args.comm_round):
                if (trace_ctx is not None
                        and r - start_round == args.trace_rounds):
                    trace_ctx.close()  # stop after the trace window
                    trace_ctx = None
                metrics = api.run_round(r)
                if r % args.frequency_of_the_test == 0 or r == args.comm_round - 1:
                    if hasattr(api, "eval_record"):
                        # FedAvg-family engines: the shared record assembler
                        # (per-client aggregate on natural partitions)
                        rec = api.eval_record(r, metrics)
                    else:
                        ev = api.evaluate() if hasattr(api, "evaluate") else {}
                        if isinstance(ev, (int, float)):  # FedGKT: bare acc
                            ev = {"acc": float(ev), "loss": 0.0}
                        n = float(max(float(metrics.get("count", 1)), 1))
                        rec = {"round": r,
                               "train_loss": float(metrics.get("loss_sum", 0)) / n,
                               "train_acc": float(metrics.get("correct", 0)) / n}
                        if ev:
                            rec["test_acc"] = float(ev["acc"])
                            rec["test_loss"] = float(ev["loss"])
                    if getattr(api, "_poisoned", None) is not None:
                        rec["backdoor_acc"] = float(
                            api.evaluate_backdoor()["acc"])
                    if getattr(api, "accountant", None) is not None:
                        rec["epsilon"] = round(api.epsilon(args.dp_delta), 4)
                    logger.log(rec, step=r)
                    log.info("round %d: %s", r, rec)
                if args.ckpt_dir and (r % 10 == 0 or r == args.comm_round - 1):
                    extra = None
                    if getattr(api, "accountant", None) is not None:
                        import numpy as np

                        # cumulative RDP totals ride the checkpoint so a
                        # resume under different q/z still reports the true
                        # epsilon for the earlier rounds
                        extra = {"dp_rdp": np.asarray(api.accountant._rdp)}
                    if args.async_ckpt:
                        # lazily created; disk write overlaps later rounds
                        if ckptr is None:
                            from fedml_tpu.core.checkpoint import AsyncCheckpointer

                            ckptr = stack.enter_context(
                                AsyncCheckpointer(args.ckpt_dir))
                        ckptr.save(r, api.net, api.server_opt_state, api.rng,
                                   extra_state=extra)
                    else:
                        from fedml_tpu.core.checkpoint import save_round

                        save_round(args.ckpt_dir, r, api.net,
                                   api.server_opt_state, api.rng,
                                   extra_state=extra)
    finally:
        # stop the XLA trace even when training crashes — the trace
        # is most wanted precisely when a run misbehaves
        stack.close()
    logger.finish()
    log.info("done in %.1fs; summary=%s", time.time() - t0,
             json.dumps(logger.summary, default=float))


if __name__ == "__main__":
    main()
