"""Exporters — turn a run's telemetry into files other tools consume.

- ``write_csv``: round records -> CSV (spreadsheet/pandas-friendly; nested
  record blocks are flattened to dotted columns);
- ``write_prometheus``: registry -> text exposition file (node_exporter
  textfile-collector shape — drop it in a scrape directory);
- ``bench_blob``: round records -> the BENCH_r*.json-compatible one-line
  summary (same keys as bench.py's ``_result``), so a telemetry run can
  stand in for a bench run in dashboards;
- ``profile_trace``: re-export of the jax.profiler bridge.

scripts/report.py is the CLI over these.
"""

from __future__ import annotations

import csv

from fedml_tpu.obs.metrics import MetricsRegistry
from fedml_tpu.utils.tracing import trace as profile_trace  # noqa: F401


def _flatten(rec: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in rec.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        elif isinstance(v, list):
            out[key] = " ".join(str(e) for e in v)
        else:
            out[key] = v
    return out


def write_csv(records: list[dict], path: str,
              kinds: tuple[str, ...] = ("round",)) -> list[str]:
    """Write selected event records as CSV; returns the column list. The
    header is the union of flattened keys over all rows (JSONL records are
    heterogeneous — eval blocks only exist on eval rounds)."""
    rows = [_flatten(r) for r in records if r.get("kind") in kinds]
    cols: list[str] = []
    for r in rows:
        for k in r:
            if k not in cols:
                cols.append(k)
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        w.writerows(rows)
    return cols


def write_prometheus(registry: MetricsRegistry, path: str) -> None:
    with open(path, "w") as f:
        f.write(registry.to_prometheus())


def bench_blob(records: list[dict], metric: str = "fedavg_rounds_per_sec",
               platform: str | None = None) -> dict:
    """BENCH-compatible summary from a run's round records.

    Throughput comes from the span timings when present (sum of per-round
    'round' spans — host dispatch + device wait, the same thing bench.py's
    per_round mode times), falling back to event-timestamp extent. Comm
    totals ride along so a wire-heavy run is legible from the blob alone."""
    rounds = [r for r in records if r.get("kind") == "round"]
    if not rounds:
        raise ValueError("no round records in event log")
    span_total = sum(r.get("spans", {}).get("round", 0.0) for r in rounds)
    blocks = [r for r in records if r.get("kind") == "block"]
    block_span = sum(b.get("spans", {}).get("round", 0.0) for b in blocks)
    block_rounds = sum(int(b.get("rounds", 0)) for b in blocks)
    n = len(rounds)
    if span_total > 0:
        # span basis: every round's host-span is measured, so n rounds
        # took span_total seconds
        rate = n / span_total
        basis = "span"
    elif block_span > 0 and block_rounds > 0:
        # block engine: round records are replayed from the scanned block
        # AFTER it executes (their timestamps are microseconds apart and
        # carry no spans) — the real execution time lives on the 'block'
        # events
        rate = block_rounds / block_span
        basis = "block_span"
    else:
        # ts basis (last resort): n record timestamps bound only the n-1
        # intervals BETWEEN rounds (the first round's duration precedes
        # its record)
        ts = [r["ts"] for r in rounds if isinstance(r.get("ts"), (int, float))]
        secs = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
        rate = (n - 1) / secs if secs > 0 else None
        basis = "ts"
    blob = {
        "metric": metric,
        "value": round(rate, 3) if rate else None,
        "unit": "rounds/sec",
        "mode": "telemetry",
        "rounds": n,
        "basis": basis,
    }
    if platform:
        blob["platform"] = platform
    bytes_sent = sum(r.get("comm", {}).get("bytes_sent", 0.0) for r in rounds)
    msgs = sum(r.get("comm", {}).get("messages_sent", 0.0) for r in rounds)
    if msgs:
        blob["comm_bytes_sent"] = int(bytes_sent)
        blob["comm_messages_sent"] = int(msgs)
    evals = [r["eval"] for r in records
             if r.get("kind") in ("round", "eval") and r.get("eval")]
    if evals and "test_acc" in evals[-1]:
        blob["final_test_acc"] = evals[-1]["test_acc"]
    return blob
