"""Device-memory + host-RSS telemetry — the HBM view of a running fleet.

A mesh that exhausts HBM fails late and opaquely (an XLA allocation error
rounds in, long after the growth started); host-side leaks on a
million-client simulation kill the box the same way. This sampler makes
both visible while the run is still alive:

- per-device stats from ``jax.local_devices()[i].memory_stats()`` — TPU
  and GPU backends report ``bytes_in_use`` / ``peak_bytes_in_use`` /
  ``bytes_limit``; the CPU backend returns ``None``, which degrades to a
  graceful no-op (host RSS still reports);
- host RSS from ``/proc/self/status`` (``VmRSS``), the same figure ``top``
  shows — absent on non-procfs hosts, again a graceful no-op.

Gauges (process registry, scraped live via obs/httpd and dumped at close):

    fed_device_bytes_in_use{device}     current HBM bytes per local device
    fed_device_peak_bytes{device}       high-water mark per local device
    fed_device_bytes_limit{device}      allocator capacity (feeds the
                                        health rule table's device_memory
                                        fraction, obs/health.py)
    fed_host_rss_bytes                  resident set size of this process

Opt-in via ``Telemetry(memwatch=...)``: a background daemon thread samples
every ``interval_s`` so scrapes between rounds stay fresh, and
``sample()`` runs synchronously at each round record so the ``mem`` block
on round records is exact-at-emit, not up-to-interval stale. Off (the
default): zero threads, zero gauges, nothing.
"""

from __future__ import annotations

import logging
import threading

from fedml_tpu.obs.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("fedml_tpu.obs.memwatch")


def host_rss_bytes() -> int | None:
    """Resident set size from ``/proc/self/status`` (VmRSS, kB); None where
    procfs is absent — callers must treat None as 'unknown', not 0."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        return None
    return None


def device_memory_stats() -> dict[str, dict]:
    """{device-label: {bytes_in_use, peak_bytes, bytes_limit}} over
    ``jax.local_devices()``. Backends without allocator stats (CPU) return
    None from ``memory_stats()`` and are skipped entirely — an empty dict
    means 'nothing to report', never 'zero bytes'."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no jax / backend not up: no stats
        log.debug("device memory stats unavailable (no jax backend)",
                  exc_info=True)
        return {}
    out: dict[str, dict] = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — per-device probe is best-effort
            log.debug("memory_stats probe failed on %s", d, exc_info=True)
            stats = None
        if not stats:
            continue
        label = f"{d.platform}:{d.id}"
        out[label] = {
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes": int(stats.get("peak_bytes_in_use",
                                        stats.get("bytes_in_use", 0))),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
        }
    return out


class MemoryWatcher:
    """Background sampler feeding the memory gauges. ``sample()`` is also
    callable synchronously (Telemetry calls it at every round record) and
    returns the compact ``mem`` block the event schema carries."""

    def __init__(self, interval_s: float = 5.0,
                 registry: MetricsRegistry | None = None):
        self.interval_s = float(interval_s)
        self.registry = registry or REGISTRY
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self.last: dict | None = None  # most recent sample (health rules)

    # -------------------------------------------------------------- sampling
    def sample(self) -> dict:
        """One synchronous sample: update the gauges, remember it for the
        health rules, and return the round-record ``mem`` block —
        {host_rss_bytes, device_bytes_in_use, device_peak_bytes} with
        absent sources omitted (the block must stay honest on CPU)."""
        block: dict = {}
        rss = host_rss_bytes()
        if rss is not None:
            self.registry.gauge("fed_host_rss_bytes").set(rss)
            block["host_rss_bytes"] = rss
        devs = device_memory_stats()
        for label, st in devs.items():
            self.registry.gauge("fed_device_bytes_in_use",
                                device=label).set(st["bytes_in_use"])
            self.registry.gauge("fed_device_peak_bytes",
                                device=label).set(st["peak_bytes"])
            if st["bytes_limit"]:
                self.registry.gauge("fed_device_bytes_limit",
                                    device=label).set(st["bytes_limit"])
        if devs:
            block["device_bytes_in_use"] = sum(
                st["bytes_in_use"] for st in devs.values())
            block["device_peak_bytes"] = max(
                st["peak_bytes"] for st in devs.values())
        snap = {"host_rss_bytes": rss, "devices": devs}
        with self._lock:
            self.last = snap
        return block

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "MemoryWatcher":
        """Arm the background thread (idempotent). One immediate sample so
        gauges exist before the first interval elapses."""
        if self._thread is not None:
            return self
        self.sample()
        self._thread = threading.Thread(target=self._loop,
                                        name="obs-memwatch", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — telemetry must never kill a run
                log.exception("memory sample failed (continuing)")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
