"""Metrics registry — counters, gauges, streaming histograms, label families.

The reference has no metrics layer at all (SURVEY §5: its observability is
ad-hoc wall-clock prints, FedAVGAggregator.py:59,85-86); FedJAX and
FL_PyTorch both standardize per-round metrics as a simulator feature. This
registry is the process-wide substrate every fedml_tpu layer reports
through: comm backends count messages/bytes into it (obs/comm_instrument),
engines fold round stats into it, and exporters dump it as JSON or
Prometheus text (obs/export).

Design constraints:
- host-side only — nothing here ever runs under jit, so an increment is a
  dict lookup + float add (the comm receive loop calls it per message);
- bounded memory — histograms are geometric-bucketed (no sample retention),
  so a million observations cost the same as ten;
- thread-safe — comm backends dispatch from their own threads.
"""

from __future__ import annotations

import math
import threading


class Counter:
    """Monotonic float counter."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock):
        self.value = 0.0
        self._lock = lock

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self.value += amount


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock):
        self.value = 0.0
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Histogram:
    """Streaming histogram with geometric buckets — O(1) memory, quantile
    estimates within half a bucket ratio (default 10 buckets/decade ->
    <= ~12% relative error), exact count/sum/min/max.

    The default span (1 µs .. 10 ks) covers everything this codebase times:
    queue-dispatch latency (µs), round/pack spans (ms..s), compiles (s..min).
    Values outside the span clamp into the edge buckets (still counted
    exactly in count/sum/min/max).
    """

    __slots__ = ("_lo", "_ratio", "_log_ratio", "_buckets", "count", "total",
                 "vmin", "vmax", "_lock")

    def __init__(self, lock: threading.Lock, lo: float = 1e-6,
                 hi: float = 1e4, buckets_per_decade: int = 10):
        self._lo = lo
        self._ratio = 10.0 ** (1.0 / buckets_per_decade)
        self._log_ratio = math.log(self._ratio)
        n = int(math.ceil(math.log(hi / lo) / self._log_ratio)) + 1
        self._buckets = [0] * n
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self._lock = lock

    def _index(self, v: float) -> int:
        if v <= self._lo:
            return 0
        i = int(math.log(v / self._lo) / self._log_ratio)
        return min(i, len(self._buckets) - 1)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._buckets[self._index(v)] += 1
            self.count += 1
            self.total += v
            self.vmin = min(self.vmin, v)
            self.vmax = max(self.vmax, v)

    def _quantile_locked(self, q: float) -> float:
        """Caller holds self._lock."""
        if not self.count:
            return math.nan
        rank = q * (self.count - 1)
        seen = 0
        for i, c in enumerate(self._buckets):
            if not c:
                continue
            if seen + c > rank:
                # geometric bucket midpoint, clamped to the observed range
                mid = self._lo * self._ratio ** (i + 0.5)
                return min(max(mid, self.vmin), self.vmax)
            seen += c
        return self.vmax

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0 <= q <= 1); nan when empty."""
        with self._lock:
            return self._quantile_locked(q)

    def summary(self) -> dict:
        """Consistent snapshot: every field comes from ONE lock acquisition,
        so a concurrent observe() cannot tear mean (count/total from
        different instants) or make the quantiles reflect three different
        populations."""
        with self._lock:
            if not self.count:
                return {"count": 0}
            return {
                "count": self.count,
                "sum": self.total,
                "mean": self.total / self.count,
                "min": self.vmin,
                "max": self.vmax,
                "p50": self._quantile_locked(0.50),
                "p95": self._quantile_locked(0.95),
                "p99": self._quantile_locked(0.99),
            }


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class MetricsRegistry:
    """Labeled metric families: ``registry.counter(name, **labels)`` returns
    the (created-once) child for that label set. ``snapshot()`` gives a
    plain-dict view; ``to_prometheus()`` the text exposition format."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (kind, {label_key: metric})
        self._families: dict[str, tuple[str, dict]] = {}

    def _child(self, kind: str, factory, name: str, labels: dict):
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (kind, {})
                self._families[name] = fam
            if fam[0] != kind:
                raise ValueError(f"metric {name!r} already registered as "
                                 f"{fam[0]}, not {kind}")
            child = fam[1].get(key)
            if child is None:
                # per-metric lock: observation hot paths (the comm receive
                # loop) must not serialize against unrelated metrics — the
                # registry lock guards only family-dict mutation
                child = factory(threading.Lock())
                fam[1][key] = child
            return child

    def counter(self, name: str, **labels) -> Counter:
        return self._child("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._child("gauge", Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._child("histogram", Histogram, name, labels)

    def remove(self, name: str, **labels) -> bool:
        """Drop one child from a family (True when it existed). The
        cardinality-maintenance escape hatch for per-rank gauges on
        fleet-sized cohorts (obs/comm_instrument heartbeat cap) — callers
        must also invalidate any memo holding the dropped child, or later
        writes land on an orphan the export never sees."""
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return False
            return fam[1].pop(key, None) is not None

    # ------------------------------------------------------------- export
    def snapshot(self) -> dict:
        """{name: {labels-as-sorted-tuple-str: value | histogram summary}}.
        Scalars for counters/gauges; ``Histogram.summary()`` dicts for
        histograms. Keys are stable strings so the snapshot is jsonable."""
        with self._lock:
            fams = {n: (k, dict(c)) for n, (k, c) in self._families.items()}
        out: dict = {}
        for name, (kind, children) in sorted(fams.items()):
            fam_out = {}
            for key, m in sorted(children.items()):
                label_s = ",".join(f"{k}={v}" for k, v in key)
                fam_out[label_s] = (m.summary() if kind == "histogram"
                                    else m.value)
            out[name] = fam_out
        return out

    def total(self, name: str) -> float:
        """Sum of a counter/gauge family over all label sets (0.0 when the
        family does not exist — callers diff totals between rounds)."""
        with self._lock:
            fam = self._families.get(name)
            children = list(fam[1].values()) if fam else []
        return float(sum(c.value for c in children))

    def to_prometheus(self) -> str:
        """Prometheus text exposition (counters/gauges as-is; histograms as
        _count/_sum plus quantile gauges — the summary-metric convention)."""
        with self._lock:
            fams = {n: (k, dict(c)) for n, (k, c) in self._families.items()}
        lines = []
        for name, (kind, children) in sorted(fams.items()):
            lines.append(f"# TYPE {name} "
                         f"{'summary' if kind == 'histogram' else kind}")
            for key, m in sorted(children.items()):
                lb = "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}" \
                    if key else ""
                if kind == "histogram":
                    s = m.summary()  # one consistent snapshot for all lines
                    lines.append(f"{name}_count{lb} {s.get('count', 0)}")
                    lines.append(f"{name}_sum{lb} {s.get('sum', 0.0)}")
                    for q, sk in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                        tag = dict(key)
                        tag["quantile"] = q
                        qlb = "{" + ",".join(f'{k}="{v}"'
                                             for k, v in sorted(tag.items())) + "}"
                        lines.append(f"{name}{qlb} {s.get(sk, math.nan)}")
                else:
                    lines.append(f"{name}{lb} {m.value}")
        return "\n".join(lines) + "\n"


# Process-wide default registry. Comm backends record into this one (they
# have no construction-time hook to receive another), and Telemetry snapshots
# it by default — so a loopback simulation's many in-process managers all
# fold into the same counters, exactly like one server process would.
REGISTRY = MetricsRegistry()
