"""Unified telemetry subsystem (obs = observability).

The reference FedML's only observability is ad-hoc wall-clock prints
(FedAVGAggregator.py:59,85-86); the seed carried only a host-side
``RoundTracer``. This package is the backend-spanning layer everything else
reports through:

- ``metrics``        — MetricsRegistry: counters / gauges / streaming
                       histograms (p50/p95/p99), labeled families, the
                       process-wide default ``REGISTRY``;
- ``events``         — structured JSONL EventLog (run header, per-round
                       records) with rotating-file and in-memory sinks;
- ``comm_instrument``— wire accounting hooks BaseCommManager calls, so
                       loopback/gRPC/MQTT report identically;
- ``telemetry``      — the ``Telemetry`` bundle engines accept
                       (``FedAvgAPI(..., telemetry=...)``,
                       ``--telemetry-dir`` on the distributed launcher);
- ``export``         — CSV / Prometheus-text / BENCH-blob exporters and the
                       jax.profiler bridge;
- ``tracing``        — cross-rank distributed tracing: per-round trace ids,
                       spans with (trace, span, parent, rank), context
                       propagated in message header scalars, stitched
                       per-round timelines + critical-path attribution;
- ``clock``          — the NTP-style clock-offset estimator the stitcher
                       rebases client spans with;
- ``trace_export``   — Chrome trace-event JSON (Perfetto /
                       chrome://tracing) + the critical-path renderer;
- ``httpd``          — live per-rank ``/metrics`` + ``/healthz`` HTTP
                       endpoints (``Telemetry(http_port=)``);
- ``memwatch``       — device-HBM / host-RSS gauges + the ``mem`` block
                       on round records (``Telemetry(memwatch=True)``);
- ``health``         — rule-driven ``HealthMonitor``: edge-triggered
                       alerts (convergence/slowdown/quorum/memory/stall)
                       into the event log + ``fed_alerts_total``;
- ``fleet``          — the fleet observability plane: in-band
                       ``__telemetry`` digests piggybacked on uplink
                       frames, rank 0's ``FleetCollector`` + ``/fleetz``
                       (``Telemetry(fleet=True)``);
- ``flightrec``      — the crash flight recorder: a bounded per-process
                       ring dumped durably on alert/SIGTERM/crash, and
                       the ``report.py --post-mortem`` timeline stitcher.

scripts/report.py renders a run's events.jsonl; docs/OBSERVABILITY.md has
the schema and metric-name reference.
"""

from fedml_tpu.obs.comm_instrument import comm_counters
from fedml_tpu.obs.events import EventLog, JsonlSink, MemorySink, read_jsonl
from fedml_tpu.obs.fleet import (TELEMETRY_KEY, DigestEmitter, FleetCollector,
                                 attach_digest)
from fedml_tpu.obs.flightrec import (FlightRecorder, flight_record,
                                     install_flight_recorder,
                                     render_post_mortem,
                                     uninstall_flight_recorder)
from fedml_tpu.obs.health import DEFAULT_RULES, HealthMonitor
from fedml_tpu.obs.httpd import MetricsHTTPServer, start_metrics_server
from fedml_tpu.obs.memwatch import MemoryWatcher
from fedml_tpu.obs.metrics import REGISTRY, MetricsRegistry
from fedml_tpu.obs.telemetry import Telemetry
from fedml_tpu.obs.tracing import (TRACE_KEY, ClientSpanBuffer,
                                   DistributedTracer, RoundTracer)

__all__ = [
    "DEFAULT_RULES",
    "REGISTRY",
    "TELEMETRY_KEY",
    "TRACE_KEY",
    "ClientSpanBuffer",
    "DigestEmitter",
    "DistributedTracer",
    "EventLog",
    "FleetCollector",
    "FlightRecorder",
    "HealthMonitor",
    "JsonlSink",
    "MemorySink",
    "MemoryWatcher",
    "MetricsHTTPServer",
    "MetricsRegistry",
    "RoundTracer",
    "Telemetry",
    "attach_digest",
    "comm_counters",
    "flight_record",
    "install_flight_recorder",
    "read_jsonl",
    "render_post_mortem",
    "start_metrics_server",
    "uninstall_flight_recorder",
]
