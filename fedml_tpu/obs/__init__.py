"""Unified telemetry subsystem (obs = observability).

The reference FedML's only observability is ad-hoc wall-clock prints
(FedAVGAggregator.py:59,85-86); the seed carried only a host-side
``RoundTracer``. This package is the backend-spanning layer everything else
reports through:

- ``metrics``        — MetricsRegistry: counters / gauges / streaming
                       histograms (p50/p95/p99), labeled families, the
                       process-wide default ``REGISTRY``;
- ``events``         — structured JSONL EventLog (run header, per-round
                       records) with rotating-file and in-memory sinks;
- ``comm_instrument``— wire accounting hooks BaseCommManager calls, so
                       loopback/gRPC/MQTT report identically;
- ``telemetry``      — the ``Telemetry`` bundle engines accept
                       (``FedAvgAPI(..., telemetry=...)``,
                       ``--telemetry-dir`` on the distributed launcher);
- ``export``         — CSV / Prometheus-text / BENCH-blob exporters and the
                       jax.profiler bridge.

scripts/report.py renders a run's events.jsonl; docs/OBSERVABILITY.md has
the schema and metric-name reference.
"""

from fedml_tpu.obs.comm_instrument import comm_counters
from fedml_tpu.obs.events import EventLog, JsonlSink, MemorySink, read_jsonl
from fedml_tpu.obs.metrics import REGISTRY, MetricsRegistry
from fedml_tpu.obs.telemetry import Telemetry

__all__ = [
    "REGISTRY",
    "EventLog",
    "JsonlSink",
    "MemorySink",
    "MetricsRegistry",
    "Telemetry",
    "comm_counters",
    "read_jsonl",
]
