"""Comm-layer instrumentation — wire accounting every backend reports alike.

``BaseCommManager`` calls these hooks at the three points all transports
share (obs must not import comm, so the dependency points this way):

- ``record_send``    — at encode time (``_encode``): messages/bytes out,
  labeled by backend, codec tier, and msg_type;
- ``record_receive`` — at decode time (``_receive_frame``): messages/bytes in;
- ``record_dispatch_latency`` — in the receive loop: seconds a decoded
  message waited in the inbound queue before its handler ran (the reference's
  MPI poll loop put a 0.3 s floor here, mpi/com_manager.py:71-78 — this
  histogram is the proof ours doesn't).

Counters land in the process-wide ``metrics.REGISTRY`` so loopback (many
managers, one process), gRPC, and MQTT runs all read through the same names:

    comm_messages_sent_total{backend,type}
    comm_bytes_sent_total{backend,codec}
    comm_bytes_total{codec,direction}        (direction = uplink|downlink)
    comm_messages_received_total{backend}
    comm_bytes_received_total{backend}
    comm_dispatch_latency_seconds{backend}   (histogram)
"""

from __future__ import annotations

import threading
import time
from functools import lru_cache

from fedml_tpu.obs.metrics import REGISTRY, MetricsRegistry

# Child metrics are memoized so the per-message hot path is just an inc()
# under that metric's own lock — no registry-lock + family-dict + sorted
# label-tuple work per frame. Key cardinality is bounded: a handful of
# backends, codecs, and protocol msg_types. Safe because REGISTRY is
# process-immortal (never reset).


@lru_cache(maxsize=512)
def _sent_msgs(backend: str, msg_type: str):
    return REGISTRY.counter("comm_messages_sent_total", backend=backend,
                            type=msg_type)


@lru_cache(maxsize=64)
def _sent_bytes(backend: str, codec: str):
    return REGISTRY.counter("comm_bytes_sent_total", backend=backend,
                            codec=codec)


@lru_cache(maxsize=16)
def _recv(backend: str):
    return (REGISTRY.counter("comm_messages_received_total", backend=backend),
            REGISTRY.counter("comm_bytes_received_total", backend=backend))


@lru_cache(maxsize=16)
def _dispatch_hist(backend: str):
    return REGISTRY.histogram("comm_dispatch_latency_seconds",
                              backend=backend)


def record_send(backend: str, codec: str, nbytes: int, msg_type: str) -> None:
    _sent_msgs(backend, msg_type).inc()
    _sent_bytes(backend, codec).inc(nbytes)


@lru_cache(maxsize=128)
def _bytes_total(codec: str, direction: str):
    return REGISTRY.counter("comm_bytes_total", codec=codec,
                            direction=direction)


def record_wire_bytes(codec: str, direction: str, nbytes: int) -> None:
    """Per-direction wire accounting (``comm_bytes_total{codec,direction}``,
    direction = uplink | downlink): at fleet fan-in the two directions have
    opposite economics — broadcast dominates downlink, per-client updates
    dominate uplink, and the uplink is the byte budget the delta/quantized
    tiers optimize (docs/PERFORMANCE.md §Wire efficiency). ``codec`` is the
    EFFECTIVE tier: the update codec (topk / delta / delta-int8 /
    delta-sign1) composed with the frame codec when both apply, else the
    frame codec alone — so the A/B evidence separates 'dense f32 frames'
    from 'quantized delta frames' without a second label."""
    _bytes_total(codec, direction).inc(nbytes)


# message types whose wire bytes are accounted under their OWN direction
# label instead of the receiver-derived uplink/downlink split. Registered
# by the protocol module that owns the frame type (the hierarchical tier
# registers e2s_evidence -> 'evidence' and s2e_verdict -> 'verdict', so
# the cross-tier robust protocol's control-plane bytes are separable from
# the update-frame budget in comm_bytes_total — the measured half of the
# O(cohort)-evidence / O(edges)-traffic claim). directional_bytes() sums
# uplink/downlink only, so overridden directions never pollute the
# per-round uplink/downlink record fields.
_DIRECTION_OVERRIDES: dict[str, str] = {}


def register_direction_override(msg_type: str, direction: str) -> None:
    """Account ``msg_type`` frames under ``comm_bytes_total{direction=}``
    with the given label (idempotent; conflicting re-registration is a
    programming error and raises)."""
    prev = _DIRECTION_OVERRIDES.get(str(msg_type))
    if prev is not None and prev != direction:
        raise ValueError(f"direction override for {msg_type!r} already "
                         f"registered as {prev!r} (got {direction!r})")
    _DIRECTION_OVERRIDES[str(msg_type)] = str(direction)


def direction_override(msg_type) -> str | None:
    return _DIRECTION_OVERRIDES.get(str(msg_type))


def directional_bytes(registry: MetricsRegistry | None = None) -> dict:
    """{'uplink': bytes, 'downlink': bytes} summed over codecs (0.0 for a
    direction with no traffic / pre-PR-9 processes)."""
    reg = registry or REGISTRY
    out = {"uplink": 0.0, "downlink": 0.0}
    fam = reg.snapshot().get("comm_bytes_total", {})
    for label_s, v in fam.items():
        for d in out:
            if f"direction={d}" in label_s:
                out[d] += float(v)
    return out


def record_receive(backend: str, nbytes: int) -> None:
    msgs, byts = _recv(backend)
    msgs.inc()
    byts.inc(nbytes)


_tls = threading.local()


def record_dispatch_latency(backend: str, seconds: float) -> None:
    _dispatch_hist(backend).observe(seconds)
    # stash for the handler about to run on THIS thread (the dispatch loop
    # notifies observers right after timing) — the tracing layer reads it
    # to attribute inbound queue wait on the client_round span
    _tls.last_dispatch_s = seconds


def last_dispatch_latency() -> float | None:
    """Queue wait of the message currently being dispatched on this thread
    (None outside a dispatch-loop handler)."""
    return getattr(_tls, "last_dispatch_s", None)


@lru_cache(maxsize=16)
def _retransmits(backend: str):
    return (REGISTRY.counter("comm_retransmits_total", backend=backend),
            REGISTRY.counter("comm_retransmit_bytes_total", backend=backend))


def record_retransmit(backend: str, nbytes: int) -> None:
    """A frame transmitted AGAIN after a delivery failure. ``*_sent_total``
    counts logical frames (one per message, at encode time); this counter
    exposes the extra wire traffic retries add — the number that diagnoses
    a flaky link."""
    msgs, byts = _retransmits(backend)
    msgs.inc()
    byts.inc(nbytes)


@lru_cache(maxsize=32)
def _send_retries(backend: str, reason: str):
    return REGISTRY.counter("comm_send_retries_total", backend=backend,
                            reason=reason)


def record_send_retry(backend: str, reason: str) -> None:
    """A send the transport is about to RETRY after a transient failure,
    labeled by the failure reason (gRPC status-code name: ``unavailable``,
    ``deadline_exceeded``). Complements ``comm_retransmits_total`` (bytes
    moved again) with the per-cause attempt count a flaky-channel
    diagnosis needs; permanent failures are raised, never counted here."""
    _send_retries(backend, reason).inc()


@lru_cache(maxsize=16)
def _duplicates(backend: str):
    return REGISTRY.counter("comm_duplicates_dropped_total", backend=backend)


def record_duplicate(backend: str) -> None:
    """An inbound frame dropped by exactly-once dedup before decode —
    received wire traffic that ``*_received_total`` (decoded frames)
    deliberately excludes."""
    _duplicates(backend).inc()


@lru_cache(maxsize=16)
def _corrupt(backend: str):
    return REGISTRY.counter("comm_corrupt_frames_total", backend=backend)


def record_corrupt_frame(backend: str) -> None:
    """An inbound frame that failed integrity/decode (CRC32 mismatch, bad
    magic, damaged deflate) and was dropped by ``_receive_frame`` instead
    of crashing the dispatch loop. Counted IN ``*_received_total`` (the
    bytes did arrive) but never dispatched."""
    _corrupt(backend).inc()


@lru_cache(maxsize=256)
def _faults(backend: str, fault: str, direction: str):
    return REGISTRY.counter("comm_faults_injected_total", backend=backend,
                            fault=fault, direction=direction)


def record_fault(backend: str, fault: str, direction: str) -> None:
    """A fault the chaos layer (fedml_tpu/chaos) injected on purpose —
    labeled by fault kind and direction so a soak run's summary can assert
    the planned chaos actually happened."""
    _faults(backend, fault, direction).inc()


# ----------------------------------------------------- robust aggregation
# Quarantine bookkeeping (core/robust_agg.py + distributed aggregator):
# the sanitation gate / robust aggregators report every rejected or
# suspected update here so a soak dashboard can watch a poisoning attempt
# the same way it watches wire faults.


@lru_cache(maxsize=16)
def _rejected(reason: str):
    return REGISTRY.counter("fed_updates_rejected_total", reason=reason)


def record_update_rejected(reason: str) -> None:
    """An uploaded update the sanitation gate rejected or a robust
    aggregator suspected, labeled by quarantine reason
    (nonfinite | norm_outlier | suspected)."""
    _rejected(reason).inc()


@lru_cache(maxsize=256)
def _suspected(rank: int):
    return REGISTRY.counter("fed_suspected_rank", rank=rank)


def record_suspected_rank(rank: int) -> None:
    """Per-rank quarantine tally — which worker keeps getting flagged."""
    _suspected(int(rank)).inc()


@lru_cache(maxsize=16)
def _stale(reason: str):
    return REGISTRY.counter("comm_stale_uploads_total", reason=reason)


def record_stale_upload(reason: str) -> None:
    """An upload the aggregator refused to slot: ``stale`` (round tag
    behind/ahead of the current round) or ``unknown_rank`` (index outside
    the worker table) — previously these silently overwrote state."""
    _stale(reason).inc()


# --------------------------------------------------------------- liveness
# Heartbeat/liveness gauges, fed by the machinery that already exists:
# every decoded inbound frame proves its sender alive (BaseCommManager.
# _receive_frame), a gRPC dedup-dropped duplicate still proves liveness
# (grpc_backend.recv), and the elastic server's undeliverable/reprobe
# bookkeeping sets the alive count. Ages are recomputed on snapshot
# (refresh_liveness) so the Prometheus dump and per-round comm deltas
# carry fresh values.

_hb_lock = threading.Lock()
_hb_last_seen: dict[int, float] = {}

# Gauge-cardinality cap for fleet-sized cohorts (docs/OBSERVABILITY.md
# §Fleet rollup): up to HEARTBEAT_RANK_CAP ranks every rank keeps its own
# ``fed_last_heartbeat_age_seconds{rank}`` child (the small-cohort view
# dashboards already use). Above the cap the export would grow
# O(world_size) lines, so refresh_liveness keeps only the
# HEARTBEAT_KEEP_STALEST stalest ranks (the ones an operator actually
# looks for) plus a three-line rollup family
# ``fed_heartbeat_age_rollup{stat=min|max|count}``; the full per-rank
# ages stay queryable via ``heartbeat_ages()`` and the /fleetz view.
HEARTBEAT_RANK_CAP = 64
HEARTBEAT_KEEP_STALEST = 16


@lru_cache(maxsize=256)
def _hb_gauge(rank: int):
    return REGISTRY.gauge("fed_last_heartbeat_age_seconds", rank=rank)


def record_rank_seen(rank) -> None:
    """A frame from ``rank`` arrived — reset its heartbeat age. Runs on
    the per-frame receive path, so the gauge child is memoized like the
    other hot-path hooks (no registry-lock traffic per frame). Above the
    cardinality cap the per-rank gauge write is skipped — the stamps
    (not the gauges) are the source of truth, and refresh_liveness owns
    which children exist."""
    try:
        rank = int(rank)
    except (TypeError, ValueError):
        return  # interop peers may ship non-integer sender ids
    with _hb_lock:
        _hb_last_seen[rank] = time.time()
        over = len(_hb_last_seen) > HEARTBEAT_RANK_CAP
    if not over:
        _hb_gauge(rank).set(0.0)


def refresh_liveness() -> None:
    """Recompute the heartbeat-age gauges from the last-seen stamps (ages
    grow between frames; a gauge is a snapshot, so exporters call this
    right before reading). At or below HEARTBEAT_RANK_CAP ranks: one
    gauge child per rank. Above it: only the HEARTBEAT_KEEP_STALEST
    stalest ranks keep children (the rest are dropped from the family)
    plus the min/max/count rollup — bounded export at any world size."""
    now = time.time()
    with _hb_lock:
        items = list(_hb_last_seen.items())
    if len(items) <= HEARTBEAT_RANK_CAP:
        for rank, ts in items:
            _hb_gauge(rank).set(max(0.0, now - ts))
        return
    ages = {rank: max(0.0, now - ts) for rank, ts in items}
    keep = set(sorted(ages, key=ages.get, reverse=True)
               [:HEARTBEAT_KEEP_STALEST])
    for rank, age in ages.items():
        if rank in keep:
            REGISTRY.gauge("fed_last_heartbeat_age_seconds",
                           rank=rank).set(age)
        else:
            REGISTRY.remove("fed_last_heartbeat_age_seconds", rank=rank)
    # the memo may hold children just removed from the family — writes
    # through it would land on orphans the export never sees
    _hb_gauge.cache_clear()
    vals = list(ages.values())
    REGISTRY.gauge("fed_heartbeat_age_rollup", stat="min").set(min(vals))
    REGISTRY.gauge("fed_heartbeat_age_rollup", stat="max").set(max(vals))
    REGISTRY.gauge("fed_heartbeat_age_rollup", stat="count").set(len(vals))


def heartbeat_ages(now: float | None = None) -> dict[int, float]:
    """rank -> seconds since its last decoded frame (the raw stamps behind
    ``fed_last_heartbeat_age_seconds``), for the heartbeat-driven cohort
    admission gate (docs/ROBUSTNESS.md §Asynchronous buffered rounds). A
    rank with no frame yet is absent — never seen is 'unknown', not
    'infinitely suspect' (a cohort must be dispatchable at boot)."""
    if now is None:
        now = time.time()
    with _hb_lock:
        return {r: max(0.0, now - ts) for r, ts in _hb_last_seen.items()}


def reset_heartbeats() -> None:
    """Clear the per-process last-seen table (tests: loopback simulations
    share the process-wide stamps, so a previous job's silence must not
    mark the next job's ranks suspect)."""
    with _hb_lock:
        _hb_last_seen.clear()
    # the memo may reference children a capped refresh removed — the next
    # job must re-create real ones, not write through orphans
    _hb_gauge.cache_clear()


def suspect_ranks(ranks, max_age_s: float | None, round_idx: int,
                  reprobe_every: int = 4,
                  ages: dict[int, float] | None = None) -> set[int]:
    """The heartbeat admission verdict, as a pure function (unit-testable
    with injected ``ages``): a rank is suspect when its heartbeat age
    exceeds the FRESHEST cohort member's age by more than ``max_age_s`` —
    RELATIVE, not absolute, because ranks are only heard from once per
    round: during a server-side stall every healthy rank's absolute age
    grows past any fixed threshold together (and an absolute rule would
    exclude the whole cohort and deadlock the barrier), while a dead rank
    keeps falling behind its liveliest peer without bound. Suspects are
    re-invited on reprobe rounds (every ``reprobe_every``-th) so a rank
    that resumed (crash window over, partition healed) can rejoin: its
    next frame resets the age and readmits it everywhere. A rank with no
    frame yet is unknown, not suspect (the cohort must be dispatchable at
    boot)."""
    if max_age_s is None:
        return set()
    if ages is None:
        ages = heartbeat_ages()
    if reprobe_every > 0 and round_idx % reprobe_every == 0:
        return set()
    known = [ages[int(r)] for r in ranks if ages.get(int(r)) is not None]
    if not known:
        return set()
    base = min(known)
    return {int(r) for r in ranks
            if ages.get(int(r)) is not None
            and ages[int(r)] - base > max_age_s}


def set_ranks_alive(n: int) -> None:
    """``fed_ranks_alive``: peer ranks currently considered reachable —
    set by the elastic server from its undeliverable/reprobe bookkeeping
    (world - 1 at start, decremented on delivery failure, restored when a
    reprobe succeeds). A server driven by a churn trace also subtracts
    its SCHEDULED-offline ranks, so alive and the quorum rule's shrunken
    expected denominator move together through diurnal troughs."""
    REGISTRY.gauge("fed_ranks_alive").set(n)


def set_ranks_scheduled_offline(n: int) -> None:
    """``fed_ranks_scheduled_offline``: ranks the active churn trace
    (chaos/churn.py) marks away for the current round's window. The
    quorum/fleet_quorum health rules subtract this from their expected
    denominator — a diurnal trough is the fleet's normal state, never an
    outage (docs/ROBUSTNESS.md §Fleet campaigns & client churn). Zero
    (and pre-registered by the churn-driven server) on trace-less runs."""
    REGISTRY.gauge("fed_ranks_scheduled_offline").set(n)


def record_round_idle() -> None:
    """``fed_rounds_idle_total``: rounds the server skipped because every
    undelivered rank was SCHEDULED-offline (an empty night-time cohort —
    the watchdog idles the round instead of re-broadcasting forever)."""
    REGISTRY.counter("fed_rounds_idle_total").inc()


def ensure_churn_families() -> None:
    """Pre-register the churn families at zero the moment a server boots
    with a trace armed — a churn-driven run's export must read 'no idle
    rounds yet', not 'metric missing'. Trace-less runs never call this,
    keeping their export byte-identical."""
    REGISTRY.gauge("fed_ranks_scheduled_offline")
    REGISTRY.counter("fed_rounds_idle_total")


def comm_counters(registry: MetricsRegistry | None = None) -> dict:
    """Flat cumulative totals (all labels summed) — the snapshot Telemetry
    diffs between rounds to put per-round byte/message counts in the event
    log. Includes dispatch-latency quantiles when any message was timed."""
    refresh_liveness()  # age gauges must be fresh in any snapshot
    reg = registry or REGISTRY
    dirs = directional_bytes(reg)
    out = {
        "messages_sent": reg.total("comm_messages_sent_total"),
        "bytes_sent": reg.total("comm_bytes_sent_total"),
        "messages_received": reg.total("comm_messages_received_total"),
        "bytes_received": reg.total("comm_bytes_received_total"),
        # per-direction split (comm_bytes_total{codec,direction}): uplink
        # is the byte budget the delta/quantized tiers optimize; one
        # undirected counter hides that broadcast dominates downlink
        "bytes_uplink": dirs["uplink"],
        "bytes_downlink": dirs["downlink"],
    }
    snap = reg.snapshot().get("comm_dispatch_latency_seconds", {})
    n = sum(s.get("count", 0) for s in snap.values())
    if n:
        out["dispatch_count"] = n
        # single-backend runs (the norm) have one child; multi-backend runs
        # get the max — a conservative "slowest transport" view
        out["dispatch_p95_s"] = max(s.get("p95", 0.0) for s in snap.values())
    return out
