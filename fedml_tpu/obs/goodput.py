"""Round economics — goodput & duty-cycle accounting.

Decomposes each round's wall-clock into EXCLUSIVE buckets and, when the
compiled round variant's XLA cost analysis is known, turns the wall into
useful-FLOPs/s, bytes/s and an MFU-style utilization figure. Three metric
families land in the process-wide ``metrics.REGISTRY``:

    fed_duty_cycle{bucket}            (gauge) fraction of the last round's
                                      wall-clock spent in ``bucket`` — the
                                      six buckets are exclusive and sum to
                                      1.0 by construction
    fed_goodput_flops_per_sec         (gauge) useful device FLOPs/s of the
                                      last round (0 until a variant's cost
                                      analysis is known)
    fed_goodput_bytes_per_sec         (gauge) bytes-accessed/s, same caveat
    fed_goodput_mfu                   (gauge) flops_per_sec / (per-chip
                                      peak x participating devices); 0
                                      when the device kind is unknown —
                                      goodput is then RELATIVE-only
    fed_goodput_rounds_total          rounds with a goodput block emitted

**Buckets** (docs/PERFORMANCE.md §Round economics):

    compute          device execution the driver waited on: the dispatch
                     span plus the measured block-until-ready wait. In
                     pipelined mode the dispatch span is issue-only and the
                     device wait surfaces at the drain sync — both are
                     folded here so sync and pipelined runs are comparable
    h2d              host->device issue time ON the driver's critical path
                     (0 in pipelined mode, where transfers ride the
                     prefetch thread — overlapped time is nobody's wall)
    prefetch_stall   pipelined: time blocked on the prefetch thread;
                     sync: the serial host pack (the stall pipelining
                     exists to hide — so an on/off A/B moves THIS bucket)
    wire_wait        cross-process server: broadcast-done -> last counted
                     arrival; 0 in the standalone engine (no wire)
    agg_flush        server aggregation flush (the standalone engine fuses
                     aggregation into the round program -> counted as
                     compute there)
    drain            the residual: record materialization, eval, broadcast
                     serialize, emit — everything else the driver did
                     serially. Computed as wall minus the other buckets,
                     which is what makes the decomposition exclusive and
                     exactly summing

The decomposition is deliberately *clipped*: buckets are folded in the
order above and each is capped at the wall-clock remaining, so overlapping
or over-reported spans can never make the sum exceed the wall (the
injected-clock oracle in tests/test_goodput.py pins sum == wall).

**Cost model**: ``record_variant_cost(name, executable)`` caches
``executable.cost_analysis()`` per jit variant name (``round_bf16_b8``,
``block_bf16_r10_b8`` — the same names ``warmup()`` compiles under).
Backends that don't report cost analysis yield ``None`` and goodput
degrades to duty-cycle-only — graceful, never raising. Everything here is
host-side and allocation-light; nothing is traced, so telemetry-off runs
stay bit-identical (test-enforced).
"""

from __future__ import annotations

import logging
import sys
import threading
from functools import lru_cache

from fedml_tpu.obs.metrics import REGISTRY

log = logging.getLogger("fedml_tpu.obs.goodput")

#: Exclusive duty-cycle buckets, in clip/fold priority order; ``drain`` is
#: always the residual.
BUCKETS = ("compute", "h2d", "prefetch_stall", "wire_wait", "agg_flush",
           "drain")

# Per-chip bf16 peak FLOP/s by device-kind substring — same table and
# matching rule as bench.py's MFU column (more-specific keys first; the
# first substring hit of the lowercased device kind wins). Unknown kinds
# return None and MFU reports 0 (relative-only goodput).
PEAK_FLOPS_BF16 = {
    "v5 lite": 1.97e14,
    "v5e": 1.97e14,
    "v5p": 4.59e14,
    "v6 lite": 9.18e14,
    "v6e": 9.18e14,
    "v4": 2.75e14,
    "v3": 1.23e14,
    "v2": 4.5e13,
}


def device_peak_flops(device_kind: str | None = None) -> float | None:
    """Per-chip peak FLOP/s for ``device_kind`` (defaults to the live
    jax backend's device 0 when jax is already imported — never imports
    jax itself). None when unknown: MFU then reads 0, goodput is
    relative-only."""
    if device_kind is None:
        jax_mod = sys.modules.get("jax")
        if jax_mod is None:
            return None
        try:
            device_kind = jax_mod.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — detection is best-effort
            log.debug("device-kind detection failed; MFU is relative-only",
                      exc_info=True)
            return None
    kind = str(device_kind).lower()
    for key, peak in PEAK_FLOPS_BF16.items():
        if key in kind:
            return peak
    return None


# ------------------------------------------------------- cost-model cache
_cost_lock = threading.Lock()
_COSTS: dict[str, dict | None] = {}


def record_variant_cost(name: str, executable) -> dict | None:
    """Cache ``executable.cost_analysis()`` under the jit variant ``name``.

    Returns ``{"flops": float|None, "bytes": float|None}`` or None when the
    backend doesn't report a cost model (CPU builds without it, mocked
    executables, ...) — callers never see an exception. Called by
    ``compile_concurrently`` for every AOT-compiled variant, so any engine
    that warms up gets per-variant cost for free."""
    ent = None
    try:
        ca = executable.cost_analysis()
        # older jax returns [dict] per device program; current returns dict
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            flops = ca.get("flops")
            nbytes = ca.get("bytes accessed")
            if flops is not None or nbytes is not None:
                ent = {
                    "flops": float(flops) if flops is not None else None,
                    "bytes": float(nbytes) if nbytes is not None else None,
                }
    except Exception:  # noqa: BLE001 — cost model is best-effort
        log.debug("cost_analysis unavailable for %s", name, exc_info=True)
    with _cost_lock:
        _COSTS[name] = ent
    return ent


def variant_cost(name: str | None) -> dict | None:
    """The cached cost entry for a variant name; None when the variant was
    never AOT-compiled or its backend reports no cost model."""
    if name is None:
        return None
    with _cost_lock:
        return _COSTS.get(name)


def clear_variant_costs() -> None:
    """Test hook: drop the per-process cost cache."""
    with _cost_lock:
        _COSTS.clear()


# -------------------------------------------------- bucket decomposition
def decompose(wall_s: float, *, compute: float = 0.0, h2d: float = 0.0,
              prefetch_stall: float = 0.0, wire_wait: float = 0.0,
              agg_flush: float = 0.0) -> dict:
    """Fold measured phase seconds into the exclusive bucket dict.

    Buckets are clipped in BUCKETS order so the running total never
    exceeds ``wall_s``; ``drain`` is the residual. The result's values sum
    to ``wall_s`` exactly (the oracle contract)."""
    wall = max(float(wall_s), 0.0)
    raw = {"compute": compute, "h2d": h2d, "prefetch_stall": prefetch_stall,
           "wire_wait": wire_wait, "agg_flush": agg_flush}
    out, total = {}, 0.0
    for b in BUCKETS[:-1]:
        v = min(max(float(raw[b]), 0.0), wall - total)
        out[b] = v
        total += v
    out["drain"] = wall - total
    return out


def buckets_from_spans(wall_s: float, spans: dict | None, *,
                       pipelined: bool = False,
                       compute_wait_s: float = 0.0,
                       wire_wait_s: float = 0.0,
                       flush_s: float = 0.0) -> dict:
    """The standard span->bucket mapping for an engine round record.

    ``spans`` is the per-round span dict the tracer already produces
    (pack/round sync; prefetch_stall/h2d pipelined; aggregate on the
    server). ``compute_wait_s`` is the measured block-until-ready wait the
    driver paid for this round's device program (the dispatch span alone
    is issue time). In pipelined mode the pack/h2d spans rode the prefetch
    thread — overlapped, so only the stall counts against the wall."""
    spans = spans or {}
    if pipelined:
        stall = float(spans.get("prefetch_stall", 0.0))
        h2d = 0.0
    else:
        stall = float(spans.get("pack", 0.0))
        h2d = float(spans.get("h2d", 0.0))
    return decompose(
        wall_s,
        compute=float(spans.get("round", 0.0)) + float(compute_wait_s),
        h2d=h2d,
        prefetch_stall=stall,
        wire_wait=float(wire_wait_s),
        agg_flush=float(spans.get("aggregate", 0.0)) + float(flush_s),
    )


# ------------------------------------------------------- metric families
@lru_cache(maxsize=8)
def _duty_gauge(bucket: str):
    return REGISTRY.gauge("fed_duty_cycle", bucket=bucket)


@lru_cache(maxsize=4)
def _gp_gauge(name: str):
    # lru_cache indirection; every call site passes a fed_* literal
    return REGISTRY.gauge(name)  # fedlint: disable=metric-discipline


@lru_cache(maxsize=2)
def _gp_counter(name: str):
    # lru_cache indirection; every call site passes a fed_* literal
    return REGISTRY.counter(name)  # fedlint: disable=metric-discipline


def ensure_goodput_families() -> None:
    """Pre-register every goodput family at zero so a clean run's
    Prometheus export always carries them — 'no goodput yet' must read as
    0, not as a missing family (same contract as the shed/secagg
    families)."""
    for b in BUCKETS:
        _duty_gauge(b)
    _gp_gauge("fed_goodput_flops_per_sec")
    _gp_gauge("fed_goodput_bytes_per_sec")
    _gp_gauge("fed_goodput_mfu")
    _gp_counter("fed_goodput_rounds_total")


# ------------------------------------------------------ per-round record
def round_goodput(wall_s: float, buckets: dict, *, variant: str | None = None,
                  cost_rounds: int = 1, n_devices: int = 1,
                  peak_flops: float | None = None,
                  device_kind: str | None = None) -> dict:
    """Build the ``goodput`` block one round record carries and feed the
    metric families.

    ``buckets`` is a :func:`decompose` result for this round's wall.
    ``cost_rounds`` normalizes a scanned block variant's cost analysis
    (which covers R rounds per dispatch) to per-round figures. ``wall_s``
    must already be per-round. When the variant's cost is unknown the
    block carries duty cycles only (relative goodput)."""
    wall = max(float(wall_s), 1e-12)
    duty = {b: buckets.get(b, 0.0) / wall for b in BUCKETS}
    blk: dict = {
        "wall_s": round(wall, 6),
        "buckets": {b: round(float(buckets.get(b, 0.0)), 6) for b in BUCKETS},
        "duty": {b: round(duty[b], 4) for b in BUCKETS},
    }
    if variant is not None:
        blk["variant"] = variant
    for b in BUCKETS:
        _duty_gauge(b).set(duty[b])
    _gp_counter("fed_goodput_rounds_total").inc()

    cost = variant_cost(variant)
    if cost is not None:
        rounds = max(int(cost_rounds), 1)
        if cost.get("flops"):
            fps = cost["flops"] / rounds / wall
            blk["flops_per_s"] = fps
            _gp_gauge("fed_goodput_flops_per_sec").set(fps)
            peak = (peak_flops if peak_flops is not None
                    else device_peak_flops(device_kind))
            if peak:
                mfu = fps / (peak * max(int(n_devices), 1))
                blk["mfu"] = round(mfu, 6)
                _gp_gauge("fed_goodput_mfu").set(mfu)
        if cost.get("bytes"):
            bps = cost["bytes"] / rounds / wall
            blk["bytes_per_s"] = bps
            _gp_gauge("fed_goodput_bytes_per_sec").set(bps)
    return blk
