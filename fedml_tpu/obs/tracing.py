"""Cross-rank distributed tracing — stitched per-round timelines.

The PR-1 telemetry layer counts *what* happened per round; this module says
*where wall-clock went across ranks*. Every round gets a trace id and every
span carries (trace id, span id, parent id, rank):

- **server** (rank 0): ``round`` (the whole round), ``broadcast`` (the
  serialize+send loop), per-rank ``downlink`` / ``uplink`` wire spans, and
  whatever the engine's ``RoundTracer`` records (``aggregate``, ``eval``);
- **client** (rank k): ``client_round`` (handler entry to upload), with
  ``unpack`` / ``local_fit`` / ``pack`` children.

Context propagation rides in the existing FMT2 JSON header scalars: the
server adds a ``__trace`` param ({tid, sid, t1}) to each broadcast, the
client echoes it back on its upload extended with its clock stamps and its
finished span buffer — so loopback, gRPC, and MQTT propagate identically
(it is just another scalar message param) and a stock peer that ignores the
key still interoperates. The server rebases client timestamps onto its own
clock with the NTP-style estimator in ``obs/clock.py`` (the broadcast ->
upload exchange IS the T1..T4 handshake) and stitches one timeline per
round.

On top of the stitched timeline, ``finish_round`` computes the per-round
**critical path**: which rank bounded the round (the straggler — last
uplink to arrive), its phase breakdown, per-rank slack, and — when a chaos
``FaultPlan`` is active — the injected straggle/delay seconds
cross-referenced from the fault ledger, so a planned 200 ms straggle
surfaces as that rank owning the critical path with a labeled span.

Span ids are pure sha256 functions of (run id, round, rank, counter) — no
RNG, no wall-clock entropy — so a run with an injected fake clock exports a
byte-stable Chrome trace (the golden test). All of this is host-side:
tracing never touches the jitted round program, and with tracing off no
``__trace`` param is ever added (frames are byte-identical to the
untraced build).

Exports: ``obs/trace_export.py`` (Chrome trace-event JSON for
Perfetto / chrome://tracing, plus the critical-path text renderer behind
``scripts/report.py --critical-path``).
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
import time
from collections import defaultdict
from functools import lru_cache

from fedml_tpu.obs.clock import ClockSync
from fedml_tpu.obs.metrics import REGISTRY

# message param carrying trace context (a JSON-header scalar on the wire).
# Server -> client: {"tid", "sid", "t1"}; client -> server: that plus
# {"t2", "t3", "spans": [span dicts]} — the piggybacked client buffer.
TRACE_KEY = "__trace"

# canonical phase order for reports (extra span names append after these)
PHASES = ("downlink", "unpack", "local_fit", "pack", "uplink",
          "aggregate", "eval")


def make_trace_id(run_id: str, round_idx: int) -> str:
    """Deterministic per-(run, round) trace id — 16 hex chars."""
    key = f"trace|{run_id}|{int(round_idx)}".encode()
    return hashlib.sha256(key).hexdigest()[:16]


def make_span_id(trace_id: str, rank: int, n: int) -> str:
    """Deterministic span id: pure in (trace, rank, per-rank counter)."""
    key = f"span|{trace_id}|{int(rank)}|{int(n)}".encode()
    return hashlib.sha256(key).hexdigest()[:16]


def _span(tid: str, sid: str, parent: str | None, rank: int, name: str,
          t0: float, t1: float, attrs: dict | None = None) -> dict:
    s = {"tid": tid, "sid": sid, "parent": parent, "rank": int(rank),
         "name": name, "t0": float(t0), "t1": float(t1)}
    if attrs:
        s["attrs"] = attrs
    return s


# --------------------------------------------------------------- RoundTracer
@lru_cache(maxsize=256)
def _span_hist(name: str):
    # process-wide histogram family so RoundTracer spans and the Prometheus
    # export read from ONE timing path (pre-PR-3 they were disjoint)
    return REGISTRY.histogram("fed_span_seconds", span=name)


class RoundTracer:
    """Per-round named span timing with aggregate statistics.

    The seed-era host-side span timer (was ``utils/tracing.py``), absorbed
    into the obs tracing path: every ``span()`` observation now also feeds
    the process-wide ``fed_span_seconds{span=...}`` histogram (so
    ``summary()`` totals and the Prometheus export agree — the histogram
    counts observations, ``summary()`` aggregates per round), and an
    optional ``sink`` (a :class:`DistributedTracer`) receives each span's
    wall-clock interval for the stitched per-round timeline. With
    ``sink=None`` the extra cost is one histogram observe per span.
    """

    def __init__(self, sink: "DistributedTracer | None" = None):
        self.rounds: list[dict[str, float]] = [{}]
        self._sink = sink

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        w0 = time.time()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            cur = self.rounds[-1]
            cur[name] = cur.get(name, 0.0) + dt
            _span_hist(name).observe(dt)
            if self._sink is not None:
                self._sink.record_span(name, w0, w0 + dt)

    def next_round(self):
        self.rounds.append({})

    def summary(self) -> dict[str, dict[str, float]]:
        """name -> {mean, p50, p95, max, total} over completed rounds."""
        import numpy as np

        per_name = defaultdict(list)
        for r in self.rounds:
            for k, v in r.items():
                per_name[k].append(v)
        out = {}
        for k, vs in per_name.items():
            a = np.asarray(vs)
            out[k] = {
                "mean": float(a.mean()),
                "p50": float(np.percentile(a, 50)),
                "p95": float(np.percentile(a, 95)),
                "max": float(a.max()),
                "total": float(a.sum()),
                "count": len(vs),
            }
        return out

    def totals(self) -> dict[str, float]:
        """name -> total seconds across all rounds (the bench span report)."""
        return {k: v["total"] for k, v in self.summary().items()}


# --------------------------------------------------------- client-side buffer
class ClientSpanBuffer:
    """Client-rank span buffer — created lazily by a client manager the
    first time an inbound broadcast carries ``__trace`` context, so clients
    trace exactly when the server does (no client-side configuration).

    ``on_broadcast`` adopts the server's context (T1, and T2 = now);
    ``span`` records children of this round's ``client_round`` root;
    ``upload_blob`` stamps T3, closes the root, and returns the dict the
    manager piggybacks on the uplink frame.
    """

    def __init__(self, rank: int, clock=time.time):
        self.rank = int(rank)
        self._clock = clock
        self._tid: str | None = None
        self._parent: str | None = None
        self._root: str | None = None
        self._t1 = 0.0
        self._t2 = 0.0
        self._n = 0
        self._spans: list[dict] = []
        self._root_attrs: dict = {}

    def on_broadcast(self, blob: dict) -> None:
        from fedml_tpu.obs import comm_instrument as _obs

        self._tid = str(blob.get("tid"))
        self._parent = blob.get("sid")
        self._t1 = float(blob.get("t1", 0.0))
        self._t2 = self._clock()
        self._n = 0
        self._spans = []
        self._root = make_span_id(self._tid, self.rank, 0)
        self._root_attrs = {}
        q = _obs.last_dispatch_latency()
        if q is not None:  # seconds the frame waited in the inbound queue
            self._root_attrs["queue_s"] = q

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        t0 = self._clock()
        try:
            yield
        finally:
            t1 = self._clock()
            self._n += 1
            sid = make_span_id(self._tid, self.rank, self._n)
            self._spans.append(_span(self._tid, sid, self._root, self.rank,
                                     name, t0, t1, attrs or None))

    def upload_blob(self) -> dict:
        """Stamp T3, close the ``client_round`` root, return the uplink
        piggyback: trace context + clock stamps + the finished spans."""
        t3 = self._clock()
        root = _span(self._tid, self._root, self._parent, self.rank,
                     "client_round", self._t2, t3,
                     self._root_attrs or None)
        return {"tid": self._tid, "sid": self._root,
                "t1": self._t1, "t2": self._t2, "t3": t3,
                "spans": self._spans + [root]}


# ------------------------------------------------------------- chaos lookup
def chaos_delays(round_idx: int) -> dict[int, float]:
    """rank -> seconds of straggle/delay the active chaos plan injected
    this round (from its fault ledger), so injected latency is attributed
    — not just observed — on the critical path. Empty when no plan is
    installed. Import is lazy: obs must not import chaos at module load
    (chaos imports obs)."""
    try:
        from fedml_tpu import chaos as _chaos
    except Exception:  # pragma: no cover - chaos always ships, but obs
        return {}      # must degrade if the package is trimmed
    plan = _chaos.active_plan()
    if plan is None:
        return {}
    out: dict[int, float] = {}
    for e in plan.ledger.for_round(round_idx, faults=("straggle", "delay")):
        fault, direction = e["fault"], e["direction"]
        src, dst = e["src"], e["dst"]
        # attribute to the CLIENT end of the link: a delayed downlink
        # (src = server rank 0) slows the destination rank's round, and
        # the server never uploads — src-only attribution would lose it
        rank = src if src not in (None, 0) else dst
        if rank is None:
            continue
        for rule in plan.rules:
            if (rule.fault == fault and rule.in_window(round_idx)
                    and rule.matches_link(direction, src, dst)):
                out[int(rank)] = out.get(int(rank), 0.0) + rule.delay_s
                break
    return out


# --------------------------------------------------------- server-side trace
class DistributedTracer:
    """The stitching tracer — one per Telemetry bundle (rank 0 / the
    standalone engine). Collects this process's spans, rebases and adopts
    piggybacked client spans, and computes the per-round critical path.

    Driven by the server manager::

        tr.begin_round(r)
        for rank in ...: msg.add_params(TRACE_KEY, tr.broadcast_ctx(rank))
        tr.end_broadcast()
        ... on each upload: tr.on_upload(rank, msg_params.get(TRACE_KEY))
        ... RoundTracer(sink=tr) records aggregate/eval via record_span
        cp = tr.finish_round()          # the round record's critical_path

    The standalone engine drives only ``begin_round`` + the RoundTracer
    sink: no arrivals means ``finish_round`` returns None (single-rank
    timelines have no straggler) while the spans still export.
    """

    def __init__(self, run_id: str, rank: int = 0, clock=time.time):
        self.run_id = str(run_id)
        self.rank = int(rank)
        self._clock = clock
        self.clock_sync = ClockSync()
        self._spans: list[dict] = []
        self._lock = threading.Lock()
        self._cur: dict | None = None

    # ------------------------------------------------------------ round flow
    def begin_round(self, round_idx: int) -> None:
        """Open round ``round_idx``'s trace (auto-finishing any open one)."""
        with self._lock:
            if self._cur is not None:
                self._finish_round_locked()
            tid = make_trace_id(self.run_id, round_idx)
            self._cur = {
                "round": int(round_idx), "tid": tid, "t0": self._clock(),
                "n": 0, "round_sid": make_span_id(tid, self.rank, 0),
                "bcast_sid": None, "bcast_t0": None, "dests": set(),
                "arrivals": {}, "client_phases": {}, "offsets": {},
                "server_spans": {}, "chaos": {},
            }

    def _next_sid(self) -> str:
        cur = self._cur
        cur["n"] += 1
        return make_span_id(cur["tid"], self.rank, cur["n"])

    def broadcast_ctx(self, dest_rank: int) -> dict:
        """The ``__trace`` param for one outgoing broadcast (stamps T1;
        opens the ``broadcast`` span on first call)."""
        with self._lock:
            cur = self._cur
            if cur is None:
                return {}
            if cur["bcast_sid"] is None:
                cur["bcast_sid"] = self._next_sid()
                cur["bcast_t0"] = self._clock()
            cur["dests"].add(int(dest_rank))
            return {"tid": cur["tid"], "sid": cur["bcast_sid"],
                    "t1": self._clock()}

    def end_broadcast(self) -> None:
        with self._lock:
            cur = self._cur
            if cur is None or cur["bcast_sid"] is None:
                return
            self._spans.append(_span(
                cur["tid"], cur["bcast_sid"], cur["round_sid"], self.rank,
                "broadcast", cur["bcast_t0"], self._clock()))

    def on_upload(self, rank: int, blob: dict | None) -> None:
        """Fold one client upload in: arrival time (T4), clock-offset
        sample, the rebased client span buffer, and the downlink/uplink
        wire spans. ``blob=None`` (stock peer, tracing-off client) still
        records the arrival so slack stays computable."""
        now = self._clock()
        rank = int(rank)
        with self._lock:
            cur = self._cur
            if cur is None:
                return
            if rank in cur["arrivals"]:
                # chaos-duplicated uplink: the first delivery is the real
                # wire time — re-recording would double the client spans
                # (same ids) and corrupt slack with the copy's arrival
                return
            cur["arrivals"][rank] = now
            if not isinstance(blob, dict) or blob.get("tid") != cur["tid"]:
                return  # no context (or a stale trace id): arrival only
            try:
                t1, t2, t3 = (float(blob["t1"]), float(blob["t2"]),
                              float(blob["t3"]))
            except (KeyError, TypeError, ValueError):
                return  # malformed peer blob must not kill the handler
            off = self.clock_sync.update(rank, t1, t2, t3, now)
            cur["offsets"][rank] = off
            phases: dict[str, float] = {}
            for s in blob.get("spans", ()):
                if not isinstance(s, dict):
                    continue
                try:
                    s = dict(s, t0=float(s["t0"]) - off,
                             t1=float(s["t1"]) - off)
                except (KeyError, TypeError, ValueError):
                    continue  # skip a damaged span, keep the rest
                self._spans.append(s)
                if s.get("name") != "client_round":
                    phases[s["name"]] = (phases.get(s["name"], 0.0)
                                         + (s["t1"] - s["t0"]))
            # clamp the rebased wire endpoints: the min-RTT offset came
            # from a different exchange, so an asymmetric round can land
            # t2-off before t1 (or t3-off after t4) — a negative-duration
            # span would flunk the schema on timing jitter
            t2s = max(t2 - off, t1)
            t3s = min(t3 - off, now)
            parent = cur["bcast_sid"] or cur["round_sid"]
            self._spans.append(_span(cur["tid"], self._next_sid(), parent,
                                     rank, "downlink", t1, t2s))
            phases["downlink"] = t2s - t1
            delays = self._round_chaos_delays(cur)
            attrs = None
            if rank in delays:
                attrs = {"chaos": "injected_delay",
                         "chaos_delay_s": delays[rank]}
                cur["chaos"][rank] = delays[rank]
            self._spans.append(_span(cur["tid"], self._next_sid(),
                                     blob.get("sid"), rank, "uplink", t3s,
                                     now, attrs))
            phases["uplink"] = now - t3s
            cur["client_phases"][rank] = phases

    def _round_chaos_delays(self, cur: dict) -> dict[int, float]:
        """chaos_delays for the open round, recomputed only when the fault
        ledger grew since the last lookup (ledger len is O(1)): N uploads
        must not each rescan a soak run's whole ledger."""
        try:
            from fedml_tpu import chaos as _chaos
        except Exception:  # pragma: no cover
            return {}
        plan = _chaos.active_plan()
        n = len(plan.ledger) if plan is not None else 0
        if cur.get("chaos_ledger_n") != n:
            cur["chaos_ledger_n"] = n
            cur["chaos_cache"] = chaos_delays(cur["round"])
        return cur["chaos_cache"]

    def record_span(self, name: str, t0: float, t1: float,
                    attrs: dict | None = None) -> None:
        """Record one local span under the open round (the RoundTracer
        sink path: aggregate/eval on the server, pack/round/eval
        standalone). No open round -> dropped (nothing to parent to)."""
        with self._lock:
            cur = self._cur
            if cur is None:
                return
            self._spans.append(_span(cur["tid"], self._next_sid(),
                                     cur["round_sid"], self.rank, name,
                                     t0, t1, attrs))
            cur["server_spans"][name] = (cur["server_spans"].get(name, 0.0)
                                         + (t1 - t0))

    def finish_round(self) -> dict | None:
        """Close the round span and return the critical-path record (None
        when no round is open or no client ever reported — standalone)."""
        with self._lock:
            return self._finish_round_locked()

    def finish(self) -> None:
        """Close any open round (Telemetry.close)."""
        with self._lock:
            if self._cur is not None:
                self._finish_round_locked()

    def _finish_round_locked(self) -> dict | None:
        cur, self._cur = self._cur, None
        now = self._clock()
        self._spans.append(_span(cur["tid"], cur["round_sid"], None,
                                 self.rank, "round", cur["t0"], now))
        arrivals = cur["arrivals"]
        if not arrivals:
            return None
        straggler = max(sorted(arrivals), key=arrivals.get)
        last = arrivals[straggler]
        phases = dict(cur["client_phases"].get(straggler, {}))
        phases.update(cur["server_spans"])
        cp = {
            "straggler": straggler,
            "round_s": now - cur["t0"],
            "phases": phases,
            "slack_s": {r: last - t for r, t in sorted(arrivals.items())},
        }
        missing = sorted(cur["dests"] - set(arrivals))
        if missing:
            cp["missing"] = missing  # elastic partial: never reported
        if cur["chaos"]:
            cp["chaos_delay_s"] = dict(cur["chaos"])
        if cur["offsets"]:
            cp["clock_offset_s"] = dict(sorted(cur["offsets"].items()))
        # registry: the report's aggregate view of the same numbers
        for name, secs in phases.items():
            REGISTRY.histogram("fed_phase_seconds", phase=name).observe(secs)
        REGISTRY.counter("fed_round_critical_path_total",
                         rank=straggler).inc()
        for r, s in cp["slack_s"].items():
            if r != straggler:
                REGISTRY.histogram("fed_straggler_slack_seconds").observe(s)
        return cp

    # ---------------------------------------------------------------- export
    def spans(self) -> list[dict]:
        with self._lock:
            return list(self._spans)
