"""Run-health monitor — a declarative rule table over the live run.

FL_PyTorch (arXiv:2202.03099) and FedJAX (arXiv:2108.02117) both treat
live experiment tracking as a first-class simulator capability; here the
live view is a ``HealthMonitor`` evaluating a JSON-loadable rule table
against the metrics registry and the stream of round/eval records, firing
**edge-triggered, deduplicated** alerts:

    rule            fires when
    ------------    ------------------------------------------------------
    convergence     the training/eval loss goes non-finite, or the last
                    ``evals_rising`` consecutive evals strictly rose
    slowdown        p50 round time over the last ``recent`` rounds exceeds
                    ``factor`` x the p50 of the trailing ``window`` rounds
    quarantine      gate/robust-aggregator rejections per round (averaged
                    over ``window`` rounds) exceed ``max_per_round``
    shed            async admission/backpressure sheds per round exceed
                    ``max_per_round`` (same windowing)
    quorum          ``fed_ranks_alive`` dropped below ``min_fraction`` of
                    the expected cohort (elastic undeliverable / crashed
                    ranks) — resolves when a reprobe brings them back
    device_memory   any device's ``bytes_in_use`` exceeds ``max_fraction``
                    of its ``bytes_limit`` (needs obs/memwatch gauges; a
                    backend without allocator stats never fires)
    stall           no round/eval progress for ``after_s`` seconds

An alert *fires* once when its condition transitions false->true and
*resolves* once on the way back — never once per round while the
condition persists. Each transition is a structured ``alert`` event in
the run's EventLog (rendered by ``scripts/report.py --alerts``) and a
``fed_alerts_total{rule,severity}`` increment (fired transitions only);
the active set + status ride ``/healthz`` (obs/httpd.py):

    status = stalled   (no progress past the stall threshold)
           | degraded  (any alert currently active)
           | ok

The rule table is data, not code: pass a list of dicts, a JSON string, or
a path to ``Telemetry(health_rules=...)`` / ``rules_from_json`` —
``DEFAULT_RULES`` documents the schema and default thresholds
(docs/OBSERVABILITY.md §Health rules).
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time

from fedml_tpu.obs.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("fedml_tpu.obs.health")

# The default rule table — the documented schema. Every entry needs
# ``rule`` (one of the kinds above) and ``severity`` (free-form label,
# conventionally warning|critical); the rest are per-rule thresholds.
DEFAULT_RULES: list[dict] = [
    {"rule": "convergence", "severity": "critical", "evals_rising": 3},
    {"rule": "slowdown", "severity": "warning",
     "window": 20, "recent": 5, "factor": 2.0},
    {"rule": "quarantine", "severity": "warning",
     "window": 5, "max_per_round": 2.0},
    {"rule": "shed", "severity": "warning",
     "window": 5, "max_per_round": 4.0},
    {"rule": "quorum", "severity": "critical", "min_fraction": 1.0},
    {"rule": "device_memory", "severity": "critical", "max_fraction": 0.92},
    {"rule": "stall", "severity": "critical", "after_s": 300.0},
    # privacy-budget ledger (docs/ROBUSTNESS.md §Privacy ledger): fires
    # once when the DP accountant's cumulative ε crosses the budget. Not
    # evaluable (never fires) on runs without a ``privacy`` block on
    # their round records; override max_epsilon per deployment.
    {"rule": "privacy_budget", "severity": "warning", "max_epsilon": 10.0},
    # server crash recovery (docs/ROBUSTNESS.md §Server crash recovery):
    # fires when the supervised server has restarted more than
    # max_restarts times — a crash LOOP (bad checkpoint, poisoned WAL,
    # deterministic fault) that supervision alone would retry forever.
    # Not evaluable on runs that never restart (family absent or zero).
    {"rule": "restart_storm", "severity": "critical", "max_restarts": 3.0},
    # fleet observability plane (docs/OBSERVABILITY.md §Fleet rollup) —
    # the quorum/staleness rules evaluated over the FLEET view (in-band
    # digests) instead of the transport's heartbeat gauges. Only
    # evaluable once at least one digest arrived (fed_fleet_digests_total
    # > 0), so a plane-off or just-booted run never false-fires.
    # fleet_quorum: reporting ranks dropped below min_fraction of the
    # expected cohort (+1 because rank 0's own row always reports).
    # Additionally gated on the fleet reaching round 1 — during round 0
    # ramp-up "reporting < expected" is boot order, not an outage.
    {"rule": "fleet_quorum", "severity": "critical", "min_fraction": 1.0},
    # fleet_staleness: the oldest rank's digest silence exceeded max_age_s
    # — a rank that stopped uploading (wedged, partitioned, crashed)
    # while the rest of the fleet rounds on.
    {"rule": "fleet_staleness", "severity": "warning", "max_age_s": 120.0},
]

_KNOWN_RULES = {r["rule"] for r in DEFAULT_RULES}


def rules_from_json(spec) -> list[dict]:
    """Normalize a rule-table spec: a list of rule dicts passes through, a
    string is inline JSON or a path to a JSON file (a typo'd path fails as
    file-not-found, not 'Expecting value'). Unknown rule kinds are loud —
    a misspelled rule silently never firing is the failure mode this
    layer exists to prevent."""
    if isinstance(spec, (list, tuple)):
        rules = [dict(r) for r in spec]
    else:
        text = spec
        if os.path.exists(spec):
            with open(spec) as f:
                text = f.read()
        elif not spec.lstrip().startswith("["):
            raise FileNotFoundError(f"health rule file not found: {spec!r}")
        rules = json.loads(text)
    for r in rules:
        kind = r.get("rule")
        if kind not in _KNOWN_RULES:
            raise ValueError(f"unknown health rule {kind!r} "
                             f"(known: {sorted(_KNOWN_RULES)})")
        r.setdefault("severity", "warning")
    return rules


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class HealthMonitor:
    """Evaluates the rule table at every round/eval record (the engines'
    per-round hook rides ``Telemetry.emit_round``) and, when ``start()``
    is armed, from a background thread between records — a fully stalled
    run emits no records, so only the thread can say so."""

    def __init__(self, telemetry=None, rules=None,
                 registry: MetricsRegistry | None = None,
                 expected_ranks: int | None = None, clock=time.time):
        self.telemetry = telemetry
        self.registry = registry or REGISTRY
        self.rules = rules_from_json(rules if rules is not None
                                     else DEFAULT_RULES)
        # cohort size the quorum rule measures against; set explicitly or
        # inferred from the run header's world_size (Telemetry.run_header)
        self.expected_ranks = expected_ranks
        self._clock = clock
        self._lock = threading.RLock()
        self.round_idx: int | None = None
        self._start_t = clock()
        self._progress_t = clock()
        # trailing windows (bounded by the largest rule window)
        max_win = max([r.get("window", 0) + r.get("recent", 0)
                       for r in self.rules] + [8])
        self._max_win = max_win
        self._round_times: list[float] = []
        self._last_round_ts: float | None = None
        self._eval_losses: list[float] = []
        self._nonfinite_seen = False
        self._quar_per_round: list[float] = []
        self._shed_per_round: list[float] = []
        # cumulative DP ε from the newest round record's privacy block
        # (None = not a DP run; the privacy_budget rule stays quiet)
        self._privacy_eps: float | None = None
        # worst per-client ε (docs/ROBUSTNESS.md §Hierarchical secure
        # aggregation: per-client ledger) — None until a round record
        # carries the client-granular summary
        self._privacy_eps_client: float | None = None
        self._last_quar = self.registry.total("fed_updates_rejected_total")
        self._last_shed = self.registry.total("fed_async_shed_total")
        # edge-trigger state + the full fired/resolved ledger
        self._active: dict[str, dict] = {}
        self.alerts: list[dict] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # pre-register the configured alert children at zero so a clean
        # run's export reads 'no alerts', not 'metric missing'
        for r in self.rules:
            self.registry.counter("fed_alerts_total", rule=r["rule"],
                                  severity=r["severity"])

    # -------------------------------------------------------------- intake
    def on_round(self, rec: dict) -> None:
        """One round record (any engine: standalone, pipelined drain, sync
        server, async flush). Updates the trailing windows and runs a
        check — the per-round health hook."""
        now = self._clock()
        with self._lock:
            self._progress_t = now
            if rec.get("round") is not None:
                self.round_idx = int(rec["round"])
            # round duration: the engine's host 'round' span when present
            # (standalone), else the inter-record timestamp delta (the
            # cross-process managers time aggregate/eval, not the wire
            # wait that a straggler actually stretches)
            span = (rec.get("spans") or {}).get("round")
            ts = rec.get("ts")
            if span is not None and span > 0:
                self._push(self._round_times, float(span))
            elif isinstance(ts, (int, float)):
                if self._last_round_ts is not None and ts > self._last_round_ts:
                    self._push(self._round_times, float(ts - self._last_round_ts))
                self._last_round_ts = float(ts)
            for v in (rec.get("metrics") or {}).values():
                if isinstance(v, float) and not math.isfinite(v):
                    self._nonfinite_seen = True
            eps = (rec.get("privacy") or {}).get("eps")
            if isinstance(eps, (int, float)):
                self._privacy_eps = float(eps)
            eps_cli = (rec.get("privacy") or {}).get("eps_client_max")
            if isinstance(eps_cli, (int, float)):
                self._privacy_eps_client = float(eps_cli)
            if rec.get("eval"):
                self._fold_eval(rec["eval"])
            # per-round quarantine/shed movement from the registry totals
            # (uniform across engines; the record's quarantine list only
            # exists on engines that carry a ledger)
            quar = self.registry.total("fed_updates_rejected_total")
            shed = self.registry.total("fed_async_shed_total")
            self._push(self._quar_per_round, max(0.0, quar - self._last_quar))
            self._push(self._shed_per_round, max(0.0, shed - self._last_shed))
            self._last_quar, self._last_shed = quar, shed
        self.check()

    def on_eval(self, rec: dict) -> None:
        with self._lock:
            self._progress_t = self._clock()
            if rec.get("round") is not None:
                self.round_idx = int(rec["round"])
            self._fold_eval(rec.get("eval") or rec)
        self.check()

    def _fold_eval(self, ev: dict) -> None:
        """Caller holds the lock. Track the loss the convergence rule
        watches (test loss when evaluated, else train loss)."""
        loss = None
        for key in ("test_loss", "train_loss", "loss"):
            if isinstance(ev.get(key), (int, float)):
                loss = float(ev[key])
                break
        if loss is None:
            return
        if not math.isfinite(loss):
            self._nonfinite_seen = True
        self._push(self._eval_losses, loss)

    def _push(self, buf: list[float], v: float) -> None:
        buf.append(v)
        del buf[:-self._max_win]

    # ---------------------------------------------------------------- rules
    def _eval_rule(self, rule: dict, snap: dict):
        """-> (firing, value, threshold) or None when not evaluable yet.
        ``snap`` is the ONE registry snapshot this check() took — the
        gauge-reading rules must not each re-copy every family on the
        per-round hot path. Caller holds the lock."""
        kind = rule["rule"]
        if kind == "convergence":
            n = int(rule.get("evals_rising", 3))
            if self._nonfinite_seen:
                return True, float("nan"), 0.0
            if len(self._eval_losses) < n + 1:
                return None
            tail = self._eval_losses[-(n + 1):]
            rising = all(b > a for a, b in zip(tail, tail[1:]))
            return rising, tail[-1], tail[0]
        if kind == "slowdown":
            recent = int(rule.get("recent", 5))
            window = int(rule.get("window", 20))
            factor = float(rule.get("factor", 2.0))
            times = self._round_times[-(window + recent):]
            base = times[:-recent]
            if len(base) < max(2, window // 4) or len(times) < recent + 2:
                return None
            p50_recent = _median(times[-recent:])
            thresh = factor * _median(base)
            return p50_recent > thresh, p50_recent, thresh
        if kind in ("quarantine", "shed"):
            window = int(rule.get("window", 5))
            buf = (self._quar_per_round if kind == "quarantine"
                   else self._shed_per_round)[-window:]
            if not buf:
                return None
            rate = sum(buf) / len(buf)
            thresh = float(rule.get("max_per_round", 2.0))
            return rate > thresh, rate, thresh
        if kind == "quorum":
            if self.expected_ranks is None or "fed_ranks_alive" not in snap:
                return None
            alive = float(sum(snap["fed_ranks_alive"].values()))
            # churn-aware denominator: ranks the trace scheduled offline
            # (chaos/churn.py) come out of BOTH sides — the server's alive
            # gauge already subtracts them, and here they shrink the
            # expected cohort — so a diurnal trough reads alive == thresh
            # (no fire) while one genuine crash inside the available set
            # still reads alive < thresh (fires once, edge-triggered).
            off = float(sum(
                snap.get("fed_ranks_scheduled_offline", {}).values()))
            expected = max(0.0, self.expected_ranks - off)
            thresh = float(rule.get("min_fraction", 1.0)) * expected
            return alive < thresh, alive, thresh
        if kind == "device_memory":
            in_use = snap.get("fed_device_bytes_in_use", {})
            limits = snap.get("fed_device_bytes_limit", {})
            fracs = [in_use[k] / limits[k] for k in in_use
                     if limits.get(k)]
            if not fracs:
                return None
            thresh = float(rule.get("max_fraction", 0.92))
            worst = max(fracs)
            return worst > thresh, worst, thresh
        if kind == "stall":
            age = self._clock() - self._progress_t
            thresh = float(rule.get("after_s", 300.0))
            return age > thresh, age, thresh
        if kind == "privacy_budget":
            if self._privacy_eps is None:
                return None  # not a DP run (no privacy block seen)
            thresh = float(rule.get("max_epsilon", 10.0))
            return self._privacy_eps > thresh, self._privacy_eps, thresh
        if kind == "restart_storm":
            fam = snap.get("fed_server_restarts_total")
            if not fam:
                return None  # WAL never armed / no restart yet
            restarts = float(sum(fam.values()))
            if restarts <= 0:
                return None  # family pre-registered but the run is clean
            thresh = float(rule.get("max_restarts", 3.0))
            return restarts > thresh, restarts, thresh
        if kind in ("fleet_quorum", "fleet_staleness"):
            # fleet-view rules: read the collector's rollup gauges
            # (obs/fleet.py). Not evaluable until a digest arrived — a
            # plane-off run's families are absent, an armed-but-quiet
            # boot reads zero digests; both stay silent.
            digests = sum(snap.get("fed_fleet_digests_total", {}).values())
            if not digests:
                return None
            if kind == "fleet_quorum":
                if self.expected_ranks is None:
                    return None
                # ramp-up gate: rows only exist once a rank's FIRST digest
                # lands, so during round 0 "reporting < expected" is just
                # boot order, not an outage. Round 1 anywhere in the fleet
                # means round 0 completed — every live rank had its chance
                # to report, and a missing row is now a real absence.
                rmax = snap.get("fed_fleet_round_max", {})
                if not rmax or max(rmax.values()) < 1:
                    return None
                reporting = float(sum(
                    snap.get("fed_fleet_ranks_reporting", {}).values()))
                # +1: rank 0's own row reports alongside the cohort.
                # Scheduled-offline ranks (churn trace) shrink the
                # expected cohort like the process-quorum rule above —
                # collector rows persist once ingested, so churn alone
                # can't drop `reporting`, but a rank held offline since
                # boot never produces a row and must not read as missing.
                off = float(sum(
                    snap.get("fed_ranks_scheduled_offline", {}).values()))
                thresh = (float(rule.get("min_fraction", 1.0))
                          * (max(0.0, self.expected_ranks - off) + 1))
                return reporting < thresh, reporting, thresh
            stale_fam = snap.get(
                "fed_fleet_digest_staleness_max_seconds", {})
            if not stale_fam:
                return None
            age = max(float(v) for v in stale_fam.values())
            thresh = float(rule.get("max_age_s", 120.0))
            return age > thresh, age, thresh
        return None

    def check(self) -> list[dict]:
        """Evaluate every rule, emit the edge transitions, return the
        transitions emitted this call. Safe from any thread (the round
        emit path and the background checker race by design)."""
        fired: list[dict] = []
        # staleness grows between digests: refresh the fleet rollup gauges
        # before snapshotting so the background checker sees real ages
        # (outside our lock — the collector has its own)
        fleet = getattr(self.telemetry, "fleet", None)
        if fleet is not None:
            fleet.refresh()
        with self._lock:
            snap = self.registry.snapshot()
            for i, rule in enumerate(self.rules):
                verdict = self._eval_rule(rule, snap)
                if verdict is None:
                    continue
                firing, value, thresh = verdict
                # edge-trigger state keyed per rule INSTANCE, not kind: a
                # two-tier table (same kind, warning + critical
                # thresholds) must not clobber one shared entry and emit
                # a fired/resolved pair on every check
                key = f"{rule['rule']}:{i}"
                active = key in self._active
                if firing and not active:
                    fired.append(self._emit(rule, key, "fired",
                                            value, thresh))
                elif not firing and active:
                    fired.append(self._emit(rule, key, "resolved",
                                            value, thresh))
        return fired

    def _emit(self, rule: dict, key: str, state: str, value, thresh) -> dict:
        """Caller holds the lock. One edge transition: ledger + event log
        + (on fired) the metrics family."""
        rec = {
            "rule": rule["rule"], "severity": rule["severity"],
            "state": state, "round": self.round_idx,
            "value": None if value is None or not math.isfinite(value)
            else round(float(value), 6),
            "threshold": round(float(thresh), 6),
        }
        if state == "fired":
            self._active[key] = rec
            self.registry.counter("fed_alerts_total", rule=rule["rule"],
                                  severity=rule["severity"]).inc()
        else:
            self._active.pop(key, None)
        if self.telemetry is not None:
            emitted = self.telemetry.events.emit("alert", **rec)
        else:
            emitted = dict(rec)
        self.alerts.append(emitted)
        log.log(logging.WARNING if state == "fired" else logging.INFO,
                "health: %s alert %s (value %s vs threshold %s, round %s)",
                rule["rule"], state, rec["value"], rec["threshold"],
                rec["round"])
        return emitted

    # ------------------------------------------------------------- healthz
    def snapshot(self) -> dict:
        """The /healthz verdict. Status is computed live (a scrape between
        checks still sees a stall), alerts are the currently-active set."""
        with self._lock:
            age = self._clock() - self._progress_t
            stall_after = next((float(r.get("after_s", 300.0))
                                for r in self.rules
                                if r["rule"] == "stall"), 300.0)
            stall_active = any(a["rule"] == "stall"
                               for a in self._active.values())
            if stall_active or age > stall_after:
                status = "stalled"
            elif self._active:
                status = "degraded"
            else:
                status = "ok"
            run_id = (self.telemetry.events.run_id
                      if self.telemetry is not None else None)
            return {
                "run": run_id,
                "status": status,
                "round": self.round_idx,
                "ranks_alive": self.registry.total("fed_ranks_alive"),
                "expected_ranks": self.expected_ranks,
                "last_progress_age_s": round(age, 3),
                "uptime_s": round(self._clock() - self._start_t, 3),
                "quarantine_total": self.registry.total(
                    "fed_updates_rejected_total"),
                "shed_total": self.registry.total("fed_async_shed_total"),
                # cumulative DP ε (null outside DP runs) — the live twin
                # of the round records' privacy block / fed_privacy_epsilon
                "privacy_epsilon": self._privacy_eps,
                # worst per-client ε (null until a per-client ledger run
                # reports) — live twin of fed_privacy_client_epsilon
                "eps_client_max": self._privacy_eps_client,
                # server crash recovery (docs/ROBUSTNESS.md §Server crash
                # recovery): the WAL's restart epoch (0 = never crashed)
                "restart_epoch": int(self.registry.total(
                    "fed_restart_epoch")),
                "alerts_fired_total": self.registry.total("fed_alerts_total"),
                "alerts": sorted(self._active.values(),
                                 key=lambda a: a["rule"]),
            }

    # ------------------------------------------------------------ lifecycle
    def start(self, interval_s: float = 5.0) -> "HealthMonitor":
        """Arm the background checker (idempotent). Needed only for
        between-round firing (stall detection on a dark fleet); the
        per-round hook alone covers everything that emits records."""
        if self._thread is not None:
            return self
        self._interval_s = float(interval_s)
        self._thread = threading.Thread(target=self._loop,
                                        name="obs-health", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — health must never kill a run
                log.exception("health check failed (continuing)")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
