"""Performance instrumentation — XLA compile accounting + pipeline metrics.

Two metric groups land in the process-wide ``metrics.REGISTRY``:

**Compile accounting** (fed by ``jax.monitoring`` listeners, installed once
per process by :func:`install`):

    fed_xla_compiles_total            backend compile passes — every
                                      ``/jax/core/compile/backend_compile_
                                      duration`` event. NOTE: on this jax a
                                      persistent-cache HIT still records
                                      one (the deserialize is timed under
                                      the same event), so a FRESH compile
                                      is defined by the cache counters
                                      below, not this one
    fed_xla_compile_seconds           (histogram) per-pass wall clock
    fed_xla_cache_requests_total      compile requests that consulted the
                                      persistent cache (0 = cache off)
    fed_xla_cache_hits_total          persistent compile-cache hits
    fed_xla_cache_misses_total        persistent compile-cache misses —
                                      the real "fresh compile" count when
                                      the cache is enabled

``engine.warmup()`` (algorithms/fedavg.py) diffs these around its AOT
compile pass, which is how the "repeat run performs zero fresh compiles"
contract is asserted rather than assumed: with the cache enabled,
fresh = cache misses; with it off (requests delta 0), fresh = compile
passes.

**Pipeline metrics** (fed by core/pipeline.py and the pipelined drivers):

    fed_h2d_seconds                   (histogram) host time issuing a round
                                      batch's host->device transfers —
                                      the device_put call, not the DMA
                                      itself (which is async on TPU)
    fed_prefetch_stall_seconds        (histogram) time the round driver
                                      waited for the prefetch thread — 0 on
                                      every round means the accelerator
                                      never saw a host-side pack stall
    fed_dispatch_depth                (gauge) rounds dispatched but not yet
                                      drained — the async-dispatch depth;
                                      the pipeline keeps this >= drain_lag

**Sharded-server-state metrics** (fed by the engines; docs/PERFORMANCE.md
§Partitioned server state):

    fed_agg_bytes_total{mode}         client-update bytes aggregated, by
                                      server-state mode (replicated |
                                      sharded)
    fed_server_state_bytes{placement} (gauge) PER-DEVICE bytes of the
                                      server plane (model + server opt
                                      state); sharded ~ replicated/ndev

All hooks are host-side and cheap (a dict lookup + float add via memoized
children, same pattern as obs/comm_instrument.py).
"""

from __future__ import annotations

import contextlib
import logging
import threading
from functools import lru_cache

from fedml_tpu.obs.metrics import REGISTRY

log = logging.getLogger("fedml_tpu.obs.perf")

_install_lock = threading.Lock()
_installed = False
_tls = threading.local()


@lru_cache(maxsize=8)
def _counter(name: str):
    # lru_cache indirection; every call site passes a fed_* literal
    return REGISTRY.counter(name)  # fedlint: disable=metric-discipline


@lru_cache(maxsize=8)
def _hist(name: str):
    # lru_cache indirection; every call site passes a fed_* literal
    return REGISTRY.histogram(name)  # fedlint: disable=metric-discipline


@lru_cache(maxsize=64)
def _span_hist(name: str):
    # the SAME family RoundTracer spans feed (obs/tracing.py) so the
    # prefetch thread's pack/transfer spans and the engine's host spans
    # read through one Prometheus name
    return REGISTRY.histogram("fed_span_seconds", span=name)


# ---------------------------------------------- per-variant attribution
# The compile observatory (docs/OBSERVABILITY.md §Compile observatory):
# jax.monitoring events fire ON THE COMPILING THREAD, so a thread-local
# variant tag set around a ``.compile()`` call attributes that thread's
# compile/cache events to the jit variant being built. Everything outside
# an :func:`attribute_compiles` scope (first-dispatch jit compiles, eval
# fns, ...) lands under the reserved ``variant="_other"`` child — which
# also gives the families a pre-registerable zero child.
#
#     fed_xla_variant_compile_seconds_total{variant}   backend compile wall
#     fed_xla_variant_compiles_total{variant}          compile passes
#     fed_xla_variant_cache_hits_total{variant}        persistent-cache hits
#     fed_xla_variant_cache_misses_total{variant}      fresh compiles
UNATTRIBUTED_VARIANT = "_other"


@lru_cache(maxsize=256)
def _variant_counter(name: str, variant: str):
    # lru_cache indirection; every call site passes a fed_* literal
    return REGISTRY.counter(name, variant=variant)  # fedlint: disable=metric-discipline


def _compile_variant() -> str:
    return getattr(_tls, "compile_variant", None) or UNATTRIBUTED_VARIANT


@contextlib.contextmanager
def attribute_compiles(variant: str):
    """Attribute this thread's jax.monitoring compile events to ``variant``
    for the duration of the scope (reentrant; inner scope wins)."""
    prev = getattr(_tls, "compile_variant", None)
    _tls.compile_variant = str(variant)
    try:
        yield
    finally:
        _tls.compile_variant = prev


def variant_compile_stats() -> dict:
    """{variant: {seconds, compiles, cache_hits, cache_misses}} from the
    live registry — the compile observatory's read side (warmup reports,
    report.py --compiles via the warmup event record, tests)."""
    out: dict[str, dict] = {}
    fams = {"fed_xla_variant_compile_seconds_total": "seconds",
            "fed_xla_variant_compiles_total": "compiles",
            "fed_xla_variant_cache_hits_total": "cache_hits",
            "fed_xla_variant_cache_misses_total": "cache_misses"}
    snap = REGISTRY.snapshot()
    for fam_name, key in fams.items():
        for label_s, value in (snap.get(fam_name) or {}).items():
            # snapshot() keys children as "k=v" strings (jsonable contract)
            if not label_s.startswith("variant="):
                continue
            variant = label_s.split("=", 1)[1]
            out.setdefault(variant, {})[key] = value
    return out


def ensure_compile_attr_families() -> None:
    """Pre-register the per-variant compile families at zero (under the
    reserved ``_other`` child) so a clean run's export carries them."""
    for fam in ("fed_xla_variant_compile_seconds_total",
                "fed_xla_variant_compiles_total",
                "fed_xla_variant_cache_hits_total",
                "fed_xla_variant_cache_misses_total"):
        _variant_counter(fam, UNATTRIBUTED_VARIANT)


# ------------------------------------------------------ compile accounting
def _on_event(name: str, **kw) -> None:
    if name == "/jax/compilation_cache/cache_hits":
        _counter("fed_xla_cache_hits_total").inc()
        _variant_counter("fed_xla_variant_cache_hits_total",
                         _compile_variant()).inc()
    elif name == "/jax/compilation_cache/cache_misses":
        _counter("fed_xla_cache_misses_total").inc()
        _variant_counter("fed_xla_variant_cache_misses_total",
                         _compile_variant()).inc()
    elif name == "/jax/compilation_cache/compile_requests_use_cache":
        _counter("fed_xla_cache_requests_total").inc()


def _on_duration(name: str, secs: float, **kw) -> None:
    if name.endswith("/backend_compile_duration"):
        _counter("fed_xla_compiles_total").inc()
        _hist("fed_xla_compile_seconds").observe(secs)
        variant = _compile_variant()
        _variant_counter("fed_xla_variant_compiles_total", variant).inc()
        _variant_counter("fed_xla_variant_compile_seconds_total",
                         variant).inc(secs)


def install() -> bool:
    """Register the jax.monitoring listeners feeding the compile counters.
    Idempotent (listeners cannot be individually unregistered, so exactly
    one pair is ever installed); returns False when jax.monitoring is
    unavailable (counters then stay at 0 — callers must treat a 0 as
    "uninstrumented", not "no compiles")."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        try:
            from jax import monitoring
        except Exception:  # noqa: BLE001 — instrumentation is best-effort
            log.debug("jax.monitoring unavailable; compile counters stay "
                      "at 0 (= uninstrumented)", exc_info=True)
            return False
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        _installed = True
        return True


def compiles_total() -> float:
    """XLA backend compile passes so far (callers diff around a phase;
    includes cache-hit deserializes — see module docstring)."""
    return REGISTRY.total("fed_xla_compiles_total")


def cache_hits_total() -> float:
    return REGISTRY.total("fed_xla_cache_hits_total")


def cache_misses_total() -> float:
    return REGISTRY.total("fed_xla_cache_misses_total")


def cache_requests_total() -> float:
    return REGISTRY.total("fed_xla_cache_requests_total")


# ------------------------------------------------------- pipeline metrics
def record_h2d(seconds: float) -> None:
    _hist("fed_h2d_seconds").observe(seconds)
    _span_hist("h2d").observe(seconds)


def record_prefetch_stall(seconds: float) -> None:
    _hist("fed_prefetch_stall_seconds").observe(seconds)


def set_dispatch_depth(n: int) -> None:
    REGISTRY.gauge("fed_dispatch_depth").set(n)


def record_span(name: str, seconds: float) -> None:
    """A host span observed off the engine's RoundTracer (the prefetch
    thread must not touch the tracer's per-round dict — see
    docs/PERFORMANCE.md §Tracing caveat)."""
    _span_hist(name).observe(seconds)


# ---------------------------------------------- fused-aggregation metrics
# docs/PERFORMANCE.md §Fused aggregation. Fed by the cross-process
# aggregator's flush paths:
#
#     fed_flush_seconds                 (histogram) one server aggregate
#                                       flush — ingest-side decode work is
#                                       per-arrival (overlapped), this is
#                                       the barrier-to-new-model latency
#     fed_agg_stack_bytes{mode}         (gauge) peak aggregation-staging
#                                       bytes of the last flush: stacked =
#                                       the full [K, ...] cohort stack,
#                                       fused = live pairwise partials
#                                       (O(log K) on the in-order path)
def record_flush_seconds(seconds: float) -> None:
    _hist("fed_flush_seconds").observe(seconds)


@lru_cache(maxsize=8)
def _agg_stack(mode: str):
    return REGISTRY.gauge("fed_agg_stack_bytes", mode=mode)


def set_agg_stack_bytes(mode: str, nbytes: float) -> None:
    """Peak aggregation-staging bytes of the last flush under ``mode``
    (fused | stacked) — the memory half of the fused-vs-stacked claim."""
    _agg_stack(mode).set(nbytes)


# --------------------------------------------- sharded-server-state metrics
# docs/PERFORMANCE.md §Partitioned server state. ``mode``/``placement`` is
# "replicated" or "sharded" so an A/B run exports both label sets side by
# side and the ~1/ndev per-device scaling is a metrics assertion, not a
# code comment.
@lru_cache(maxsize=8)
def _agg_bytes(mode: str):
    return REGISTRY.counter("fed_agg_bytes_total", mode=mode)


def record_agg_bytes(mode: str, nbytes: float) -> None:
    """Client-update bytes folded through aggregation this round (stacked
    cohort payload: K x model bytes) under the given server-state mode."""
    _agg_bytes(mode).inc(nbytes)


def set_server_state_bytes(placement: str, per_device_bytes: float) -> None:
    """PER-DEVICE resident bytes of the server plane (global model +
    server optimizer state). Sharded runs report ~1/ndev of the
    replicated figure — the acceptance metric for the partitioned
    server state (ISSUE 6)."""
    REGISTRY.gauge("fed_server_state_bytes",
                   placement=placement).set(per_device_bytes)


# ------------------------------------------------ buffered-async metrics
# docs/ROBUSTNESS.md §Asynchronous buffered rounds. Fed by the async server
# mode (distributed/fedavg/server_manager.py) and the virtual-clock
# simulator (core/async_buffer.py) identically:
#
#     fed_buffer_fill_seconds        (histogram) first arrival -> flush of
#                                    each buffered aggregate (virtual
#                                    seconds in the simulator)
#     fed_update_staleness           (histogram; prometheus quantile
#                                    labels) server version at aggregation
#                                    minus the version each folded update
#                                    trained against
#     fed_async_shed_total{reason}   arrivals the ingest path refused or
#                                    evicted: stale (admission bound),
#                                    overflow (backpressure shed-stalest),
#                                    nonfinite (quarantined at the door),
#                                    crash (simulator: dead-rank dispatch)
def record_buffer_fill(seconds: float) -> None:
    _hist("fed_buffer_fill_seconds").observe(seconds)


def record_update_staleness(staleness: float) -> None:
    _hist("fed_update_staleness").observe(float(staleness))


@lru_cache(maxsize=16)
def _async_shed(reason: str):
    return REGISTRY.counter("fed_async_shed_total", reason=reason)


def record_async_shed(reason: str) -> None:
    _async_shed(reason).inc()


def ensure_async_shed_families() -> None:
    """Pre-register every shed-reason child at zero so an async run's
    Prometheus export always carries the full family — a clean run must
    read as 'nothing shed', not 'metric missing'."""
    # mirrors core/async_buffer.SHED_REASONS (obs must not import core —
    # the dependency points the other way; drift is test-pinned)
    for reason in ("stale", "overflow", "nonfinite", "crash", "suspect",
                   "undecodable", "server_restart", "offline"):
        _async_shed(reason)


# --------------------------------------- secure aggregation + privacy
# docs/ROBUSTNESS.md §Secure aggregation / §Privacy ledger. Fed by the
# masked secure-aggregation tier (distributed/turboaggregate.py) and the
# DP aggregators (distributed/fedavg_robust.py, algorithms/
# fedavg_robust.py):
#
#     fed_secagg_rounds_total{outcome}    masked rounds by how they
#                                         decoded: full (whole cohort),
#                                         recovered (dropout + mask
#                                         recovery), shed (below the t+1
#                                         threshold / reveal lost —
#                                         round re-broadcast)
#     fed_secagg_dropped_slots_total      cohort slots whose masked
#                                         upload never arrived
#     fed_secagg_recovery_seconds         (histogram) reveal fan-out ->
#                                         last reveal reply per recovery
#     fed_privacy_epsilon                 cumulative DP ε at the ledger's
#                                         reporting δ — the budget the
#                                         privacy_budget health rule
#                                         alerts on
@lru_cache(maxsize=4)
def _secagg_rounds(outcome: str):
    return REGISTRY.counter("fed_secagg_rounds_total", outcome=outcome)


def record_secagg_round(outcome: str) -> None:
    _secagg_rounds(outcome).inc()


@lru_cache(maxsize=1)
def _secagg_dropped():
    return REGISTRY.counter("fed_secagg_dropped_slots_total")


def record_secagg_dropped(n: int) -> None:
    _secagg_dropped().inc(n)


def record_secagg_recovery_seconds(seconds: float) -> None:
    _hist("fed_secagg_recovery_seconds").observe(seconds)


def set_privacy_epsilon(eps: float) -> None:
    REGISTRY.gauge("fed_privacy_epsilon").set(float(eps))


#     fed_privacy_client_epsilon{stat}    per-client ε rollup at the
#                                         ledger's reporting δ: stat=max
#                                         (worst single client — the
#                                         never-under-report figure),
#                                         stat=mean, stat=count (clients
#                                         with any charge). Fed by
#                                         core/privacy.charge_and_record
#                                         when a ClientPrivacyLedger rides
#                                         the round.
@lru_cache(maxsize=4)
def _client_eps(stat: str):
    return REGISTRY.gauge("fed_privacy_client_epsilon", stat=stat)


def set_client_epsilon(eps_max: float, eps_mean: float, count: int) -> None:
    _client_eps("max").set(float(eps_max))
    _client_eps("mean").set(float(eps_mean))
    _client_eps("count").set(float(count))


def ensure_secagg_families() -> None:
    """Pre-register the secure-aggregation outcome children at zero so a
    masked run's Prometheus export always carries the full family."""
    for outcome in ("full", "recovered", "shed"):
        _secagg_rounds(outcome)
    _secagg_dropped()


def ensure_client_privacy_family() -> None:
    """Pre-register the per-client ε gauge children at zero so a DP
    masked run's export always carries the family (even before the first
    charge lands)."""
    for stat in ("max", "mean", "count"):
        _client_eps(stat)


# ---------------------------------------------------- server crash recovery
# docs/ROBUSTNESS.md §Server crash recovery:
#     fed_server_restarts_total          completed server restarts (the
#                                        restart epoch, synced at boot so
#                                        a restarted PROCESS's fresh
#                                        registry still reports the count)
#     fed_restart_epoch                  (gauge) the live restart epoch —
#                                        also on /healthz
#     fed_recovery_seconds               (histogram) checkpoint restore +
#                                        WAL replay wall time per boot
#     fed_ckpt_torn_total                torn checkpoint files skipped by
#                                        restore_latest's fallback
def sync_server_restarts(epoch: int) -> None:
    """Bring ``fed_server_restarts_total`` up to the WAL's restart epoch:
    a restarted process boots with a fresh registry, so the counter is
    advanced by the DELTA between the journaled epoch and whatever this
    process already counted (simulated in-process restarts inc once per
    boot; a twice-restarted real process lands at 2 in one step)."""
    delta = float(epoch) - REGISTRY.total("fed_server_restarts_total")
    if delta > 0:
        _counter("fed_server_restarts_total").inc(delta)
    REGISTRY.gauge("fed_restart_epoch").set(float(epoch))


def record_recovery_seconds(seconds: float) -> None:
    _hist("fed_recovery_seconds").observe(seconds)


def record_ckpt_torn() -> None:
    _counter("fed_ckpt_torn_total").inc()


def ensure_restart_families() -> None:
    """Pre-register the crash-recovery families at zero so any WAL-armed
    run's Prometheus export carries them (the restart-storm health rule
    and the ci.sh supervised-restart leg read the family, not its
    absence)."""
    _counter("fed_server_restarts_total")
    REGISTRY.gauge("fed_restart_epoch")
    _counter("fed_ckpt_torn_total")
