"""Fleet observability plane — in-band telemetry rollup to one pane of glass.

Every signal the obs stack exports is **per-rank**: ``--metrics_port``
binds PORT+rank per process, so "watch the fleet" meant scraping hundreds
of ports. This module folds the fleet back into rank 0 the same way
tracing does (obs/tracing.py): each client/edge rank periodically packs a
compact **digest** — round/wave progress, counter deltas for the
``fed_``/``comm_`` families, a p50/p95/p99 sketch of its local phase
timings, ε when known, host-RSS/device bytes — into a ``__telemetry``
blob piggybacked on the uplink frames it already sends. Stock peers
ignore the key; with the plane off no frame carries it (wire
byte-identical, test-enforced).

Rank 0's :class:`FleetCollector` merges digests into a rank-labeled fleet
registry served as ``/fleetz`` (obs/httpd.py — aggregated JSON: per-rank
liveness/round/staleness/bytes/ε, fleet rollups, status) and federates
O(1) rollup gauges into ``/metrics``:

    fed_fleet_digests_total{run,job}                 digests ingested
    fed_fleet_ranks_reporting{run,job}               distinct ranks seen
    fed_fleet_round_min{run,job} / _round_max        progress spread
    fed_fleet_digest_staleness_max_seconds{run,job}  oldest rank's silence
    fed_fleet_epsilon_max{run,job}                   worst reported ε

Per-rank detail deliberately stays in the ``/fleetz`` JSON, never as
per-rank metric children — the export must not grow O(world_size) lines
(the same cardinality rule the heartbeat gauges follow above their cap).
``run`` and the reserved ``job`` label namespace the rollups per run so
the multi-tenant scheduler inherits the plane instead of rebuilding it.

Enablement is in-band and zero-config on clients, exactly like
``__trace``: the server attaches a marker to its broadcast frames when
``Telemetry(fleet=True)`` armed a collector; a client that sees the
marker lazily creates a :class:`DigestEmitter` and starts piggybacking.
In a 2-tier topology the edge collects its block's digests and forwards
ONE folded blob on its partial frame, so root ingress stays O(edges).

Byte budget: a digest is a few hundred bytes of JSON header scalars.
Every attach is accounted under ``comm_bytes_total{codec=json,
direction=telemetry}`` — a direction ``directional_bytes()`` deliberately
excludes, so round records' uplink/downlink fields stay clean — and tests
assert the per-rank-per-round average stays ≤ ``DIGEST_BYTE_BUDGET``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque

from fedml_tpu.obs.comm_instrument import record_wire_bytes
from fedml_tpu.obs.flightrec import flight_record
from fedml_tpu.obs.memwatch import device_memory_stats, host_rss_bytes
from fedml_tpu.obs.metrics import REGISTRY, MetricsRegistry

# The in-band digest key — a JSON-header scalar on existing frames, like
# tracing's ``__trace``. MyMessage.MSG_ARG_KEY_TELEMETRY mirrors this
# constant (test-pinned equal): the protocol vocabulary lives in
# message_define, the obs layer owns the semantics.
TELEMETRY_KEY = "__telemetry"

# Documented per-rank per-round digest byte budget (docs/OBSERVABILITY.md
# §Fleet rollup): asserted from comm_bytes_total{direction=telemetry} in
# tests — a digest that outgrows this is a schema regression, not tuning.
DIGEST_BYTE_BUDGET = 1024

# counter vocabulary a digest's ``ctr`` block carries (deltas since the
# rank's previous digest) — the flat comm_counters() names
_CTR_KEYS = ("messages_sent", "bytes_sent", "messages_received",
             "bytes_received", "bytes_uplink", "bytes_downlink")

# a rank silent longer than this is marked stale in /fleetz (and drives
# the fleet_staleness health rule via the staleness-max rollup gauge)
DEFAULT_STALE_AFTER_S = 60.0


def _quantiles(samples) -> list[float]:
    """[p50, p95, p99] of a small sample list (exact-by-sort: the per-rank
    reservoir is bounded, so sorting is cheap)."""
    s = sorted(samples)
    n = len(s)
    out = []
    for q in (0.50, 0.95, 0.99):
        out.append(round(s[min(int(q * (n - 1) + 0.5), n - 1)], 6))
    return out


class DigestEmitter:
    """A client/edge rank's digest builder — created lazily the first time
    a broadcast carries the fleet marker (zero client-side config, the
    ``ClientSpanBuffer`` pattern). ``phase()`` times local phases into a
    bounded reservoir; ``digest()`` packs the blob one uplink carries."""

    def __init__(self, rank: int, run_id: str | None = None,
                 registry: MetricsRegistry | None = None,
                 max_phase_samples: int = 64, clock=time.perf_counter):
        self.rank = int(rank)
        self.run_id = run_id
        self.registry = registry or REGISTRY
        self._clock = clock
        self._phases: dict[str, deque] = {}
        self._max_samples = int(max_phase_samples)
        self._last_ctr: dict[str, float] = {}
        # duty-cycle accounting (docs/PERFORMANCE.md §Round economics):
        # phase() accumulates busy seconds; digest() divides by the
        # inter-digest interval — one float per digest, well inside the
        # byte budget
        self._busy = 0.0
        self._last_digest_t: float | None = None
        # scheduled availability (chaos/churn.py): adopted from a churn-
        # armed server's broadcast marker, echoed on each digest so the
        # fleet view's ``avail`` column reads straight off the rank rows.
        # None = no trace anywhere = the blob is byte-identical to pre-
        # churn digests (fedtop renders '-')
        self._avail: float | None = None
        self._lock = threading.Lock()

    def on_downlink(self, marker: dict) -> None:
        """Adopt the server's run identity from the broadcast marker (the
        digest must label itself with the SERVER's run id — a client
        process has no Telemetry bundle of its own)."""
        run = marker.get("run")
        if run:
            self.run_id = str(run)
        av = marker.get("avail")
        if av is not None:
            self._avail = float(av)

    # ---------------------------------------------------------- phase timing
    class _Phase:
        __slots__ = ("_em", "_name", "_t0")

        def __init__(self, em, name):
            self._em, self._name = em, name

        def __enter__(self):
            self._t0 = self._em._clock()
            return self

        def __exit__(self, *exc):
            dt = self._em._clock() - self._t0
            with self._em._lock:
                buf = self._em._phases.get(self._name)
                if buf is None:
                    buf = deque(maxlen=self._em._max_samples)
                    self._em._phases[self._name] = buf
                buf.append(dt)
                self._em._busy += dt
            return False

    def phase(self, name: str):
        """Context manager timing one local phase (unpack/local_fit/pack)
        into the quantile reservoir — independent of tracing, so the fleet
        view works on untraced runs."""
        return self._Phase(self, name)

    # --------------------------------------------------------------- the blob
    def digest(self, round_idx: int, wave=None, eps=None,
               gflops=None, avail=None) -> dict:
        """The compact uplink blob: round/wave progress, comm counter
        deltas since this rank's previous digest, per-phase [p50,p95,p99],
        duty cycle (phase-busy seconds over the inter-digest interval),
        GFLOPs/s when the caller knows one, ε when the caller knows one,
        and host/device memory. Also drops a ``digest`` record into the
        flight ring — in a crash timeline these are the 'what was this
        rank doing' breadcrumbs."""
        from fedml_tpu.obs.comm_instrument import comm_counters

        now = comm_counters(self.registry)
        t = self._clock()
        with self._lock:
            ctr = {k: int(now.get(k, 0.0) - self._last_ctr.get(k, 0.0))
                   for k in _CTR_KEYS}
            self._last_ctr = {k: now.get(k, 0.0) for k in _CTR_KEYS}
            spans = {name: _quantiles(buf)
                     for name, buf in self._phases.items() if buf}
            interval = (t - self._last_digest_t
                        if self._last_digest_t is not None else None)
            busy, self._busy = self._busy, 0.0
            self._last_digest_t = t
        duty = (min(busy / interval, 1.0)
                if interval and interval > 0 else None)
        blob: dict = {"rank": self.rank, "round": int(round_idx)}
        if duty is not None:
            blob["duty"] = round(duty, 3)
        if gflops is not None:
            blob["gf"] = round(float(gflops), 3)
        if self.run_id:
            blob["run"] = self.run_id
        if wave is not None:
            blob["wave"] = int(wave)
        if any(ctr.values()):
            blob["ctr"] = {k: v for k, v in ctr.items() if v}
        if spans:
            blob["spans"] = spans
        if eps is not None:
            blob["eps"] = round(float(eps), 6)
        if avail is None:
            avail = self._avail  # the marker-adopted value, if any
        if avail is not None:
            blob["avail"] = round(float(avail), 3)
        rss = host_rss_bytes()
        if rss is not None:
            blob["rss"] = int(rss)
        devs = device_memory_stats()
        if devs:
            blob["dev"] = int(sum(st["bytes_in_use"] for st in devs.values()))
        flight_record("digest", rank=self.rank, round=int(round_idx),
                      wave=None if wave is None else int(wave))
        return blob


def attach_digest(msg, blob: dict) -> None:
    """Attach a digest (or an edge's folded blob) to an outgoing frame and
    account its serialized size under ``comm_bytes_total{codec=json,
    direction=telemetry}`` — the measured half of the byte-budget claim.
    The direction is deliberately NOT uplink: ``directional_bytes()``
    ignores it, so round records' wire fields never include plane
    overhead."""
    record_wire_bytes("json", "telemetry",
                      len(json.dumps(blob, default=float).encode()))
    msg.add_params(TELEMETRY_KEY, blob)


class FleetCollector:
    """Rank 0's fleet registry: ingests digests (flat uploads and edges'
    folded blobs), serves the ``/fleetz`` JSON, and federates O(1) rollup
    gauges into the metrics registry. All methods are thread-safe (the
    comm dispatch loop ingests while scrapes snapshot)."""

    def __init__(self, run_id: str | None = None, job: str = "",
                 registry: MetricsRegistry | None = None,
                 expected_ranks: int | None = None,
                 stale_after_s: float = DEFAULT_STALE_AFTER_S,
                 clock=time.time, health=None):
        self.run_id = run_id
        self.job = str(job)
        self.registry = registry or REGISTRY
        self.expected_ranks = expected_ranks
        self.stale_after_s = float(stale_after_s)
        self.health = health
        self._clock = clock
        self._lock = threading.Lock()
        # rank -> {digest fields + seen_ts + cumulative byte tallies}
        self._ranks: dict[int, dict] = {}
        self._digests = 0
        # pre-register the rollup families at zero so a clean fleet run's
        # export reads 'nothing reported yet', not 'metric missing'
        for name in ("fed_fleet_digests_total",):
            self._counter(name)
        for name in ("fed_fleet_ranks_reporting", "fed_fleet_round_min",
                     "fed_fleet_round_max", "fed_fleet_epsilon_max",
                     "fed_fleet_digest_staleness_max_seconds"):
            self._gauge(name)

    def _labels(self) -> dict:
        # per-run namespacing + the reserved multi-tenant ``job`` label
        return {"run": self.run_id or "", "job": self.job}

    def _gauge(self, name: str):
        # families are literal at the pre-registration site above — this
        # helper only folds in the run/job labels
        return self.registry.gauge(name, **self._labels())  # fedlint: disable=metric-discipline

    def _counter(self, name: str):
        return self.registry.counter(name, **self._labels())  # fedlint: disable=metric-discipline

    # ----------------------------------------------------------------- marker
    def marker(self) -> dict:
        """The s2c enablement marker (attached next to the ``__trace``
        context when the plane is armed): tells every downstream rank to
        start digesting, and under which run identity."""
        m = {"run": self.run_id or ""}
        if self.job:
            m["job"] = self.job
        return m

    # ----------------------------------------------------------------- ingest
    def ingest(self, blob) -> None:
        """Fold one inbound ``__telemetry`` blob in. An edge's folded blob
        carries its block's digests under ``block`` — each child lands as
        its own rank row, then the edge's own digest, so the per-rank view
        is tier-agnostic while root ingress stays O(edges) frames."""
        if not isinstance(blob, dict):
            return
        for child in blob.get("block", ()):
            if isinstance(child, dict):
                self._ingest_one(child)
        self._ingest_one({k: v for k, v in blob.items() if k != "block"})
        self.refresh()

    def _ingest_one(self, d: dict) -> None:
        try:
            rank = int(d["rank"])
        except (KeyError, TypeError, ValueError):
            return  # a blob with no rank identity is unplaceable
        now = self._clock()
        with self._lock:
            row = self._ranks.setdefault(rank, {"bytes_uplink": 0,
                                                "bytes_downlink": 0})
            ctr = d.get("ctr") or {}
            row["bytes_uplink"] += int(ctr.get("bytes_uplink", 0))
            row["bytes_downlink"] += int(ctr.get("bytes_downlink", 0))
            for k in ("round", "wave", "eps", "rss", "dev", "spans", "run",
                      "duty", "gf", "avail"):
                if d.get(k) is not None:
                    row[k] = d[k]
            row["seen_ts"] = now
            self._digests += 1
        self._counter("fed_fleet_digests_total").inc()
        flight_record("fleet_ingest", rank=rank, round=d.get("round"))

    def note_avail(self, offline: set, world_size: int) -> None:
        """Server-side availability stamp (chaos/churn.py): a scheduled-
        offline rank sends no digests while away, so its row would keep
        the last avail it echoed — rank 0, which owns the trace, overrides
        the column on EXISTING rows (never creates one: a phantom row
        would inflate ``fed_fleet_ranks_reporting`` and skew the
        fleet-quorum denominator)."""
        with self._lock:
            for rank, row in self._ranks.items():
                if 0 < rank < world_size:
                    row["avail"] = 0.0 if rank in offline else 1.0

    def note_server(self, round_idx: int, eps=None, duty=None,
                    gflops=None) -> None:
        """Rank 0's own row — fed from ``Telemetry.emit_round`` (every
        engine that emits round records updates the server line, including
        its ε and round-economics figures, without a wire hop)."""
        now = self._clock()
        with self._lock:
            row = self._ranks.setdefault(0, {"bytes_uplink": 0,
                                             "bytes_downlink": 0})
            row["round"] = int(round_idx)
            if eps is not None:
                row["eps"] = round(float(eps), 6)
            if duty is not None:
                row["duty"] = round(float(duty), 3)
            if gflops is not None:
                row["gf"] = round(float(gflops), 3)
            rss = host_rss_bytes()
            if rss is not None:
                row["rss"] = int(rss)
            row["seen_ts"] = now
        self.refresh()

    # ---------------------------------------------------------------- rollups
    def refresh(self) -> None:
        """Recompute the O(1) rollup gauges (staleness grows between
        digests, so exporters refresh right before reading — the
        ``refresh_liveness`` discipline)."""
        now = self._clock()
        with self._lock:
            rows = list(self._ranks.values())
        if not rows:
            return
        rounds = [int(r["round"]) for r in rows if r.get("round") is not None]
        epss = [float(r["eps"]) for r in rows if r.get("eps") is not None]
        stale = [max(0.0, now - r["seen_ts"]) for r in rows
                 if r.get("seen_ts")]
        self._gauge("fed_fleet_ranks_reporting").set(len(rows))
        if rounds:
            self._gauge("fed_fleet_round_min").set(min(rounds))
            self._gauge("fed_fleet_round_max").set(max(rounds))
        if epss:
            self._gauge("fed_fleet_epsilon_max").set(max(epss))
        if stale:
            self._gauge("fed_fleet_digest_staleness_max_seconds").set(
                round(max(stale), 3))

    # ----------------------------------------------------------------- fleetz
    def snapshot(self) -> dict:
        """The ``/fleetz`` body: per-rank rows (liveness, round/wave,
        cumulative wire bytes, ε, memory, phase sketch), fleet rollups,
        and the overall status — ``waiting`` (no digest yet) | ``ok`` |
        ``degraded`` (some rank stale past ``stale_after_s``)."""
        self.refresh()
        now = self._clock()
        with self._lock:
            ranks = {r: dict(row) for r, row in self._ranks.items()}
            digests = self._digests
        out_ranks: dict[str, dict] = {}
        any_stale = False
        for r in sorted(ranks):
            row = ranks[r]
            staleness = (round(max(0.0, now - row["seen_ts"]), 3)
                         if row.get("seen_ts") else None)
            stale = staleness is not None and staleness > self.stale_after_s
            any_stale = any_stale or stale
            out_ranks[str(r)] = {
                "round": row.get("round"),
                "wave": row.get("wave"),
                "staleness_s": staleness,
                "bytes_uplink": row.get("bytes_uplink", 0),
                "bytes_downlink": row.get("bytes_downlink", 0),
                "eps": row.get("eps"),
                "rss_bytes": row.get("rss"),
                "device_bytes": row.get("dev"),
                "spans": row.get("spans"),
                "duty": row.get("duty"),
                "gflops": row.get("gf"),
                "avail": row.get("avail"),
                "status": "stale" if stale else "ok",
            }
        rounds = [v["round"] for v in out_ranks.values()
                  if v["round"] is not None]
        status = ("waiting" if not out_ranks
                  else "degraded" if any_stale else "ok")
        alerts = []
        if self.health is not None:
            try:
                alerts = self.health.snapshot().get("alerts", [])
            except Exception:  # noqa: BLE001 — /fleetz must answer anyway
                logging.getLogger("fedml_tpu.obs.fleet").warning(
                    "health snapshot failed during /fleetz render",
                    exc_info=True)
                alerts = []
        return {
            "run": self.run_id,
            "job": self.job or None,
            "status": status,
            "expected_ranks": self.expected_ranks,
            "ranks_reporting": len(out_ranks),
            "digests_total": digests,
            "ranks": out_ranks,
            "rollup": {
                "round_min": min(rounds) if rounds else None,
                "round_max": max(rounds) if rounds else None,
                "staleness_max_s": max(
                    (v["staleness_s"] for v in out_ranks.values()
                     if v["staleness_s"] is not None), default=None),
                "eps_max": max((v["eps"] for v in out_ranks.values()
                                if v["eps"] is not None), default=None),
                "bytes_uplink": sum(v["bytes_uplink"]
                                    for v in out_ranks.values()),
                "bytes_downlink": sum(v["bytes_downlink"]
                                      for v in out_ranks.values()),
            },
            "alerts": alerts,
        }
