"""Provenance header for BENCH blobs — who/what/where a number came from.

Every bench writer (bench.py, bench_scaling.py, scripts/chaos_soak.py)
stamps the same ``provenance`` block on its JSON blob so scripts/runstore.py
can index and compare figures across commits:

    {"provenance": {"git_sha": "79fc809", "jax": "0.4.x", "jaxlib": "...",
                    "device_kind": "TPU v4", "device_count": 4,
                    "dataset_source": "synthetic", "date": "2026-08-07"}}

Everything is best-effort and stdlib-only: git absent -> sha None; jax not
imported -> device fields None (this module NEVER imports jax itself — the
bench parent process must stay jax-free); the wall-clock ``date`` is
PASSED IN by the caller (scripts layer), never read here, keeping the
module importable from clock-disciplined code. Historical blobs without
the block are tolerated everywhere (runstore indexes them headerless).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys

log = logging.getLogger("fedml_tpu.obs.provenance")


def git_sha(cwd: str | None = None) -> str | None:
    """The short HEAD sha, or None outside a repo / without git."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=cwd or os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            capture_output=True, text=True, timeout=10)
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except Exception:  # noqa: BLE001 — provenance is best-effort
        log.debug("git sha lookup failed; provenance carries sha=None",
                  exc_info=True)
        return None


def _dist_version(name: str) -> str | None:
    try:
        from importlib import metadata
        return metadata.version(name)
    except Exception:  # noqa: BLE001
        log.debug("version lookup for %s failed", name, exc_info=True)
        return None


def _device_info() -> tuple[str | None, int | None]:
    """(device_kind, device_count) from an ALREADY-IMPORTED jax, else
    (None, None). Reading sys.modules instead of importing keeps the
    bench parent (which must never import jax) safe to stamp from."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return None, None
    try:
        devs = jax_mod.devices()
        return devs[0].device_kind, len(devs)
    except Exception:  # noqa: BLE001
        log.debug("device enumeration failed; provenance device fields "
                  "are None", exc_info=True)
        return None, None


def provenance(date: str | None = None,
               dataset_source: str | None = None) -> dict:
    """The common provenance block. ``date`` is the caller's wall-clock
    date string (scripts stamp it; nothing here reads a clock)."""
    kind, count = _device_info()
    return {
        "git_sha": git_sha(),
        "jax": _dist_version("jax"),
        "jaxlib": _dist_version("jaxlib"),
        "device_kind": kind,
        "device_count": count,
        "dataset_source": dataset_source,
        "date": date,
    }


def stamp(blob: dict, date: str | None = None,
          dataset_source: str | None = None) -> dict:
    """Attach the provenance block to a BENCH blob in place (and return
    it). Never overwrites an existing block — a relay (bench.py's parent
    re-emitting a child's line) must not clobber the measuring process's
    stamp."""
    if "provenance" not in blob:
        blob["provenance"] = provenance(date=date,
                                        dataset_source=dataset_source)
    return blob
